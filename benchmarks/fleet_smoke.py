"""Fleet observability smoke (ISSUE 17) — the CI gate for the
federated metrics plane.

End-to-end over REAL HTTP on whatever device is available (CI: CPU):
three live engine-server replicas, one :class:`FleetAggregator`
scraping them, and every fleet claim checked against ground truth:

1. **exact federation** — after an asymmetric load phase (plus a
   round-robin ``endpoints=`` spray from the shared load core), a
   quiesced ``POST /scrape`` must leave the fleet's merged
   ``pio_http_requests_total`` children EQUAL to the per-replica sums
   and the merged latency-histogram bucket vector EQUAL to the
   per-bucket sum of the replicas' vectors — the merged p99 is then by
   construction the pooled-population quantile. A latency fault armed
   only while replica 2 is driven skews its distribution, so the smoke
   also shows the number the merge refuses to produce:
   average-of-per-replica-p99s visibly disagrees with the pooled p99;
2. **cross-replica trace lookup** — a fault-injected slow query sent
   with a fixed ``traceparent`` to replica 2 ONLY must come back
   through the fleet's ``GET /trace.json?id=`` naming that replica;
3. **fleet SLO** — with background load on, the fleet-scoped latency
   spec (committed ``slo/specs/ci.json``, evaluated over the MERGED
   registry) must go ok → breach under an injected ``serving.dispatch``
   latency fault → back to ok after the fault clears;
4. **hot keys** — the fleet-wide Space-Saving union must surface the
   Zipf-hottest entity and conserve the per-replica demand totals.

Prints one JSON line; exits non-zero when any check fails.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _loadgen import (  # noqa: E402
    expect_json_field,
    json_post_sender,
    run_load,
    sample_entities,
)

SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "slo", "specs", "ci.json")

#: the fleet-scoped latency spec the injected fault must breach
LATENCY_SPEC = "queries-p99-latency"
N_USERS = 48
ROUTE = "/queries.json"
#: a fixed W3C trace id (32 hex) the smoke plants on replica 2 only
TRACE_ID = "abadcafe" * 4
SPAN_ID = "deadbeefcafef00d"


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read())


def _post(port: int, path: str, body: bytes = b"",
          headers: Optional[dict] = None) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def _drive(n: int, seed: int, endpoints=None, port: int = 0,
           rate=None, threads: int = 4, stop=None) -> None:
    """Closed-loop (or open-loop at ``rate``) Zipf-skewed query load
    against one replica or round-robin across ``endpoints``."""
    rng = np.random.default_rng(seed)
    users = sample_entities(rng, N_USERS, n, zipf=1.5)
    sender = json_post_sender(
        port, ROUTE,
        body_fn=lambda k: json.dumps({"user": f"u{users[k]}",
                                      "num": 5}).encode(),
        check=expect_json_field("itemScores"), endpoints=endpoints)
    stats, _wall = run_load(sender, n, threads, rate_qps=rate,
                            stop=stop)
    if stats.errors and stop is None:
        raise RuntimeError(
            f"{len(stats.errors)} failed queries under smoke load "
            f"(first: {stats.errors[0]})")


def _route_children(export: dict, family: str) -> dict:
    """label-items → child dict, for the children scoped to the
    query route (the fleet's own HTTP traffic lives on other routes,
    so this comparison is exact by construction)."""
    out = {}
    for child in (export.get(family) or {}).get("children") or []:
        labels = dict(child.get("labels") or {})
        if labels.get("route") == ROUTE:
            out[tuple(sorted(labels.items()))] = child
    return out


def _dense(buckets) -> list:
    """Cumulative ``[le, cum]`` export pairs → per-bucket counts."""
    counts, prev = [], 0
    for _le, cum in buckets:
        counts.append(int(cum) - prev)
        prev = int(cum)
    return counts


def _fleet_spec(fleet_port: int, name: str) -> dict:
    for sp in (_get(fleet_port, "/slo.json").get("specs") or []):
        if sp["name"] == name:
            return sp
    raise RuntimeError(f"spec {name!r} not evaluated by the fleet")


def _await_fleet_state(fleet_port: int, name: str, want,
                       timeout_s: float) -> dict:
    deadline = time.monotonic() + timeout_s
    sp = _fleet_spec(fleet_port, name)
    while time.monotonic() < deadline:
        sp = _fleet_spec(fleet_port, name)
        if sp["state"] in want:
            return sp
        time.sleep(0.25)
    return sp


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    from predictionio_tpu import faults
    from predictionio_tpu.fleet import FleetConfig, create_fleet_server
    from predictionio_tpu.obs import StreamingHistogram
    from predictionio_tpu.server.engineserver import ServerConfig
    from serving_bench import _boot_server, _wait_warm, synth_model

    model = synth_model(N_USERS, 64, 8, device=False)
    replicas = [_boot_server(model, ServerConfig(
        batching=True, max_batch=16, batch_window_ms=2.0,
        queue_deadline_ms=10_000.0)) for _ in range(3)]
    ports = [srv.port for _qs, srv in replicas]
    names = [f"127.0.0.1:{p}" for p in ports]

    agg, fleet_srv = create_fleet_server(
        FleetConfig(replicas=names, scrape_interval_sec=0.25,
                    slo_specs=SPEC_PATH, slo_interval_sec=0.2,
                    hot_keys_k=64),
        host="127.0.0.1", port=0)
    fleet_srv.start_background()
    fport = fleet_srv.port

    checks: dict = {}
    out: dict = {"bench": "fleet_smoke", "replicas": names,
                 "specs": SPEC_PATH}
    stop_evt = threading.Event()
    bg: Optional[threading.Thread] = None
    try:
        for i, p in enumerate(ports):
            _wait_warm(p, f"fleet_smoke replica {i}")
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if _get(fport, "/fleet.json")["replicasUp"] == 3:
                break
            time.sleep(0.25)
        checks["replicas_up"] = \
            _get(fport, "/fleet.json")["replicasUp"] == 3

        # phase 1 — asymmetric load: replica 2's share runs under a
        # 60 ms dispatch fault (below the SLO threshold; its only job
        # is to make per-replica latency distributions DIFFER), then a
        # round-robin endpoints= spray from the shared load core
        _drive(100, seed=3, port=ports[0])
        _drive(40, seed=5, port=ports[1])
        faults.inject("serving.dispatch", "latency", delay_ms=60.0)
        try:
            _drive(12, seed=7, port=ports[2], threads=2)
        finally:
            faults.clear("serving.dispatch")
        _drive(60, seed=9, endpoints=names, threads=6)

        _post(fport, "/scrape")
        rep_exports = [_get(p, "/metrics.json") for p in ports]
        fleet_export = _get(fport, "/metrics.json")

        # exact counter federation: every /queries.json child of the
        # merged family equals the sum of the replicas' children
        fam = "pio_http_requests_total"
        sums: dict = {}
        for ex in rep_exports:
            for key, child in _route_children(ex, fam).items():
                sums[key] = sums.get(key, 0.0) + float(child["value"])
        fleet_vals = {k: float(c["value"]) for k, c in
                      _route_children(fleet_export, fam).items()}
        out["query_requests"] = {"fleet": sum(fleet_vals.values()),
                                 "replicas": sum(sums.values())}
        checks["counters_sum_exact"] = bool(sums) and fleet_vals == sums

        # exact histogram federation: merged per-bucket counts equal
        # the per-bucket sum of the replicas' vectors, so the merged
        # p99 IS the pooled-population p99 — and visibly NOT the
        # average of per-replica p99s (replica 2's faulted share)
        fam = "pio_http_request_duration_seconds"
        hsums: dict = {}
        p99s = []
        for ex in rep_exports:
            for key, child in _route_children(ex, fam).items():
                dense = _dense(child["buckets"])
                prev = hsums.get(key)
                hsums[key] = ([a + b for a, b in zip(prev, dense)]
                              if prev else dense)
                p99s.append(StreamingHistogram.from_buckets(
                    child["buckets"]).quantile(0.99))
        fleet_hists = _route_children(fleet_export, fam)
        checks["histogram_buckets_exact"] = bool(hsums) and all(
            _dense(fleet_hists[key]["buckets"]) == dense
            for key, dense in hsums.items()
            if key in fleet_hists) and set(hsums) == set(fleet_hists)
        pooled_p99 = max(
            StreamingHistogram.from_buckets(c["buckets"]).quantile(0.99)
            for c in fleet_hists.values())
        avg_p99 = sum(p99s) / len(p99s) if p99s else 0.0
        out["pooled_p99_ms"] = round(pooled_p99 * 1e3, 2)
        out["avg_of_replica_p99s_ms"] = round(avg_p99 * 1e3, 2)
        checks["pooled_p99_not_avg_of_p99s"] = \
            pooled_p99 > 1.2 * avg_p99

        # scrape again with zero new traffic: the merge is
        # delta-based, so a quiescent cycle must change nothing
        _post(fport, "/scrape")
        fleet_vals2 = {
            k: float(c["value"]) for k, c in _route_children(
                _get(fport, "/metrics.json"),
                "pio_http_requests_total").items()}
        checks["quiescent_scrape_idempotent"] = fleet_vals2 == fleet_vals

        # hot keys: the Zipf-hottest entity tops the fleet union and
        # the union conserves total demand across replicas
        hot = _get(fport, "/hotkeys.json")
        top_keys = [k["key"] for k in hot["fleet"][:3]]
        out["hot_keys_top3"] = top_keys
        checks["hot_key_found"] = "u0" in top_keys
        fleet_total = _get(fport, "/fleet.json")["hotKeys"]["total"]
        rep_total = sum(
            (_get(p, "/status.json").get("hotKeys") or {}
             ).get("total") or 0.0 for p in ports)
        out["hot_key_totals"] = {"fleet": fleet_total,
                                 "replicas": rep_total}
        checks["hot_key_demand_conserved"] = fleet_total == rep_total

        # phase 2 — cross-replica trace lookup: ONE fault-injected
        # slow query rides a fixed traceparent into replica 2 only;
        # the fleet fan-out must find it there by id
        faults.inject("serving.dispatch", "latency", delay_ms=300.0)
        try:
            _post(ports[2], ROUTE,
                  body=json.dumps({"user": "u1", "num": 5}).encode(),
                  headers={
                      "Content-Type": "application/json",
                      "traceparent": f"00-{TRACE_ID}-{SPAN_ID}-01"})
        finally:
            faults.clear("serving.dispatch")
        try:
            found = _get(fport, f"/trace.json?id={TRACE_ID}")
        except urllib.error.HTTPError as e:
            found = {"error": e.code}
        out["trace_found_on"] = found.get("replica")
        checks["trace_found_on_right_replica"] = \
            found.get("replica") == names[2]

        # phase 3 — fleet SLO green → lit → green over the MERGED
        # registry, with steady background load on all replicas
        bg = threading.Thread(
            target=lambda: _drive(1 << 20, seed=13, endpoints=names,
                                  threads=6, rate=25.0, stop=stop_evt),
            daemon=True, name="fleet-bg-load")
        bg.start()
        green0 = _await_fleet_state(fport, LATENCY_SPEC,
                                    ("ok",), 20.0)
        checks["slo_green_before"] = green0["state"] == "ok"

        faults.inject("serving.dispatch", "latency", delay_ms=400.0)
        t_inject = time.monotonic()
        lit = _await_fleet_state(fport, LATENCY_SPEC,
                                 ("breach",), 30.0)
        out["breach"] = {k: lit.get(k) for k in
                         ("state", "burnFast", "burnSlow",
                          "violations")}
        out["detect_sec"] = round(time.monotonic() - t_inject, 1)
        checks["slo_breach_detected"] = lit["state"] == "breach"
        metrics_text = urllib.request.urlopen(
            f"http://127.0.0.1:{fport}/metrics", timeout=30
        ).read().decode()
        checks["fleet_slo_series_exported"] = any(
            ln.startswith("pio_slo_burn_rate")
            and f'slo="{LATENCY_SPEC}"' in ln
            for ln in metrics_text.splitlines())

        faults.clear("serving.dispatch")
        recovered = _await_fleet_state(fport, LATENCY_SPEC,
                                       ("ok", "idle"), 60.0)
        out["recovery_state"] = recovered["state"]
        checks["slo_recovered"] = recovered["state"] in ("ok", "idle")
    finally:
        faults.clear()
        stop_evt.set()
        if bg is not None:
            bg.join(timeout=60)
        agg.stop()
        fleet_srv.shutdown()
        for qs, srv in replicas:
            qs.stop_slo()
            srv.shutdown()
    ok = all(bool(v) for v in checks.values())
    print(json.dumps({"ok": ok, **out, **checks}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
