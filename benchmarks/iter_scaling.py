"""Whole-iteration scaling law on the attached device.

The round-4 on-chip gram profile showed every profiled stage (gather,
gram, solve) at multi-TF/s while the full training iteration achieves
0.83 TF/s — so the bound is something the per-stage view misses. This
probe fits the iteration's scaling empirically: time the fused trainer
across an (nnz, rank) grid with the packing amortized.

- time ∝ nnz, flat in rank      → HBM/gather-bound (bytes per entry)
- time ∝ nnz·rank²              → compute-bound (the gram/solve math)
- large nnz-independent offset  → dispatch/fusion overhead

Each cell reports seconds/iteration (best of GRID_REPS, hard-synced)
and the padded-FLOP-model TF/s, one JSON line per cell.

Usage: python benchmarks/iter_scaling.py   (from the repo root)
Env:   GRID_NNZ="2000000,20000000" GRID_RANKS="32,64" GRID_REPS=3
       GRID_ITERS=5
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    nnzs = [int(x) for x in os.environ.get(
        "GRID_NNZ", "2000000,6000000,20000000").split(",")]
    ranks = [int(x) for x in os.environ.get(
        "GRID_RANKS", "32,64,128").split(",")]
    reps = int(os.environ.get("GRID_REPS", "3"))
    iters = int(os.environ.get("GRID_ITERS", "5"))

    import jax

    from predictionio_tpu.models.als import (
        ALSParams,
        RatingsCOO,
        als_flops_per_iter,
        pack_ratings,
        train_als,
    )

    dev = jax.devices()[0].device_kind

    def hard_sync(x):
        np.asarray(jax.device_get(x[0, :1]))

    for nnz in nnzs:
        n_users = max(int(138_000 * nnz / 20_000_000), 64)
        n_items = max(int(27_000 * nnz / 20_000_000), 64)
        items = (np.random.default_rng(1).zipf(1.3, size=nnz)
                 % n_items).astype(np.int32)
        users = np.random.default_rng(0).integers(
            0, n_users, nnz).astype(np.int32)
        ratings = RatingsCOO(users, items,
                             np.ones(nnz, np.float32), n_users, n_items)
        for rank in ranks:
            params = ALSParams(rank=rank, num_iterations=iters,
                               implicit_prefs=True, alpha=40.0,
                               reg=0.01, seed=3)
            try:
                packed = pack_ratings(ratings, params)
                U, V = train_als(ratings, params, packed=packed)  # warm
                hard_sync(V)
                best = float("inf")
                for _ in range(reps):
                    t0 = time.monotonic()
                    U, V = train_als(ratings, params, packed=packed)
                    hard_sync(V)
                    best = min(best, time.monotonic() - t0)
                fl = als_flops_per_iter(packed[0], packed[1], params)
                print(json.dumps({
                    "nnz": nnz, "rank": rank,
                    "s_per_iter": round(best / iters, 4),
                    "ratings_per_s_per_iter": round(
                        nnz * iters / best, 1),
                    "model_tflops": round(fl * iters / best / 1e12, 3),
                    "device": dev,
                }), flush=True)
            except Exception as e:  # noqa: BLE001 — next cell
                print(json.dumps({
                    "nnz": nnz, "rank": rank,
                    "error": str(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
