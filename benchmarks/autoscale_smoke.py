"""Autoscaling smoke (ISSUE 18) — the CI gate for the router +
replica-lifecycle + autoscaler control loop.

End-to-end over REAL HTTP on whatever device is available (CI: CPU):
three live engine-server replicas behind the entity-affinity
:class:`QueryRouter`, a :class:`FleetAggregator` scraping them against
a committed-knee capacity model, and the :class:`Autoscaler` closing
the loop through a :class:`ReplicaLifecycle`:

1. **10x open-loop ramp** — offered load steps from the baseline to
   10x; fleet headroom crosses the policy floor and the autoscaler
   must scale OUT (decision logged, new replica warm-gated into the
   ring) while every committed SLO stays green;
2. **chaos drill** — mid-ramp one original replica is transport-killed
   at the PR 11 fault point (``router.forward``) and then actually
   shut down: the router must shed to survivors with ZERO failed
   in-deadline queries, and the autoscaler's heal pass must replace
   the corpse (a ``replace`` decision, outside the cooldown);
3. **scale-in without flap** — after the ramp returns to baseline,
   sustained headroom over the ceiling must bring the fleet back to
   ``min_replicas`` and then HOLD: no scale-out/scale-in oscillation
   for several cooldown windows.

The full decision log is written to ``autoscale_decisions.json``
(override with ``AUTOSCALE_DECISIONS_PATH``) and uploaded as a CI
artifact. Prints one JSON line; exits non-zero when any check fails.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from typing import Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _loadgen import (  # noqa: E402
    expect_json_field,
    json_post_sender,
    run_load,
    sample_entities,
)

SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "slo", "specs", "ci.json")

N_USERS = 48
ROUTE = "/queries.json"
BASE_QPS = 4.0
RAMP_QPS = 40.0            # the 10x step
#: committed single-replica knee: at 3 replicas the ramp sits well
#: past floor (1 - 40/36 < 0.15) and the baseline well past ceiling
#: (1 - 4/36 > 0.60), so both directions trigger deterministically
KNEE_QPS = 12.0
RAMP_SEC = 14.0
KILL_AFTER_SEC = 4.0
SETTLE_SEC = 14.0          # scale-in + flap watch after the ramp


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read())


def _load(port: int, rate: float, seconds: float, seed: int,
          stats_sink: list, threads: int = 6) -> threading.Thread:
    """Open-loop Zipf-skewed query load through the ROUTER for a fixed
    duration; the LoadStats lands in ``stats_sink`` for the
    zero-failures check."""
    rng = np.random.default_rng(seed)
    n = int(rate * seconds)
    users = sample_entities(rng, N_USERS, n, zipf=1.5)
    sender = json_post_sender(
        port, ROUTE,
        body_fn=lambda k: json.dumps({"user": f"u{users[k]}",
                                      "num": 5}).encode(),
        check=expect_json_field("itemScores"))

    def run() -> None:
        stats, wall = run_load(sender, n, threads, rate_qps=rate)
        stats_sink.append((stats, wall))

    t = threading.Thread(target=run, daemon=True,
                         name=f"autoscale-load-{seed}")
    t.start()
    return t


def _await(predicate, timeout_s: float, poll: float = 0.25) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return bool(predicate())


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    from predictionio_tpu import faults
    from predictionio_tpu.fleet import FleetConfig, create_fleet_server
    from predictionio_tpu.router import (
        Autoscaler,
        AutoscalePolicy,
        QueryRouter,
        ReplicaLifecycle,
        RouterConfig,
        create_router_server,
    )
    from predictionio_tpu.server.engineserver import ServerConfig
    from serving_bench import _boot_server, _wait_warm, synth_model

    model = synth_model(N_USERS, 64, 8, device=False)
    cfg = ServerConfig(batching=True, max_batch=16,
                       batch_window_ms=2.0, queue_deadline_ms=10_000.0)

    def _safe_stop(qs, srv):
        def stop() -> None:
            try:
                qs.stop_slo()
                srv.shutdown()
            except Exception:   # double-stop after the chaos kill
                pass
        return stop

    replicas = [_boot_server(model, cfg) for _ in range(3)]
    names = [f"127.0.0.1:{srv.port}" for _qs, srv in replicas]

    capacity_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        ".autoscale_capacity.json")
    with open(capacity_path, "w", encoding="utf-8") as f:
        json.dump({"configs": {"router": {"knee_qps": KNEE_QPS}}}, f)

    agg, fleet_srv = create_fleet_server(
        FleetConfig(replicas=names, scrape_interval_sec=0.25,
                    stale_after_sec=1.5, slo_specs=SPEC_PATH,
                    slo_interval_sec=0.2, capacity_path=capacity_path),
        host="127.0.0.1", port=0)
    fleet_srv.start_background()

    router = QueryRouter(RouterConfig(retries=1, eject_failures=2,
                                      eject_sec=2.0),
                         registry=agg.registry)
    router_srv = create_router_server(router, host="127.0.0.1", port=0)
    router_srv.start_background()
    agg.attach_router(router)
    router.set_health(lambda name: {"up": True, "down": False}.get(
        agg.replica_health(name)))

    def spawn():
        qs, srv = _boot_server(model, cfg)
        replicas.append((qs, srv))
        return f"127.0.0.1:{srv.port}", _safe_stop(qs, srv)

    lifecycle = ReplicaLifecycle(spawn, router=router, aggregator=agg,
                                 registry=agg.registry,
                                 drain_deadline_sec=15.0)
    policy = AutoscalePolicy(min_replicas=3, max_replicas=5,
                             headroom_floor=0.15, headroom_ceiling=0.60,
                             scale_in_sustain_sec=2.0, cooldown_sec=2.0,
                             interval_sec=0.5)
    autoscaler = Autoscaler(agg, lifecycle, policy,
                            registry=agg.registry)
    agg.attach_autoscaler(autoscaler)

    checks: dict = {}
    out: dict = {"bench": "autoscale_smoke", "replicas": names,
                 "kneeQps": KNEE_QPS, "baseQps": BASE_QPS,
                 "rampQps": RAMP_QPS}
    stats_sink: list = []
    corpse = names[1]
    try:
        for i, (_qs, srv) in enumerate(replicas):
            _wait_warm(srv.port, f"autoscale_smoke replica {i}")
        for name, (qs, srv) in zip(names, replicas):
            lifecycle.adopt(name, stop_fn=_safe_stop(qs, srv))
        checks["replicas_adopted"] = lifecycle.count("ready") == 3
        autoscaler.start()
        checks["replicas_up"] = _await(
            lambda: _get(fleet_srv.port, "/fleet.json")[
                "replicasUp"] == 3, 15.0)

        # baseline: min_replicas pins the fleet — sustained high
        # headroom at 3 replicas must NOT scale below the floor count
        base_t = _load(router_srv.port, BASE_QPS, 5.0, seed=3,
                       stats_sink=stats_sink)
        base_t.join(timeout=60)
        checks["baseline_holds_min"] = lifecycle.live_count() == 3

        # the 10x ramp, with a chaos kill mid-ramp: transport fault
        # at the router's PR 11 point + a REAL shutdown of the corpse
        ramp_t = _load(router_srv.port, RAMP_QPS, RAMP_SEC, seed=5,
                       stats_sink=stats_sink)

        def _kill() -> None:
            faults.inject("router.forward", "error",
                          match={"replica": corpse})
            qs, srv = replicas[1]
            _safe_stop(qs, srv)()

        killer = threading.Timer(KILL_AFTER_SEC, _kill)
        killer.start()
        scaled_out = _await(
            lambda: lifecycle.live_count() > 3, RAMP_SEC + 10.0)
        ramp_t.join(timeout=120)
        checks["scale_out_observed"] = scaled_out
        replaced = _await(
            lambda: any(d["action"] == "replace" for d in
                        autoscaler.status()["decisions"]), 20.0)
        checks["corpse_replaced"] = replaced
        faults.clear("router.forward")

        # SLOs green through the whole ramp+kill (merged registry);
        # specs whose traffic lane this smoke doesn't drive (stream
        # freshness) sit in insufficient_data, which is not a breach
        specs = _get(fleet_srv.port, "/slo.json").get("specs") or []
        out["slo_states"] = {sp["name"]: sp["state"] for sp in specs}
        checks["slo_green_through_ramp"] = bool(specs) and all(
            sp["state"] in ("ok", "idle", "insufficient_data")
            for sp in specs)
        checks["query_slos_ok"] = all(
            sp["state"] == "ok" for sp in specs
            if sp["name"].startswith("queries-"))

        # back to baseline: sustained headroom over the ceiling must
        # scale the fleet back to min_replicas...
        settle_t = _load(router_srv.port, BASE_QPS, SETTLE_SEC,
                         seed=7, stats_sink=stats_sink)
        scaled_in = _await(
            lambda: (lifecycle.count("ready") == 3
                     and lifecycle.live_count() == 3),
            SETTLE_SEC + 30.0)
        checks["scale_in_to_min"] = scaled_in
        decisions = autoscaler.status()["decisions"]
        checks["scale_in_logged"] = any(
            d["action"] == "scale_in" for d in decisions)
        seq_at_min = max((d["seq"] for d in decisions), default=0)

        # ...and then HOLD: several cooldown windows with no policy
        # action in either direction is the no-flap proof
        time.sleep(3 * (policy.cooldown_sec
                        + policy.scale_in_sustain_sec) / 2)
        settle_t.join(timeout=60)
        late = [d for d in autoscaler.status()["decisions"]
                if d["seq"] > seq_at_min
                and d["action"] in ("scale_out", "scale_in")]
        out["late_actions"] = late
        checks["no_flap_after_settle"] = not late
        checks["fleet_back_to_min"] = lifecycle.live_count() == 3
        checks["corpse_not_a_member"] = corpse not in router.members()

        # zero failed in-deadline queries across baseline + ramp +
        # kill + settle — the router shed every one to a survivor
        errors = [e for stats, _w in stats_sink
                  for e in stats.errors]
        sent = sum(len(stats.lat) + len(stats.shed)
                   for stats, _w in stats_sink)
        out["queries_ok"] = sent
        out["first_errors"] = errors[:3]
        checks["zero_failed_queries"] = sent > 0 and not errors

        # decisions visible on /fleet.json, series on /metrics
        fleet = _get(fleet_srv.port, "/fleet.json")
        auto = fleet.get("autoscale") or {}
        checks["decisions_on_fleet_json"] = bool(auto.get("decisions"))
        # the removed log is INTENTIONAL exits only (`ptpu fleet
        # status` exit-code source): scale-in victims belong there,
        # the chaos corpse must NOT — it died, it wasn't removed
        removed = auto.get("removed") or []
        out["removed"] = removed
        checks["scale_in_exits_tracked"] = (
            len(removed) >= 1 and corpse not in removed)
        metrics_text = urllib.request.urlopen(
            f"http://127.0.0.1:{fleet_srv.port}/metrics",
            timeout=30).read().decode()
        for fam in ("pio_router_requests_total",
                    "pio_autoscale_decisions_total",
                    "pio_autoscale_replicas"):
            checks[f"{fam}_exported"] = any(
                ln.startswith(fam) for ln in metrics_text.splitlines())

        out["decision_count"] = len(auto.get("decisions") or [])
        out["routerStatus"] = {
            "members": len(router.members()),
            "retries": sum(
                c.value for _i, c in (agg.registry.get(
                    "pio_router_retries_total").children()))}
    finally:
        faults.clear()
        autoscaler.stop()
        log_path = os.environ.get("AUTOSCALE_DECISIONS_PATH",
                                  "autoscale_decisions.json")
        try:
            with open(log_path, "w", encoding="utf-8") as f:
                json.dump({"policy": autoscaler.status()["policy"],
                           "decisions": autoscaler.status()["decisions"],
                           "removed": autoscaler.status()["removed"]},
                          f, indent=2)
        except OSError:
            pass
        lifecycle.close(stop_replicas=True)
        router_srv.shutdown()
        agg.stop()
        fleet_srv.shutdown()
        try:
            os.remove(capacity_path)
        except OSError:
            pass
    ok = all(bool(v) for v in checks.values())
    print(json.dumps({"ok": ok, **out, **checks}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
