"""Eval-sweep benchmark: 8-point hyperparameter grid, k-fold, measuring
the effect of (a) per-fold pack reuse (``pack_ratings_cached``) and
(b) the thread-parallel grid walk (``MetricEvaluator(parallelism=)``,
the reference's ``.par`` map — ``MetricEvaluator.scala:224-231``).

Usage: python benchmarks/eval_sweep_bench.py [n_events]
Prints one JSON line with sequential-cold vs parallel-warm sweep times.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main() -> None:
    n_events = int(sys.argv[1]) if len(sys.argv) > 1 else 60_000
    import jax

    # ptpu: allow[config-drift] — standalone bench entrypoint pinning
    # the platform before any framework import, same job as
    # force_cpu_if_requested (no library code runs before this line)
    jax.config.update("jax_platforms", "cpu")

    from predictionio_tpu.controller.context import Context
    from predictionio_tpu.controller.evaluation import (
        Evaluation,
        MetricEvaluator,
    )
    from predictionio_tpu.controller.params import EngineParams
    from predictionio_tpu.models import als as als_mod
    from predictionio_tpu.models.als import ALSParams
    from predictionio_tpu.templates.recommendation import (
        DataSourceParams,
        PrecisionAtK,
        recommendation_engine,
    )

    rng = np.random.default_rng(0)
    n_users, n_items = 800, 300
    events = [
        {"user": f"u{rng.integers(n_users)}", "item": f"i{rng.integers(n_items)}",
         "rating": float(rng.integers(1, 6))}
        for _ in range(n_events)
    ]

    # feed events through an in-memory store so the DataSource reads the
    # real path
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.registry import Storage

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
                           "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
                           "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
                           "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM"})
    from predictionio_tpu.data.storage.base import App

    app_id = storage.apps().insert(App(id=0, name="sweepapp"))
    storage.events().init(app_id)
    storage.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=e["user"],
               target_entity_type="item", target_entity_id=e["item"],
               properties={"rating": e["rating"]}) for e in events], app_id)

    engine = recommendation_engine()
    grid = [
        EngineParams(
            datasource=("", DataSourceParams(app_name="sweepapp", eval_k=3)),
            algorithms=[("als", ALSParams(rank=r, num_iterations=5,
                                          reg=reg, seed=3))])
        for r in (4, 8) for reg in (0.01, 0.05, 0.1, 0.3)
    ]
    ctx = Context(app_name="sweepapp", _storage=storage)
    ev = Evaluation(engine=engine, metric=PrecisionAtK(k=5))

    def run(parallelism):
        als_mod._pack_cache.clear()
        t0 = time.monotonic()
        res = MetricEvaluator(ev, parallelism=parallelism).evaluate(ctx, grid)
        return time.monotonic() - t0, res

    run(parallelism=1)  # warm jit caches so the comparison is fair

    # round-1 equivalent: every retrain re-packs (no pack_ratings_cached)
    import predictionio_tpu.templates.recommendation as rec_mod
    real_cached = als_mod.pack_ratings_cached
    als_mod_pack = als_mod.pack_ratings
    try:
        rec_mod.pack_ratings_cached = lambda r, p, mesh=None: \
            als_mod_pack(r, p, mesh)
        t_nopack, _ = run(parallelism=1)
    finally:
        rec_mod.pack_ratings_cached = real_cached

    t_seq, r_seq = run(parallelism=1)
    t_par, r_par = run(parallelism=4)
    assert [s.score for s in r_seq.scores] == [s.score for s in r_par.scores]

    print(json.dumps({
        "grid_points": len(grid),
        "folds": 3,
        "n_events": n_events,
        "sweep_round1_nopack_s": round(t_nopack, 2),
        "sweep_sequential_s": round(t_seq, 2),
        "sweep_parallel4_s": round(t_par, 2),
        "speedup_vs_round1": round(t_nopack / t_par, 2),
        "best_index": r_par.best_index,
    }))


if __name__ == "__main__":
    main()
