"""North-star run: MovieLens-20M (documented surrogate) through the REAL
CLI — app new → import → train → eval (VERDICT r3 task 6).

The reference's end-to-end is ``pio build && pio train && pio eval`` on
the scala-parallel-recommendation template over ml-20m
(``BASELINE.json`` north_star; ``Evaluation.scala:32-89`` metric grid).
This script drives the same flow through ``predictionio_tpu.cli``
subprocesses: the surrogate events land in a segmentfs store via
``ptpu import``, ``ptpu train`` runs the recommendation engine at the
requested scale on the attached device, and ``ptpu eval`` runs the
shipped Precision@K grid + NDCG@10 over k folds.

Every stage is wall-clocked; the result is ONE JSON document for
BASELINE.md's real-data-vs-synthetic table.

Usage:
  python benchmarks/northstar_ml20m.py --scale 1.0 \
      [--npz /tmp/ml20m_full.npz] [--rank 64] [--eval-scale 0.1]

``--eval-scale`` bounds the k-fold grid's cost: the eval app holds a
seeded subsample of the ratings (1.0 = the full set). The train stage
always runs at --scale.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def cli_env(home: Path, events_dir: Path, platform: str) -> dict:
    env = dict(os.environ)
    # APPEND to PYTHONPATH, never replace: the device tunnel's PJRT
    # plugin rides the ambient PYTHONPATH (a sitecustomize hook);
    # overwriting it makes every CLI subprocess lose the chip with
    # "Unable to initialize backend" (measured: first full-scale run
    # died at the train stage exactly this way)
    pp = env.get("PYTHONPATH", "")
    env.update({
        "PIO_HOME": str(home),
        "PYTHONPATH": f"{REPO}:{pp}" if pp else str(REPO),
        # segmentfs event data (the TPU-pod backend, native codec);
        # sqlite metadata rides the default under PIO_HOME
        "PIO_STORAGE_SOURCES_SEG_TYPE": "segmentfs",
        "PIO_STORAGE_SOURCES_SEG_PATH": str(events_dir),
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SEG",
    })
    if platform:
        env["JAX_PLATFORMS"] = platform
    return env


def run_cli(env: dict, *args, timeout=7200, tolerate_failure=False):
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.cli", *args],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=str(REPO))
    dt = time.monotonic() - t0
    if proc.returncode != 0 and not tolerate_failure:
        sys.stderr.write(f"FAILED {args}: rc={proc.returncode}\n"
                         f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}\n")
        raise SystemExit(1)
    return proc, dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--npz", default="")
    ap.add_argument("--rank", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--eval-scale", type=float, default=0.1,
                    help="fraction of ratings in the eval app's store")
    ap.add_argument("--eval-k", type=int, default=2)
    ap.add_argument("--platform", default="",
                    help="JAX_PLATFORMS override ('' = leave as-is)")
    ap.add_argument("--workdir", default="")
    args = ap.parse_args()

    from benchmarks.ml20m_surrogate import (
        generate,
        verify_marginals,
        write_events_jsonl,
    )

    result: dict = {"metric": "northstar_ml20m",
                    "scale": args.scale, "rank": args.rank}

    # --- dataset ---
    t0 = time.monotonic()
    if args.npz and os.path.exists(args.npz):
        d = np.load(args.npz)
        users, items, stars, ts = (d["users"], d["items"], d["stars"],
                                   d["ts"])
        n_users, n_movies = int(d["n_users"]), int(d["n_movies"])
    else:
        users, items, stars, ts, n_users, n_movies = generate(args.scale)
    result["marginals"] = verify_marginals(users, items, stars, ts,
                                           n_users, n_movies, args.scale)
    result["gen_s"] = round(time.monotonic() - t0, 1)

    workdir = Path(args.workdir) if args.workdir else \
        Path(tempfile.mkdtemp(prefix="northstar_"))
    workdir.mkdir(parents=True, exist_ok=True)
    partial_path = workdir / "result_partial.json"
    if partial_path.exists():
        try:
            prev = json.loads(partial_path.read_text())
            # completed stage numbers survive a late-stage crash+retry
            for k2, v2 in prev.items():
                result.setdefault(k2, v2)
        except (OSError, json.JSONDecodeError):
            pass

    def checkpoint_result():
        partial_path.write_text(json.dumps(result))
    home = workdir / "pio_home"
    home.mkdir(exist_ok=True)
    events_dir = workdir / "segmentfs"
    env = cli_env(home, events_dir, args.platform)

    # --- JSONL + import through the real CLI (resumable: a completed
    # import leaves a marker so a retried run — e.g. after a transient
    # tunnel failure in a later stage — skips the slow stages) ---
    marker = workdir / ".import_done"
    if marker.exists():
        # keep the measured value restored from result_partial.json if
        # the import ran in an earlier attempt of this workdir
        result.setdefault("import_s", "skipped (marker present)")
    else:
        t0 = time.monotonic()
        jsonl = workdir / "events.jsonl"
        if not jsonl.exists():
            write_events_jsonl(jsonl, users, items, stars, ts)
            result["jsonl_write_s"] = round(time.monotonic() - t0, 1)

        # resume-after-mid-import-crash: the app may exist with a
        # partial chunk prefix committed — recreate it empty rather
        # than dying on "already exists" or double-importing
        run_cli(env, "app", "new", "ml20m", tolerate_failure=True)
        run_cli(env, "app", "data-delete", "ml20m", "-f",
                tolerate_failure=True)
        proc, dt = run_cli(env, "import", "--app", "ml20m",
                           "--input", str(jsonl))
        result["import_s"] = round(dt, 1)
        # `ptpu import` now also builds the columnar sidecar (the
        # one-time encode the first train used to pay); report the
        # split so the ingest rate stays comparable across rounds
        warm_s = 0.0
        for line in proc.stdout.splitlines():
            if line.startswith("Columnar sidecar ready ("):
                warm_s = float(line.split("(")[1].split("s")[0])
        result["import_columnar_warm_s"] = round(warm_s, 1)
        result["import_ev_per_s"] = round(
            len(users) / max(dt - warm_s, 1e-9), 1)
        marker.write_text("ok")
        checkpoint_result()

    # --- train via ptpu train (the full-data flagship run) ---
    variant = {
        "id": "northstar", "version": "1",
        "engineFactory":
            "predictionio_tpu.templates.recommendation:"
            "recommendation_engine",
        "datasource": {"params": {"app_name": "ml20m"}},
        "algorithms": [{
            "name": "als",
            "params": {"rank": args.rank, "num_iterations": args.iters,
                       "reg": 0.01, "seed": 3, "implicit_prefs": True,
                       "alpha": 40.0}}],
    }
    ej = workdir / "engine.json"
    ej.write_text(json.dumps(variant))
    def parse_stages(stdout: str):
        for line in stdout.splitlines():
            if line.startswith("Train stages: "):
                try:
                    return json.loads(line[len("Train stages: "):])
                except json.JSONDecodeError:
                    return None
        return None

    def needs_third(res):
        t1, t2 = res.get("train_s"), res.get("train2_s")
        return (t1 is not None and t2 is not None
                and "train3_s" not in res
                and abs(t1 - t2) / max(min(t1, t2), 1e-9) > 0.2)

    if ("train_s" in result and "train2_s" in result
            and os.environ.get("NORTHSTAR_RETRAIN") != "1"):
        # both completed train runs survive the retry — but the
        # third-sample-on-wide-spread guarantee still applies to a
        # resumed artifact
        if needs_third(result):
            proc, dt = run_cli(env, "train", "--engine-json", str(ej))
            result["train3_s"] = round(dt, 1)
            result["train3_stages"] = parse_stages(proc.stdout)
    else:
        # a forced retrain replaces ALL samples: a stale third sample
        # from a previous attempt must not suppress (or pollute) the
        # fresh spread check
        for stale in ("train3_s", "train3_stages"):
            result.pop(stale, None)
        # TWO consecutive trains: the flagship number plus its
        # run-to-run stability (VERDICT r4 weak #1: 2x variance with
        # no evidence of where the host seconds went — the per-stage
        # breakdown the CLI now prints lands in this artifact)
        proc, dt = run_cli(env, "train", "--engine-json", str(ej))
        result["train_s"] = round(dt, 1)
        result["train_stages"] = parse_stages(proc.stdout)
        result["train_ratings_per_s_per_iter"] = round(
            len(users) * args.iters / dt, 1)
        checkpoint_result()
        proc, dt = run_cli(env, "train", "--engine-json", str(ej))
        result["train2_s"] = round(dt, 1)
        result["train2_stages"] = parse_stages(proc.stdout)
        # the device tunnel's dispatch/load time varies run to run
        # (host stages are stable — see the per-stage breakdowns); a
        # >20% spread gets a third sample so the artifact shows the
        # distribution, not two draws
        if needs_third(result):
            proc, dt = run_cli(env, "train", "--engine-json", str(ej))
            result["train3_s"] = round(dt, 1)
            result["train3_stages"] = parse_stages(proc.stdout)
    checkpoint_result()

    # --- deploy + query: the serving moment through the real CLI
    # (CreateServer.scala:484-633 role) — load the trained model from
    # the blob store, bind (device placement happens here), serve real
    # HTTP queries with the micro-batcher on ---
    if os.environ.get("NORTHSTAR_DEPLOY", "1") == "1" \
            and "deploy_query_p50_ms" not in result:
        import http.client
        import socket
        import urllib.request

        # a resumed run must not carry a stale failure next to fresh
        # numbers (same rule as the train3 purge above)
        result.pop("deploy_query_error", None)
        with socket.socket() as probe:  # a free port, not a guess
            probe.bind(("127.0.0.1", 0))
            port = probe.getsockname()[1]
        dp = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli", "deploy",
             "--engine-json", str(ej), "--ip", "127.0.0.1",
             "--port", str(port), "--batching"],
            env=env, cwd=str(REPO), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        # drain stderr continuously (an unread PIPE blocks the server
        # once the buffer fills) but keep the tail for diagnostics
        import threading

        err_tail: list = [""]

        def _drain():
            for line in dp.stderr:
                err_tail[0] = (err_tail[0] + line)[-300:]

        threading.Thread(target=_drain, daemon=True).start()
        try:
            t0 = time.monotonic()
            warm = False
            while time.monotonic() - t0 < 600:
                if dp.poll() is not None:  # died at startup: fail fast
                    result["deploy_query_error"] = \
                        f"deploy exited rc={dp.returncode}: " \
                        f"{err_tail[0]}"
                    break
                try:
                    st = json.loads(urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/status.json",
                        timeout=5).read())
                    if st.get("servingWarm"):
                        warm = True
                        break
                except Exception:  # noqa: BLE001 — still starting
                    pass
                time.sleep(1.0)
            result["deploy_warm_s"] = round(time.monotonic() - t0, 1)
            if warm:
                lats = []
                bad = None
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=60)
                    rng_q = np.random.default_rng(3)
                    for q in rng_q.integers(1, n_users, 60):
                        body = json.dumps({"user": str(int(q)),
                                           "num": 10}).encode()
                        t1 = time.monotonic()
                        conn.request("POST", "/queries.json",
                                     body=body,
                                     headers={"Content-Type":
                                              "application/json"})
                        out = json.loads(conn.getresponse().read())
                        if "itemScores" not in out:
                            bad = f"bad response: {str(out)[:200]}"
                            break
                        lats.append(time.monotonic() - t1)
                    conn.close()
                except Exception as qe:  # noqa: BLE001 — the deploy
                    # probe must not abort the remaining stages (eval
                    # still has to run; every other stage tolerates
                    # failure)
                    bad = f"{type(qe).__name__}: {str(qe)[:200]}"
                if bad is not None:
                    result["deploy_query_error"] = bad
                elif lats:
                    arr = np.asarray(lats[10:] or lats) * 1e3
                    result["deploy_query_p50_ms"] = round(
                        float(np.percentile(arr, 50)), 2)
                    result["deploy_query_p99_ms"] = round(
                        float(np.percentile(arr, 99)), 2)
            elif "deploy_query_error" not in result:
                result["deploy_query_error"] = "warmup timeout"
        finally:
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/stop", method="POST"),
                    timeout=10).read()
            except Exception:  # noqa: BLE001 — kill below regardless
                pass
            try:
                dp.wait(timeout=15)
            except subprocess.TimeoutExpired:
                dp.kill()
        checkpoint_result()

    # --- eval: shipped Precision@K grid + NDCG@10, k-fold, through
    # ptpu eval on a seeded subsample app (documented --eval-scale) ---
    if args.eval_scale > 0:
        rng = np.random.default_rng(17)
        if args.eval_scale < 1.0:
            sel = rng.random(len(users)) < args.eval_scale
        else:
            sel = np.ones(len(users), bool)
        # tolerate "already exists" on a resumed run; marker prevents
        # duplicate event import (and a pointless JSONL rewrite) on
        # retry
        run_cli(env, "app", "new", "ml20m_eval", tolerate_failure=True)
        emarker = workdir / ".eval_import_done"
        if not emarker.exists():
            ejsonl = workdir / "events_eval.jsonl"
            write_events_jsonl(ejsonl, users[sel], items[sel],
                               stars[sel], ts[sel])
            run_cli(env, "app", "data-delete", "ml20m_eval", "-f",
                    tolerate_failure=True)
            run_cli(env, "import", "--app", "ml20m_eval",
                    "--input", str(ejsonl))
            emarker.write_text("ok")
        evmod = workdir / "northstar_eval.py"
        evmod.write_text(f"""
from predictionio_tpu.controller import Evaluation
from predictionio_tpu.controller.evaluation import EngineParamsGenerator
from predictionio_tpu.controller.params import EngineParams
from predictionio_tpu.models.als import ALSParams
from predictionio_tpu.templates.recommendation import (
    DataSourceParams, NDCGAtK, PrecisionAtK, recommendation_engine)

APP = "ml20m_eval"
evaluation = Evaluation(
    engine=recommendation_engine(),
    metric=NDCGAtK(k=10, rating_threshold=2.0),
    other_metrics=[PrecisionAtK(k=1, rating_threshold=4.0),
                   PrecisionAtK(k=3, rating_threshold=4.0),
                   PrecisionAtK(k=10, rating_threshold=4.0)],
)


class _Gen(EngineParamsGenerator):
    engine_params_list = [
        EngineParams(
            datasource=("", DataSourceParams(app_name=APP,
                                             eval_k={args.eval_k})),
            algorithms=[("als", ALSParams(
                rank={args.rank}, num_iterations={args.iters}, reg=reg,
                seed=3, implicit_prefs=True, alpha=40.0))])
        for reg in (0.01, 0.1)
    ]


engine_params_generator = _Gen()
""")
        env_eval = dict(env,
                        PYTHONPATH=f"{workdir}:{env['PYTHONPATH']}")
        proc, dt = run_cli(env_eval, "eval",
                           "northstar_eval:evaluation",
                           "northstar_eval:engine_params_generator")
        result["eval_s"] = round(dt, 1)
        result["eval_scale"] = args.eval_scale
        checkpoint_result()
        out_lines = proc.stdout.strip().splitlines()
        result["eval_one_liner"] = out_lines[-1] if out_lines else \
            "(eval produced no stdout)"

    # device probe in a CHILD with the same env the CLI stages ran
    # under (reports what they actually used), bounded: backend init
    # through a hung tunnel blocks indefinitely and must not eat a
    # finished multi-hour run
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            env=env, capture_output=True, text=True, timeout=180)
        result["device"] = probe.stdout.strip().splitlines()[-1] \
            if probe.returncode == 0 and probe.stdout.strip() \
            else "unknown"
    except Exception:  # noqa: BLE001 — timeout/crash: don't die
        result["device"] = "unknown"
    result["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())
    print(json.dumps(result))


if __name__ == "__main__":
    main()
