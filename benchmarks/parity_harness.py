"""Quality-parity harness (VERDICT r1 task 4).

Generates a DETERMINISTIC synthetic MovieLens-like dataset (seeded zipf
item popularity, planted low-rank structure, 1–5 star ratings), then runs
the reference's evaluation contract — the Precision@K grid
(k ∈ {1,3,10} × thresholds {0,2,4}, reference ``tests/pio_tests/engines/
recommendation-engine/src/main/scala/Evaluation.scala:32-89``) plus
NDCG@10 — over k-fold splits for TWO trainers:

- the framework path: ``train_als`` (float32, padded/bucketed layouts,
  Pallas solver on TPU), and
- an EXACT oracle: dense float64 per-row normal-equation ALS with
  identical semantics (same init draw, same ALS-WR λ·n regularization,
  same jitter, same update order).

Both factor sets are scored by the same top-K protocol; the harness
asserts every metric's |Δ| ≤ 1% (relative, floored at 0.005 absolute for
near-zero metrics) and prints one JSON document for PARITY.md.

Usage: python benchmarks/parity_harness.py [--scale S]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "")

import numpy as np


def make_dataset(n_users=3000, n_items=800, nnz=120_000, rank=8, seed=7):
    """Seeded MovieLens-shaped ratings with planted low-rank structure."""
    rng = np.random.default_rng(seed)
    Ut = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    Vt = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    items = (np.random.default_rng(seed + 1).zipf(1.25, size=nnz)
             % n_items).astype(np.int32)
    users = rng.integers(0, n_users, nnz).astype(np.int32)
    # dedupe (user, item) pairs — one rating per pair, like MovieLens
    key = users.astype(np.int64) * n_items + items
    _, first = np.unique(key, return_index=True)
    users, items = users[first], items[first]
    raw = (Ut[users] * Vt[items]).sum(axis=1)
    raw = 3.0 + 1.6 * raw / max(np.abs(raw).std(), 1e-9)
    stars = np.clip(np.round(raw + 0.2 * rng.normal(size=raw.shape)),
                    1, 5).astype(np.float32)
    return users, items, stars, n_users, n_items


def oracle_als(users, items, vals, n_users, n_items, rank, iters, reg,
               seed, jitter=1e-6, implicit=False, alpha=1.0):
    """Float64 exact ALS: the dense-CPU oracle with the framework's
    exact semantics (init draw from the same jax PRNG, ALS-WR λ·n
    scaling, Hu-Koren-Volinsky confidence in implicit mode, per-row
    normal equations solved by LAPACK)."""
    import jax

    ku, ki = jax.random.split(jax.random.key(seed))
    U = np.asarray(jax.random.normal(ku, (n_users, rank)),
                   dtype=np.float64) / np.sqrt(rank)
    V = np.asarray(jax.random.normal(ki, (n_items, rank)),
                   dtype=np.float64) / np.sqrt(rank)

    def csr(rows, cols, v, n_rows):
        order = np.argsort(rows, kind="stable")
        r, c, w = rows[order], cols[order], v[order]
        indptr = np.zeros(n_rows + 1, dtype=np.int64)
        np.cumsum(np.bincount(r, minlength=n_rows), out=indptr[1:])
        return indptr, c, w.astype(np.float64)

    u_ptr, u_cols, u_vals = csr(users, items, vals, n_users)
    i_ptr, i_cols, i_vals = csr(items, users, vals, n_items)
    eye = np.eye(rank)

    def half(fixed, indptr, cols, w, n_rows):
        G = fixed.T @ fixed if implicit else None
        out = np.zeros((n_rows, rank))
        for i in range(n_rows):
            s, e = indptr[i], indptr[i + 1]
            n = e - s
            F = fixed[cols[s:e]]
            if implicit:
                c1 = alpha * w[s:e]
                A = G + (F * c1[:, None]).T @ F \
                    + (reg * max(n, 1) + jitter) * eye
                b = (c1 + 1.0) @ F if n else np.zeros(rank)
            else:
                A = F.T @ F + (reg * max(n, 1) + jitter) * eye
                b = F.T @ w[s:e] if n else np.zeros(rank)
            out[i] = np.linalg.solve(A, b) if n else 0.0
        return out

    for _ in range(iters):
        U = half(V, u_ptr, u_cols, u_vals, n_users)
        V = half(U, i_ptr, i_cols, i_vals, n_items)
    return U, V


def topk(U, V, k):
    scores = U @ V.T
    idx = np.argpartition(-scores, min(k, scores.shape[1] - 1),
                          axis=1)[:, :k]
    ordered = np.take_along_axis(
        idx, np.argsort(-np.take_along_axis(scores, idx, axis=1),
                        kind="stable", axis=1), axis=1)
    return ordered


def eval_metrics(U, V, test_u, test_i, test_r, ks=(1, 3, 10),
                 thresholds=(0.0, 2.0, 4.0), ndcg_k=10):
    """Reference eval contract over held-out ratings: per test-user
    Precision@K (relevant = held-out rated ≥ threshold) averaged over
    users, plus binary NDCG@10 at threshold 2.0."""
    by_user = {}
    for u, i, r in zip(test_u, test_i, test_r):
        by_user.setdefault(int(u), []).append((int(i), float(r)))
    users_sorted = sorted(by_user)
    max_k = max(max(ks), ndcg_k)
    recs = topk(U[users_sorted], V, max_k)
    out = {}
    for thr in thresholds:
        for k in ks:
            vals = []
            for row, u in enumerate(users_sorted):
                rel = {i for i, r in by_user[u] if r >= thr}
                if not rel:
                    continue
                hits = sum(1 for i in recs[row, :k] if i in rel)
                vals.append(hits / k)
            out[f"precision@{k}_thr{thr:g}"] = float(np.mean(vals)) \
                if vals else 0.0
    # binary NDCG@10, threshold 2.0
    vals = []
    for row, u in enumerate(users_sorted):
        rel = {i for i, r in by_user[u] if r >= 2.0}
        if not rel:
            continue
        dcg = sum(1.0 / np.log2(p + 2)
                  for p, i in enumerate(recs[row, :ndcg_k]) if i in rel)
        ideal = sum(1.0 / np.log2(p + 2)
                    for p in range(min(len(rel), ndcg_k)))
        vals.append(dcg / ideal if ideal else 0.0)
    out[f"ndcg@{ndcg_k}_thr2"] = float(np.mean(vals)) if vals else 0.0
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--folds", type=int, default=3)
    ap.add_argument("--rank", type=int, default=10)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--reg", type=float, default=0.01)
    # full-scale fold (VERDICT r2 task 7): explicit dims + sampled-user
    # metric eval, e.g. --n-users 138000 --n-items 27000 --nnz 20000000
    # --folds 1 --eval-sample 4096
    ap.add_argument("--n-users", type=int, default=None)
    ap.add_argument("--n-items", type=int, default=None)
    ap.add_argument("--nnz", type=int, default=None)
    ap.add_argument("--eval-sample", type=int, default=0,
                    help="metric eval on this many sampled test users "
                         "(0 = all)")
    args = ap.parse_args()

    import jax

    from predictionio_tpu.models.als import (
        ALSParams,
        RatingsCOO,
        train_als,
    )

    users, items, stars, n_users, n_items = make_dataset(
        n_users=args.n_users or int(3000 * args.scale),
        n_items=args.n_items or int(800 * args.scale),
        nnz=args.nnz or int(120_000 * args.scale))
    n = len(users)
    rng = np.random.default_rng(11)
    if args.folds == 1:
        # single big fold: 90/10 split (a k-fold with k=1 has no train)
        fold_of = np.where(rng.random(n) < 0.1, 0, 1)
    else:
        perm = rng.permutation(n)
        fold_of = np.arange(n) % args.folds
        fold_of = fold_of[np.argsort(perm, kind="stable")]

    params = ALSParams(rank=args.rank, num_iterations=args.iters,
                       reg=args.reg, seed=3)
    report = {"device": str(jax.devices()[0].device_kind),
              "n_users": n_users, "n_items": n_items, "nnz": n,
              "rank": args.rank, "iters": args.iters, "reg": args.reg,
              "folds": {}}
    worst = 0.0
    for f in range(args.folds if args.folds > 1 else 1):
        tr = fold_of != 0 if args.folds == 1 else fold_of != f
        te = ~tr
        if args.eval_sample:
            # metric eval on a user sample: full-scale folds score 4k
            # users instead of 130k (training is still full-scale)
            te_users = np.unique(users[te])
            pick = np.random.default_rng(13).choice(
                te_users, size=min(args.eval_sample, len(te_users)),
                replace=False)
            te = te & np.isin(users, pick)
        ratings = RatingsCOO(users[tr], items[tr], stars[tr],
                             n_users, n_items)
        t0 = time.monotonic()
        U_f, V_f = train_als(ratings, params)
        U_f = np.asarray(U_f, dtype=np.float64)[:n_users]
        V_f = np.asarray(V_f, dtype=np.float64)[:n_items]
        t_fw = time.monotonic() - t0
        t0 = time.monotonic()
        U_o, V_o = oracle_als(users[tr], items[tr], stars[tr], n_users,
                              n_items, args.rank, args.iters, args.reg,
                              seed=3)
        t_or = time.monotonic() - t0
        m_f = eval_metrics(U_f, V_f, users[te], items[te], stars[te])
        m_o = eval_metrics(U_o, V_o, users[te], items[te], stars[te])

        # implicit mode: binarize likes (★≥3), HKV confidence — the
        # similar-product/e-commerce templates' trainer, and the regime
        # where top-K metrics are far from zero
        like = stars[tr] >= 3.0
        imp = RatingsCOO(users[tr][like], items[tr][like],
                         np.ones(int(like.sum()), np.float32),
                         n_users, n_items)
        ip = ALSParams(rank=args.rank, num_iterations=args.iters,
                       reg=args.reg, seed=3, implicit_prefs=True,
                       alpha=10.0)
        Ui_f, Vi_f = train_als(imp, ip)
        Ui_f = np.asarray(Ui_f, dtype=np.float64)[:n_users]
        Vi_f = np.asarray(Vi_f, dtype=np.float64)[:n_items]
        Ui_o, Vi_o = oracle_als(imp.users, imp.items, imp.ratings,
                                n_users, n_items, args.rank, args.iters,
                                args.reg, seed=3, implicit=True,
                                alpha=10.0)
        lik_te = stars[te] >= 3.0
        mi_f = eval_metrics(Ui_f, Vi_f, users[te][lik_te],
                            items[te][lik_te], stars[te][lik_te],
                            thresholds=(0.0,))
        mi_o = eval_metrics(Ui_o, Vi_o, users[te][lik_te],
                            items[te][lik_te], stars[te][lik_te],
                            thresholds=(0.0,))
        m_f.update({f"implicit_{k}": v for k, v in mi_f.items()})
        m_o.update({f"implicit_{k}": v for k, v in mi_o.items()})

        deltas = {}
        for key in m_f:
            # relative gate with a small absolute floor: near-zero
            # metrics compare at 1% of 0.02 = 2e-4 absolute
            denom = max(abs(m_o[key]), 0.02)
            d = abs(m_f[key] - m_o[key]) / denom
            deltas[key] = round(d, 5)
            worst = max(worst, d)
        report["folds"][f] = {
            "framework": {k: round(v, 5) for k, v in m_f.items()},
            "oracle_f64": {k: round(v, 5) for k, v in m_o.items()},
            "rel_delta": deltas,
            "train_s_framework": round(t_fw, 2),
            "train_s_oracle": round(t_or, 2),
        }
    report["worst_rel_delta"] = round(worst, 5)
    report["pass_1pct"] = bool(worst <= 0.01)
    print(json.dumps(report, indent=1))
    if not report["pass_1pct"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
