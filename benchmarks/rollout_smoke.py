"""CI smoke: the progressive-delivery loop end-to-end on a toy engine.

Boots a real engine server on a loopback port with a synthetic stable
release, then exercises BOTH terminal rollout outcomes:

1. **Auto-rollback** — canaries a deliberately erroring candidate at
   50% and asserts the health gate rolls it back within the configured
   window, stable traffic never stops answering, and ``/release.json``
   records the canary + rollback history.
2. **Auto-promote** — canaries a healthy candidate and asserts it ramps
   to 100%, becomes the serving + pinned stable, and zero queries fail
   across the swap.

Exit 0 on success; non-zero with a reason otherwise. Run on CPU:
``JAX_PLATFORMS=cpu python benchmarks/rollout_smoke.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.error
import urllib.request
from datetime import datetime, timezone

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def call(port: int, method: str, path: str, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


def synth_model(seed: int, n_users: int = 32, n_items: int = 48,
                rank: int = 8):
    import numpy as np

    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.als import ALSModel, ALSParams

    rng = np.random.default_rng(seed)
    return ALSModel(
        user_factors=rng.standard_normal(
            (n_users, rank)).astype(np.float32),
        item_factors=rng.standard_normal(
            (n_items, rank)).astype(np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))


class PoisonServing:
    """The 'bad retrain': every candidate query fails."""

    def supplement(self, q):
        raise RuntimeError("candidate poison")


def drive(port: int, n_users: int = 24):
    results = []
    for u in range(n_users):
        results.append(call(port, "POST", "/queries.json",
                            {"user": f"u{u}", "num": 3}))
    return results


def main() -> int:
    from predictionio_tpu.controller import Context
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.data.storage.base import (
        STATUS_COMPLETED,
        EngineInstance,
        Model,
    )
    from predictionio_tpu.rollout import HealthPolicy
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
        create_engine_server,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )
    from predictionio_tpu.workflow import persistence
    from predictionio_tpu.workflow.core import load_models_for_deploy

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "rollsmoke"))
    ctx = Context(app_name="rollsmoke", _storage=storage)
    now = datetime.now(timezone.utc)
    for i, iid in enumerate(("stable-1", "cand-bad", "cand-good")):
        storage.engine_instances().insert(EngineInstance(
            id=iid, status=STATUS_COMPLETED, start_time=now,
            end_time=now, engine_id="smoke", engine_version="1",
            engine_variant="engine.json", engine_factory="synthetic"))
        storage.models().insert(Model(
            id=iid, models=persistence.dumps_models(
                [synth_model(seed=i)])))

    engine = recommendation_engine()
    ep = default_engine_params("rollsmoke", rank=8)
    inst = storage.engine_instances().get("stable-1")
    models = load_models_for_deploy(ctx, engine, inst, ep)
    qs = QueryServer(ctx, engine, ep, models, inst,
                     ServerConfig(warm_start=False))
    srv = create_engine_server(qs, host="127.0.0.1", port=0)
    srv.start_background()
    port = srv.port
    try:
        # -- phase 1: erroring candidate must auto-roll-back ---------------
        policy = HealthPolicy(window_sec=0.3, min_queries=5,
                              ramp=(0.5, 1.0), max_error_rate=0.2)
        ctl = qs.start_canary("cand-bad", fraction=0.5, policy=policy,
                              actor="rollout-smoke",
                              reason="deliberately erroring")
        qs._candidate.serving = PoisonServing()
        deadline = time.monotonic() + 60
        saw_candidate_error = False
        while time.monotonic() < deadline and ctl.active:
            for status, body in drive(port):
                if status == 500:
                    saw_candidate_error = True
                elif status == 200:
                    assert body.get("itemScores"), f"bad body: {body}"
                else:
                    raise AssertionError(
                        f"unexpected status {status}: {body}")
            time.sleep(0.02)
        assert not ctl.active, "gate never concluded on erroring canary"
        assert ctl.outcome == "rolled_back", ctl.outcome
        assert saw_candidate_error, "canary traffic never hit candidate"
        status, rel = call(port, "GET", "/release.json")
        actions = [e["action"] for e in rel["history"]]
        assert "canary" in actions and "rollback" in actions, actions
        assert rel["serving"]["stableInstanceId"] == "stable-1"
        assert rel["arms"]["candidate"]["errors"] > 0
        print(f"[rollback] auto-rolled-back after {ctl.windows} "
              f"window(s): {ctl.last_decision.reason}")

        # -- phase 2: healthy candidate must ramp to pinned stable ---------
        policy = HealthPolicy(window_sec=0.3, min_queries=5,
                              ramp=(0.25, 1.0))
        ctl = qs.start_canary("cand-good", policy=policy,
                              actor="rollout-smoke",
                              reason="healthy retrain")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and ctl.active:
            for status, body in drive(port):
                assert status == 200 and body.get("itemScores"), \
                    f"query failed during healthy ramp: {status} {body}"
            time.sleep(0.02)
        assert not ctl.active, "gate never concluded on healthy canary"
        assert ctl.outcome == "promoted", ctl.outcome
        assert qs.instance.id == "cand-good"
        status, rel = call(port, "GET", "/release.json")
        assert rel["state"]["stable"] == "cand-good"
        assert rel["state"]["pinned"] == "cand-good"
        actions = [e["action"] for e in rel["history"]]
        assert "ramp" in actions and "promote" in actions, actions
        # the promoted release also answers /status.json coherently
        status, st = call(port, "GET", "/status.json")
        assert st["release"]["stable"] == "cand-good"
        print(f"[promote] ramped to 100% and pinned after "
              f"{ctl.windows} window(s)")
    finally:
        srv.shutdown()
    print("rollout smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
