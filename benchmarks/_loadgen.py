"""Shared load-generation core for the HTTP benchmarks (ISSUE 15).

``serving_bench``, ``http_ingest_bench`` and the mixed-traffic
``load_harness`` all drive a real server from a worker-thread pool;
before this module each kept its own near-copy of the pool, the index
hand-off, the latency accounting and the keep-alive connection
handling. One definition now, with both loop disciplines:

- **closed loop** (``rate_qps=None``): workers fire as fast as the
  server answers — latency is measured from each send. Good for
  "how fast can it go" burst batteries; it systematically under-states
  latency under overload (a stalling server slows the offered load).
- **open loop** (``rate_qps`` set): request *k*'s intended start is
  ``t0 + k/rate`` regardless of how the server is doing, and latency
  is measured **from that schedule** — the coordinated-omission-safe
  discipline (MLPerf-style): a stalling server accrues queueing delay
  on every scheduled arrival instead of silently thinning the load.
  Sweeping the rate and watching p99 is how the qps-vs-p99 knee is
  found.

Senders own their connections and heal them: a sender must raise on
failure and may keep per-thread state (one keep-alive HTTP/1.1
connection per worker — on a shared host, per-request TCP
setup/teardown dominates before the server does).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Callable, Iterable, Optional, Tuple

import numpy as np

#: sender verdicts: anything else must be raised as an exception
OK = "ok"
SHED = "shed"


class LoadStats:
    """Thread-safe accumulator: latencies (seconds) by verdict plus
    error strings."""

    def __init__(self) -> None:
        self.lat: list = []
        self.shed: list = []
        self.errors: list = []
        self._lock = threading.Lock()

    def ok(self, dt: float) -> None:
        with self._lock:
            self.lat.append(dt)

    def shed_one(self, dt: float) -> None:
        with self._lock:
            self.shed.append(dt)

    def error(self, msg: str) -> None:
        with self._lock:
            self.errors.append(msg)

    def percentiles(self) -> dict:
        """``{p50_ms, p90_ms, p99_ms}`` over the OK latencies (empty
        dict when none landed)."""
        if not self.lat:
            return {}
        arr = np.sort(np.asarray(self.lat)) * 1e3
        return {
            "p50_ms": round(float(np.percentile(arr, 50)), 2),
            "p90_ms": round(float(np.percentile(arr, 90)), 2),
            "p99_ms": round(float(np.percentile(arr, 99)), 2),
        }

    def summary(self, wall: float) -> dict:
        """The standard result row every consumer emits."""
        out = {
            "n": len(self.lat),
            "shed": len(self.shed),
            "errors": len(self.errors),
            "qps": (round(len(self.lat) / wall, 1) if wall > 0
                    else None),
            **self.percentiles(),
        }
        return out


def run_load(worker_factory: Callable[[], Callable[[int], str]],
             n_requests: int, n_threads: int,
             rate_qps: Optional[float] = None,
             start_delay: float = 0.05,
             stop: Optional[threading.Event] = None
             ) -> Tuple[LoadStats, float]:
    """Drive ``n_requests`` through ``n_threads`` workers.

    ``worker_factory()`` runs once per thread and returns
    ``send(k) -> "ok" | "shed"``; the sender raises on failure and owns
    (and heals) its own connection. Closed loop measures from each
    send; open loop (``rate_qps``) measures from request *k*'s
    scheduled arrival ``t0 + k/rate`` — see the module docstring for
    why that distinction is the whole point. ``stop`` (optional) ends
    the run early — used by background traffic lanes whose duration is
    decided by a foreground measurement.

    Returns ``(stats, wall_seconds)`` where wall spans first scheduled
    arrival (open) or first send (closed) to last completion.
    """
    stats = LoadStats()
    it = iter(range(int(n_requests)))
    it_lock = threading.Lock()
    t0 = time.monotonic() + start_delay if rate_qps else None

    def loop() -> None:
        send = worker_factory()
        try:
            while not (stop is not None and stop.is_set()):
                with it_lock:
                    k = next(it, None)
                if k is None:
                    return
                if rate_qps:
                    t_ref = t0 + k / rate_qps
                    delay = t_ref - time.monotonic()
                    if delay > 0:
                        if stop is None:
                            time.sleep(delay)
                        elif stop.wait(delay):
                            return
                else:
                    t_ref = time.monotonic()
                try:
                    verdict = send(k)
                except Exception as e:  # noqa: BLE001 — surface, not die
                    stats.error(str(e))
                    continue
                # latency from the SCHEDULED start under open loop:
                # waiting for a worker/connection counts against the
                # server, never against the workload
                dt = time.monotonic() - t_ref
                if verdict == SHED:
                    stats.shed_one(dt)
                else:
                    stats.ok(dt)
        finally:
            closer = getattr(send, "close", None)
            if closer is not None:
                try:
                    closer()
                except Exception:  # noqa: BLE001 — teardown only
                    pass

    threads = [threading.Thread(target=loop, daemon=True)
               for _ in range(int(n_threads))]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - (max(t_start, t0) if t0 is not None
                               else t_start)
    return stats, wall


def parse_endpoints(spec: Iterable[str]) -> list:
    """``["host:port", "http://host:port", ...]`` →
    ``[(host, port), ...]`` — the ``--endpoints`` grammar of the
    multi-replica harness (schemes are accepted and stripped; the
    load core speaks plain keep-alive HTTP)."""
    out = []
    for item in spec:
        item = str(item).strip().rstrip("/")
        if not item:
            continue
        if "://" in item:
            item = item.split("://", 1)[1]
        host, _, port = item.rpartition(":")
        if not host:
            raise ValueError(f"endpoint {item!r} needs host:port")
        out.append((host, int(port)))
    if not out:
        raise ValueError("no endpoints given")
    return out


def json_post_sender(port: int, path, body_fn: Callable[[int], bytes],
                     check: Optional[Callable[[int, bytes],
                                              Optional[str]]] = None,
                     shed_status: Iterable[int] = (503,),
                     host: str = "127.0.0.1",
                     timeout: float = 120.0,
                     endpoints: Optional[Iterable[str]] = None,
                     content_type: str = "application/json"
                     ) -> Callable[[], Callable[[int], str]]:
    """A ``worker_factory`` POSTing JSON over one keep-alive
    connection per worker. ``path`` is a string or ``path(k)``;
    ``check(status, payload)`` returns an error string for a bad
    response (None = OK; default accepts exactly 200). A transport
    error closes the connection — ``http.client`` reconnects lazily on
    the next request.

    ``endpoints`` (ISSUE 17): a list of ``host:port`` targets sprayed
    round-robin — request ``k`` goes to target ``k % N``, so an
    open-loop schedule splits evenly across a replica fleet. Each
    worker keeps one keep-alive connection PER target. Overrides
    ``host``/``port`` when given."""
    shed = set(shed_status)
    targets = (parse_endpoints(endpoints) if endpoints
               else [(host, port)])

    def factory() -> Callable[[int], str]:
        conns = [http.client.HTTPConnection(h, p, timeout=timeout)
                 for h, p in targets]

        def send(k: int) -> str:
            conn = conns[k % len(conns)]
            body = body_fn(k)
            try:
                conn.request(
                    "POST", path(k) if callable(path) else path,
                    body=body,
                    headers={"Content-Type": content_type})
                resp = conn.getresponse()
                payload = resp.read()
            except Exception:
                conn.close()  # reconnect lazily on the next request
                raise
            if resp.status in shed:
                return SHED
            if check is not None:
                err = check(resp.status, payload)
                if err:
                    raise RuntimeError(err)
            elif resp.status != 200:
                raise RuntimeError(f"status {resp.status}")
            return OK

        def close() -> None:
            for c in conns:
                c.close()

        send.close = close  # type: ignore[attr-defined]
        return send

    return factory


def expect_json_field(field: str) -> Callable[[int, bytes],
                                              Optional[str]]:
    """A ``check`` asserting status 200 and a non-null ``field`` in
    the JSON body (the ``itemScores`` contract of /queries.json)."""

    def check(status: int, payload: bytes) -> Optional[str]:
        if status != 200:
            return f"status {status}"
        try:
            if json.loads(payload).get(field) is None:
                return f"bad response: missing {field!r}"
        except (ValueError, UnicodeDecodeError) as e:
            return f"unparseable response: {e}"
        return None

    return check


def sample_entities(rng, n_entities: int, size: int,
                    zipf: Optional[float] = None) -> np.ndarray:
    """Uniform entity draw, or Zipf(α)-skewed when ``zipf`` is set
    (rank 1 = the hottest entity, wrapped into the id space) — the
    hot-entity skew production recommendation traffic actually has."""
    if zipf is None:
        return rng.integers(0, n_entities, size)
    return (rng.zipf(float(zipf), size=size) - 1) % n_entities
