"""Staged serving-pipeline smoke (ISSUE 9) — the CI gate for the
continuous-batching batch path.

End-to-end over real HTTP on whatever device is available (CI: CPU):

1. deploy a synthetic device-budget model with the STAGED pipeline and
   flood it with concurrent bursts; every query must answer 200 with a
   correctly-shaped, correctly-ordered result (no lost or swapped
   slots);
2. prove overlap from the server's own accounting: the
   device-idle-fraction gauge moved off 1.0 and at least one dispatch
   launched while an earlier batch was still in flight
   (`pio_pipeline_overlapped_dispatches_total` > 0), with the
   per-stage `pio_pipeline_stage_seconds` series present on /metrics;
3. exercise the deadline path deterministically: a second server with a
   aggressive `queue_deadline_ms` and a wide batch window sheds a lone
   query with 503 and counts it in
   `pio_query_deadline_exceeded_total`.

Prints one JSON line; exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import urllib.error
import urllib.request
from datetime import datetime, timezone

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from predictionio_tpu.controller import Context  # noqa: E402
from predictionio_tpu.data.bimap import BiMap  # noqa: E402
from predictionio_tpu.data.storage import App, Storage  # noqa: E402
from predictionio_tpu.data.storage.base import (  # noqa: E402
    STATUS_COMPLETED,
    EngineInstance,
)
from predictionio_tpu.models.als import ALSModel, ALSParams  # noqa: E402
from predictionio_tpu.server.engineserver import (  # noqa: E402
    QueryServer,
    ServerConfig,
    create_engine_server,
)
from predictionio_tpu.templates.recommendation import (  # noqa: E402
    default_engine_params,
    recommendation_engine,
)


def call(port, method, path, body=None, timeout=120):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else (
        b"" if method == "POST" else None)
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _server(model, cfg):
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "pipesmoke"))
    ctx = Context(app_name="pipesmoke", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="smoke", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="smoke", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    storage.engine_instances().insert(inst)
    qs = QueryServer(
        ctx, recommendation_engine(),
        default_engine_params("pipesmoke", rank=model.params.rank),
        [model], inst, cfg)
    return qs, create_engine_server(qs, "127.0.0.1",
                                    0).start_background()


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    rng = np.random.default_rng(0)
    # past HOST_SERVE_WORK at batch size, so the batcher actually
    # dispatches to the device backend (CPU in CI) — small enough that
    # a burst answers in seconds
    n_users, n_items, rank = 5_000, 70_000, 32
    import jax

    model = ALSModel(
        user_factors=jax.device_put(rng.standard_normal(
            (n_users, rank)).astype(np.float32)),
        item_factors=jax.device_put(rng.standard_normal(
            (n_items, rank)).astype(np.float32)),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))

    checks = {}
    qs, srv = _server(model, ServerConfig(
        batching=True, max_batch=16, batch_window_ms=2.0,
        warm_start=False))
    try:
        # 1) burst correctness: every query answers with ITS user's
        # top-k (references computed through the per-query path)
        want = {}
        for u in (1, 7, 42, 99):
            _, want[u] = call(srv.port, "POST", "/queries.json",
                              {"user": f"u{u}", "num": 5})
        n_flood = 96
        results: list = [None] * n_flood
        statuses: list = [None] * n_flood
        users = [(1, 7, 42, 99)[i % 4] for i in range(n_flood)]

        def fire(i):
            try:
                statuses[i], results[i] = call(
                    srv.port, "POST", "/queries.json",
                    {"user": f"u{users[i]}", "num": 5})
            except Exception as e:  # noqa: BLE001 — surface in checks
                statuses[i] = str(e)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(n_flood)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        checks["burst_all_200"] = all(s == 200 for s in statuses)
        checks["burst_no_swapped_slots"] = all(
            r == want[u] for r, u in zip(results, users))

        # 2) overlap proof from the server's own accounting
        _, status = call(srv.port, "GET", "/status.json")
        pipe = status.get("pipeline") or {}
        ov = pipe.get("overlap") or {}
        checks["pipeline_mode_staged"] = pipe.get("mode") == "staged"
        checks["device_idle_moved"] = (
            ov.get("deviceIdleFraction") is not None
            and ov["deviceIdleFraction"] < 1.0)
        checks["overlapped_dispatches"] = (
            ov.get("overlappedDispatches", 0) > 0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=30) as resp:
            text = resp.read().decode()
        checks["stage_series_exported"] = (
            'pio_pipeline_stage_seconds' in text
            and 'stage="dispatch"' in text)
    finally:
        srv.shutdown()

    # 3) deadline shedding, deterministically: a lone query against a
    # wide batch window + sub-window deadline MUST shed with 503
    qs2, srv2 = _server(model, ServerConfig(
        batching=True, max_batch=16, batch_window_ms=500.0,
        queue_deadline_ms=50.0, warm_start=False))
    try:
        try:
            status_code, _ = call(srv2.port, "POST", "/queries.json",
                                  {"user": "u1", "num": 5})
        except urllib.error.HTTPError as e:
            status_code = e.code
        checks["deadline_503"] = status_code == 503
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv2.port}/metrics",
                timeout=30) as resp:
            text = resp.read().decode()
        shed = [ln for ln in text.splitlines()
                if ln.startswith("pio_query_deadline_exceeded_total")]
        checks["deadline_counted"] = bool(
            shed and float(shed[0].rsplit(" ", 1)[1]) >= 1.0)
    finally:
        srv2.shutdown()

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({"bench": "pipeline_smoke", "ok": ok, **checks}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
