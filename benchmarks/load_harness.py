"""Mixed-traffic load harness: the qps-vs-p99 frontier + capacity model.

ISSUE 15's measuring instrument. Every prior serving number in the
BENCH line came from a single-lane workload — queries alone, ingest
alone, fold-ins alone. Production traffic is all of them at once, and
the PR 10 freshness claim (21.6 ms event→servable) had never been
measured while queries were in flight. This harness drives the REAL
deployed stack (event server + engine server sharing the in-process
invalidation bus) with **mixed open-loop traffic**:

- Zipf-skewed ``/queries.json`` load at a fixed offered rate
  (coordinated-omission-safe: latency measured from each request's
  scheduled arrival — ``benchmarks/_loadgen.py``);
- concurrent event ingest through ``POST /events.json`` at a fraction
  of the query rate (new and existing entities, so the streaming
  trainer folds rows in AND the serving cache sees invalidations);
- the streaming trainer's fold-ins riding those ingests into the live
  binding (hot swaps under load);
- an optional held-open canary ramp serving a cohort fraction from a
  candidate binding.

Per serving config the offered rate is swept up a ladder until the
config stops sustaining it (achieved < 92% of offered, sheds past 1%,
or any failed request) — the last sustained rate is the **knee**. A
verification pass then runs at 80% of the knee, measuring p99 AND
event→servable freshness under that load (the ingest→fold-in→serve
probe from ``streaming_smoke`` with the query generator running).

Output: one JSON line plus ``CAPACITY.json`` (``--out``) — per config:
the frontier rows, ``knee_qps``, ``p99_at_80pct_knee_ms``,
``freshness_under_load_ms``, ``device_idle_fraction`` — the
machine-readable capacity model ``bench.py`` embeds in the BENCH line
and ``ptpu slo check`` gates against the committed
``slo/specs/ci.json`` (docs/slo.md).

Usage: python benchmarks/load_harness.py
           [--configs host,staged,cached] [--rate-min QPS]
           [--rate-max QPS] [--step-sec S] [--zipf ALPHA]
           [--ingest-frac F] [--canary F|0] [--freshness-trials N]
           [--out CAPACITY.json] [--ci]
           [--endpoints URL[,URL...]]

``--ci`` picks small, runner-friendly defaults (the CI capacity-gate
step). Configs: host | staged | serial | cached | replicated |
sharded | quantized | router (mesh configs skip themselves on one
device). The ``router`` config (ISSUE 18) boots TWO engine-server
replicas behind the entity-affinity :class:`QueryRouter` and drives
every query lane through the router's HTTP front — the frontier then
prices the router hop and the CAPACITY.json row feeds the
autoscaler's knee model.

``--endpoints`` (ISSUE 17) switches to **external-fleet mode**: no
local stack is booted — the query lane sprays round-robin across the
given already-running replicas (request *k* → replica ``k % N``), so
the same open-loop frontier sweep measures a multi-replica fleet
behind a ``ptpu fleet serve`` aggregator. Ingest/canary/freshness
lanes are skipped (they need the in-process stack).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from datetime import datetime, timedelta, timezone

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _loadgen import (  # noqa: E402
    expect_json_field,
    json_post_sender,
    run_load,
    sample_entities,
)
from predictionio_tpu.controller import Context  # noqa: E402
from predictionio_tpu.data import DataMap, Event  # noqa: E402
from predictionio_tpu.data.storage import App, Storage  # noqa: E402
from predictionio_tpu.data.storage.base import (  # noqa: E402
    STATUS_COMPLETED,
    AccessKey,
    EngineInstance,
)
from predictionio_tpu.templates.recommendation import (  # noqa: E402
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import (  # noqa: E402
    get_latest_completed,
    load_models_for_deploy,
    run_train,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
N_SEED_USERS = 30
N_SEED_ITEMS = 30

#: a rate step "sustains" when it achieves at least this fraction of
#: the offered rate with sheds under SHED_FRAC and zero failures
SUSTAIN_FRAC = 0.92
SHED_FRAC = 0.01


def _server_config(name: str, app_name: str, step_sec: float):
    """The ServerConfig for one named serving config — every config
    carries the streaming trainer so fold-ins ride the ingest lane."""
    from predictionio_tpu.server.engineserver import ServerConfig

    base = dict(
        streaming=True, stream_app_name=app_name,
        stream_interval_ms=100.0, stream_canary_probes=2,
        stream_consumer=f"load-harness-{name}",
        # shed fast enough that an over-the-knee step ends within the
        # step window instead of parking requests for 30s
        queue_deadline_ms=max(step_sec * 1000.0, 5_000.0))
    table = {
        "host": {},
        "staged": dict(batching=True, max_batch=64,
                       batch_window_ms=2.0),
        "serial": dict(batching=True, max_batch=64,
                       batch_window_ms=2.0,
                       serving_pipeline="serial"),
        "cached": dict(serving_cache=True, cache_ttl_sec=5.0,
                       hot_entities=0),
        "replicated": dict(batching=True, max_batch=64,
                           batch_window_ms=2.0,
                           serving_mode="replicated"),
        "sharded": dict(batching=True, max_batch=64,
                        batch_window_ms=2.0, serving_mode="sharded"),
        "quantized": dict(batching=True, max_batch=64,
                          batch_window_ms=2.0, serving_quant="int8"),
        # per-replica config behind the entity-affinity router; the
        # router itself is wired up in Stack
        "router": dict(batching=True, max_batch=64,
                       batch_window_ms=2.0),
    }
    if name not in table:
        raise SystemExit(f"unknown config {name!r} "
                         f"(know: {sorted(table)})")
    return ServerConfig(**base, **table[name])


def _seed(storage, app_id) -> int:
    """The two-taste-group seed corpus (mirrors streaming_smoke)."""
    rng = np.random.default_rng(7)
    events, t = [], T0
    for u in range(N_SEED_USERS):
        group = range(0, 15) if u % 2 == 0 else range(15, 30)
        for i in rng.choice(list(group), size=8, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": 5.0}), event_time=t))
            t += timedelta(minutes=1)
    storage.events().insert_batch(events, app_id)
    return len(events)


class Stack:
    """One booted serving stack: storage, trained instance, event
    server + engine server sharing the process-default bus."""

    def __init__(self, cfg_name: str, step_sec: float,
                 canary_fraction: float):
        from predictionio_tpu.server.engineserver import (
            QueryServer,
            create_engine_server,
        )
        from predictionio_tpu.server.eventserver import (
            build_app as build_event_app,
        )
        from predictionio_tpu.server.http import AppServer

        app_name = f"loadharness_{cfg_name}"
        storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
        app_id = storage.apps().insert(App(0, app_name))
        storage.events().init(app_id)
        storage.access_keys().insert(
            AccessKey(key="lh", app_id=app_id, events=[]))
        self.n_seed_events = _seed(storage, app_id)
        ctx = Context(app_name=app_name, _storage=storage)
        engine = recommendation_engine()
        ep = default_engine_params(app_name, rank=8, num_iterations=6,
                                   reg=0.05, seed=11)
        run_train(ctx, engine, ep, engine_id=app_name,
                  engine_factory="templates.recommendation")
        inst = get_latest_completed(ctx, engine_id=app_name)
        models = load_models_for_deploy(ctx, engine, inst, ep)
        server_cfg = _server_config(cfg_name, app_name, step_sec)
        self.qs = QueryServer(
            ctx, engine, ep, models, inst, server_cfg)
        self.ev_srv = AppServer(build_event_app(storage), "127.0.0.1",
                                0).start_background()
        self.en_srv = create_engine_server(
            self.qs, "127.0.0.1", 0).start_background()
        self._wait_warm()
        # the router config serves through a QueryRouter in front of
        # TWO replicas (each with its own streaming consumer cursor,
        # so fold-ins land on both) — the query lane prices the
        # router hop, spill, and retry machinery end to end
        self.extra: list = []
        self.router = None
        self.router_srv = None
        self.query_port = self.en_srv.port
        if cfg_name == "router":
            import dataclasses

            from predictionio_tpu.router import (
                QueryRouter,
                RouterConfig,
                create_router_server,
            )

            cfg2 = dataclasses.replace(
                server_cfg,
                stream_consumer=f"{server_cfg.stream_consumer}-r1")
            qs2 = QueryServer(
                ctx, engine, ep,
                load_models_for_deploy(ctx, engine, inst, ep),
                inst, cfg2)
            srv2 = create_engine_server(
                qs2, "127.0.0.1", 0).start_background()
            self.extra.append((qs2, srv2))
            self._wait_warm(srv2.port)
            self.router = QueryRouter(RouterConfig(retries=1))
            for port in (self.en_srv.port, srv2.port):
                self.router.add(f"127.0.0.1:{port}")
            self.router_srv = create_router_server(
                self.router, "127.0.0.1", 0).start_background()
            self.query_port = self.router_srv.port
        self.canary = False
        if canary_fraction > 0:
            # a held-open canary ramp rides along: a cohort fraction
            # serves from a candidate binding while the gate never
            # closes (the mixed-traffic lane, not a rollout test)
            from predictionio_tpu.rollout import HealthPolicy

            now = datetime.now(timezone.utc)
            storage.engine_instances().insert(EngineInstance(
                id=f"{app_name}-cand", status=STATUS_COMPLETED,
                start_time=now, end_time=now, engine_id=app_name,
                engine_version="1", engine_variant="engine.json",
                engine_factory="synthetic"))
            cand_models = load_models_for_deploy(ctx, engine, inst, ep)
            self.qs.start_canary(
                f"{app_name}-cand", fraction=canary_fraction,
                policy=HealthPolicy(window_sec=3600,
                                    min_queries=1 << 30),
                models=cand_models, actor="load-harness")
            self.qs._candidate.warm_done.wait(timeout=300)
            self.canary = True

    def _wait_warm(self, port: int = 0) -> None:
        port = port or self.en_srv.port
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status.json",
                    timeout=30) as resp:
                if json.loads(resp.read()).get("servingWarm"):
                    return
            time.sleep(0.2)
        raise RuntimeError("serving warmup did not finish")

    def status(self) -> dict:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.en_srv.port}/status.json",
                timeout=30) as resp:
            return json.loads(resp.read())

    def get(self, path: str) -> dict:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.en_srv.port}{path}",
                timeout=30) as resp:
            return json.loads(resp.read())

    def shutdown(self) -> None:
        if self.router_srv is not None:
            self.router_srv.shutdown()
        for qs, srv in self.extra:
            qs.stop_stream()
            qs.stop_slo()
            srv.shutdown()
        self.qs.stop_stream()
        self.qs.stop_slo()
        self.en_srv.shutdown()
        self.ev_srv.shutdown()


def _ingest_check(status: int, payload: bytes):
    if status != 201:
        return f"ingest status {status}"
    return None


def _ingest_sender(stack: Stack, tag: str):
    """Event-lane sender: two thirds of the lane ingests ratings for
    BRAND-NEW users (fold-in row growth), one third for existing seed
    users (cache invalidation + row updates)."""

    def body(k: int) -> bytes:
        user = (f"u{k % N_SEED_USERS}" if k % 3 == 0
                else f"lh_{tag}_{k}")
        return json.dumps({
            "event": "rate", "entityType": "user", "entityId": user,
            "targetEntityType": "item",
            "targetEntityId": f"i{k % 15}",
            "properties": {"rating": 5.0}}).encode()

    return json_post_sender(stack.ev_srv.port,
                            "/events.json?accessKey=lh",
                            body_fn=body, check=_ingest_check,
                            shed_status=())


def _query_sender(stack: Stack, users: np.ndarray):
    return json_post_sender(
        stack.query_port, "/queries.json",
        body_fn=lambda k: json.dumps({"user": f"u{users[k]}",
                                      "num": 5}).encode(),
        check=expect_json_field("itemScores"), shed_status=(503,))


def _step(stack: Stack, tag: str, rate: float, step_sec: float,
          zipf, ingest_frac: float) -> dict:
    """One frontier point: open-loop queries at ``rate`` with the
    ingest lane running beside them."""
    n = max(int(rate * step_sec), 8)
    rng = np.random.default_rng(int(rate) + 17)
    users = sample_entities(rng, N_SEED_USERS, n, zipf)
    n_threads = int(min(64, max(8, rate // 2)))

    ingest_stop = threading.Event()
    ingest_box: list = []
    ingest_rate = max(rate * ingest_frac, 1.0)
    ingest_thread = threading.Thread(
        target=lambda: ingest_box.append(run_load(
            _ingest_sender(stack, tag),
            max(int(ingest_rate * step_sec * 4), 8), 2,
            rate_qps=ingest_rate, stop=ingest_stop)),
        daemon=True, name="ingest-lane")
    ingest_thread.start()
    try:
        stats, wall = run_load(_query_sender(stack, users), n,
                               n_threads, rate_qps=rate)
    finally:
        ingest_stop.set()
        ingest_thread.join(timeout=60)
    row = {
        "offered_qps": rate,
        "achieved_qps": (round(len(stats.lat) / wall, 1)
                         if wall > 0 else 0.0),
        "window_sec": round(wall, 2),
        **stats.summary(wall),
    }
    row.pop("qps", None)  # achieved_qps is the canonical name here
    if ingest_box:
        istats, iwall = ingest_box[0]
        row["ingest"] = {"offered_qps": round(ingest_rate, 2),
                         **istats.summary(iwall)}
    total = len(stats.lat) + len(stats.shed)
    row["sustained"] = bool(
        stats.lat
        and not stats.errors
        and row["achieved_qps"] >= SUSTAIN_FRAC * rate
        and len(stats.shed) <= SHED_FRAC * max(total, 1))
    if stats.errors:
        row["first_error"] = stats.errors[0][:160]
    return row


def _freshness_under_load(stack: Stack, tag: str, rate: float,
                          step_sec: float, zipf, trials: int) -> dict:
    """The PR 10 ingest→fold-in→servable probe WHILE the query
    generator holds the config at ``rate`` (80% of its knee): the
    freshness the streaming trainer delivers under real serving
    contention, not on an idle box."""
    n = max(int(rate * step_sec * 2), 16)
    rng = np.random.default_rng(23)
    users = sample_entities(rng, N_SEED_USERS, n, zipf)
    stop = threading.Event()
    box: list = []
    load_thread = threading.Thread(
        target=lambda: box.append(run_load(
            _query_sender(stack, users), n,
            int(min(64, max(8, rate // 2))), rate_qps=rate,
            stop=stop)),
        daemon=True, name="knee80-load")
    load_thread.start()
    samples_ms = []
    timeouts = 0
    try:
        time.sleep(min(1.0, step_sec / 4))  # let the load settle
        for k in range(trials):
            user = f"fresh_{tag}_{k}"
            t0 = time.monotonic()
            for j in range(3):
                body = json.dumps({
                    "event": "rate", "entityType": "user",
                    "entityId": user, "targetEntityType": "item",
                    "targetEntityId": f"i{(k * 3 + j) % 15}",
                    "properties": {"rating": 5.0}}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{stack.ev_srv.port}"
                    f"/events.json?accessKey=lh", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as resp:
                    assert resp.status == 201, resp.status
            deadline = time.monotonic() + 30.0
            servable = None
            while time.monotonic() < deadline:
                q = json.dumps({"user": user, "num": 5}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{stack.query_port}"
                    f"/queries.json", data=q,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req,
                                                timeout=30) as resp:
                        got = json.loads(resp.read())
                except urllib.error.HTTPError:
                    got = {}
                if got.get("itemScores"):
                    servable = (time.monotonic() - t0) * 1000.0
                    break
                time.sleep(0.02)
            if servable is None:
                timeouts += 1
            else:
                samples_ms.append(servable)
    finally:
        stop.set()
        load_thread.join(timeout=120)
    out: dict = {"trials": trials, "timeouts": timeouts}
    if samples_ms:
        arr = np.sort(np.asarray(samples_ms))
        out["p50_ms"] = round(float(np.percentile(arr, 50)), 1)
        out["max_ms"] = round(float(arr[-1]), 1)
    if box:
        stats, wall = box[0]
        out["load"] = {"offered_qps": rate, **stats.summary(wall)}
    return out


def measure_config(cfg_name: str, rates, step_sec: float, zipf,
                   ingest_frac: float, canary_fraction: float,
                   freshness_trials: int) -> dict:
    """The full sweep for one serving config: frontier → knee → the
    80%-of-knee verification pass with freshness under load."""
    stack = Stack(cfg_name, step_sec, canary_fraction)
    try:
        frontier = []
        knee = None
        for rate in rates:
            row = _step(stack, f"{cfg_name}_{int(rate)}", rate,
                        step_sec, zipf, ingest_frac)
            frontier.append(row)
            if row["sustained"]:
                knee = rate
            else:
                break  # past the knee; higher rates only melt further
        out: dict = {
            "config": cfg_name,
            "step_sec": step_sec,
            "mixed_traffic": {
                "ingest_fraction": ingest_frac,
                "canary_fraction": (canary_fraction
                                    if stack.canary else 0.0),
                "foldins": True,
            },
            "frontier": frontier,
            "knee_qps": knee,
        }
        if knee is not None:
            fresh = _freshness_under_load(
                stack, cfg_name, 0.8 * knee, step_sec, zipf,
                freshness_trials)
            out["p99_at_80pct_knee_ms"] = (fresh.get("load") or {}
                                           ).get("p99_ms")
            out["freshness_under_load_ms"] = fresh.get("p50_ms")
            out["freshness"] = fresh
        if stack.router is not None:
            rs = stack.router.status()
            out["router"] = {
                "replicas": len(stack.router.members()),
                "vnodes": rs["ring"]["vnodes"],
                "retries": rs["retries"],
            }
        status = stack.status()
        overlap = (status.get("pipeline") or {}).get("overlap") or {}
        out["device_idle_fraction"] = overlap.get("deviceIdleFraction")
        stream = status.get("stream") or {}
        out["stream"] = {
            "eventsConsumed": stream.get("eventsConsumed"),
            "applies": stream.get("applies"),
            "canaryRejects": stream.get("canaryRejects"),
            "cursorLag": stream.get("cursorLag"),
        }
        # the fold-ins really ran WHILE we were measuring: more events
        # consumed than the seed corpus, at least one applied delta
        out["foldins_applied_under_load"] = bool(
            (stream.get("applies") or 0) >= 1
            and (stream.get("eventsConsumed") or 0)
            > stack.n_seed_events)
        out["slo_burning"] = (status.get("slo") or {}).get("burning")
        return out
    finally:
        stack.shutdown()


def measure(configs="host,staged,cached", rate_min: float = 8.0,
            rate_max: float = 128.0, step_sec: float = 4.0,
            zipf: float = 1.2, ingest_frac: float = 0.1,
            canary_fraction: float = 0.1,
            freshness_trials: int = 4) -> dict:
    """The whole harness (importable — bench.py embeds the result as
    the BENCH line's ``capacity`` block)."""
    import jax

    n_dev = len(jax.devices())
    rates = []
    r = rate_min
    while r <= rate_max:
        rates.append(float(r))
        r *= 2
    out: dict = {
        "bench": "load_harness",
        "device": jax.devices()[0].device_kind,
        "devices": n_dev,
        "step_sec": step_sec,
        "zipf": zipf,
        "rates": rates,
        "configs": {},
    }
    for name in [c.strip() for c in configs.split(",") if c.strip()]:
        if name in ("replicated", "sharded") and n_dev < 2:
            out["configs"][name] = {"skipped": f"needs >1 device, "
                                               f"have {n_dev}"}
            continue
        out["configs"][name] = measure_config(
            name, rates, step_sec, zipf, ingest_frac,
            canary_fraction, freshness_trials)
    return out


def measure_endpoints(endpoints, rate_min: float = 8.0,
                      rate_max: float = 128.0, step_sec: float = 4.0,
                      zipf: float = 1.2,
                      n_entities: int = N_SEED_USERS) -> dict:
    """External-fleet mode: the frontier sweep against already-running
    replicas, round-robin per request. Boots nothing and imports no
    jax — the replicas own the devices; this process is purely a
    coordinated-omission-safe traffic source."""
    targets = [e.strip() for e in endpoints if e.strip()]
    rates = []
    r = rate_min
    while r <= rate_max:
        rates.append(float(r))
        r *= 2
    frontier = []
    knee = None
    for rate in rates:
        n = max(int(rate * step_sec), 8)
        rng = np.random.default_rng(int(rate) + 17)
        users = sample_entities(rng, n_entities, n, zipf)
        sender = json_post_sender(
            0, "/queries.json",
            body_fn=lambda k: json.dumps(
                {"user": f"u{users[k]}", "num": 5}).encode(),
            check=expect_json_field("itemScores"),
            shed_status=(503,), endpoints=targets)
        stats, wall = run_load(sender, n,
                               int(min(64, max(8, rate // 2))),
                               rate_qps=rate)
        row = {
            "offered_qps": rate,
            "achieved_qps": (round(len(stats.lat) / wall, 1)
                             if wall > 0 else 0.0),
            "window_sec": round(wall, 2),
            **stats.summary(wall),
        }
        row.pop("qps", None)
        total = len(stats.lat) + len(stats.shed)
        row["sustained"] = bool(
            stats.lat
            and not stats.errors
            and row["achieved_qps"] >= SUSTAIN_FRAC * rate
            and len(stats.shed) <= SHED_FRAC * max(total, 1))
        if stats.errors:
            row["first_error"] = stats.errors[0][:160]
        frontier.append(row)
        if row["sustained"]:
            knee = rate
        else:
            break
    return {
        "bench": "load_harness",
        "mode": "endpoints",
        "endpoints": targets,
        "replicas": len(targets),
        "step_sec": step_sec,
        "zipf": zipf,
        "rates": rates,
        "frontier": frontier,
        "knee_qps": knee,
    }


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    argv = sys.argv[1:]

    def flag(name, default, cast=float):
        if name in argv:
            i = argv.index(name)
            v = cast(argv[i + 1])
            del argv[i:i + 2]
            return v
        return default

    ci = "--ci" in argv
    if ci:
        argv.remove("--ci")
    endpoints = flag("--endpoints", "", str)
    configs = flag("--configs",
                   "host,staged,cached,router", str)
    rate_min = flag("--rate-min", 8.0)
    rate_max = flag("--rate-max", 64.0 if ci else 128.0)
    step_sec = flag("--step-sec", 3.0 if ci else 4.0)
    zipf = flag("--zipf", 1.2)
    ingest_frac = flag("--ingest-frac", 0.1)
    canary = flag("--canary", 0.1)
    trials = flag("--freshness-trials", 3 if ci else 4, int)
    out_path = flag("--out", "", str)
    if argv:
        raise SystemExit(f"unknown arguments: {argv}")

    if endpoints:
        result = measure_endpoints(
            endpoints.split(","), rate_min=rate_min,
            rate_max=rate_max, step_sec=step_sec, zipf=zipf)
        result["measured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        if out_path:
            with open(out_path, "w", encoding="utf-8") as f:
                json.dump(result, f, indent=1)
        print(json.dumps(result))
        return 0 if result["knee_qps"] is not None else 1

    capacity = measure(configs=configs, rate_min=rate_min,
                       rate_max=rate_max, step_sec=step_sec,
                       zipf=zipf, ingest_frac=ingest_frac,
                       canary_fraction=canary,
                       freshness_trials=trials)
    capacity["measured_at"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    if out_path:
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(capacity, f, indent=1)
    print(json.dumps(capacity))
    # the harness itself only fails when NOTHING could be measured;
    # judgment lives in the committed gate (`ptpu slo check`)
    measured = [c for c in capacity["configs"].values()
                if c.get("knee_qps") is not None]
    return 0 if measured else 1


if __name__ == "__main__":
    sys.exit(main())
