"""CI smoke: boot a real engine server, fire queries, validate /metrics.

Deploys a toy synthetic-model engine on a loopback port (batched AND
unbatched), pushes queries through HTTP, then asserts:

- ``GET /metrics`` parses as Prometheus text format 0.0.4 (every
  non-comment line is ``name{labels} value``, every histogram's +Inf
  bucket equals its ``_count``)
- the query-latency histogram series recorded the traffic
- the per-phase, batch-occupancy, and queue-depth series exist
- ``/status.json`` carries ``compilesSinceWarm`` and the transfer-guard
  violation counter

Exit 0 on success; non-zero with a reason otherwise. Run on CPU:
``JAX_PLATFORMS=cpu python benchmarks/metrics_smoke.py``.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
import urllib.request
from datetime import datetime, timezone

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_METRIC_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'                      # name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'    # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (-?[0-9.eE+-]+|[+-]Inf|NaN)$')


def validate_exposition(text: str) -> None:
    """Line-grammar + histogram-consistency check of the 0.0.4 format."""
    assert text.endswith("\n"), "exposition must end with a newline"
    counts: dict = {}
    inf_buckets: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line.startswith("# TYPE"):
                parts = line.split()
                assert len(parts) == 4 and parts[3] in (
                    "counter", "gauge", "histogram", "summary",
                    "untyped"), f"bad TYPE line: {line!r}"
            continue
        assert _METRIC_LINE.match(line), f"bad metric line: {line!r}"
        name = line.split("{")[0].split(" ")[0]
        value = float(line.rsplit(" ", 1)[1].replace("+Inf", "inf"))
        if name.endswith("_count"):
            base_and_labels = line.rsplit(" ", 1)[0].replace(
                "_count", "", 1)
            counts[base_and_labels] = value
        if name.endswith("_bucket") and 'le="+Inf"' in line:
            key = (line.rsplit(" ", 1)[0]
                   .replace("_bucket", "", 1)
                   .replace(',le="+Inf"', "").replace('le="+Inf"', "")
                   .replace("{}", ""))
            inf_buckets[key] = value
    for key, v in inf_buckets.items():
        assert counts.get(key) == v, \
            f"histogram {key!r}: +Inf bucket {v} != _count {counts.get(key)}"


def fetch(port: int, path: str) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.read().decode("utf-8")


def boot_and_probe(batching: bool) -> None:
    import numpy as np

    from predictionio_tpu.controller import Context
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.data.storage.base import (
        STATUS_COMPLETED,
        EngineInstance,
    )
    from predictionio_tpu.models.als import ALSModel, ALSParams
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
        create_engine_server,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )

    rank, n_users, n_items = 8, 32, 64
    rng = np.random.default_rng(0)
    model = ALSModel(
        user_factors=rng.standard_normal((n_users, rank)).astype(
            np.float32),
        item_factors=rng.standard_normal((n_items, rank)).astype(
            np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "smoke"))
    ctx = Context(app_name="smoke", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="smoke", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="smoke", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    cfg = ServerConfig(batching=batching, max_batch=8,
                       batch_window_ms=2.0)
    qs = QueryServer(ctx, recommendation_engine(),
                     default_engine_params("smoke", rank=rank),
                     [model], inst, cfg)
    srv = create_engine_server(qs, host="127.0.0.1", port=0)
    srv.start_background()
    port = srv.port
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if json.loads(fetch(port, "/status.json")).get("servingWarm"):
                break
            time.sleep(0.2)
        else:
            raise RuntimeError("serving warmup did not finish")
        for i in range(12):
            body = json.dumps({"user": f"u{i % n_users}",
                               "num": 3}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=30).read()

        text = fetch(port, "/metrics")
        validate_exposition(text)
        mode = "batched" if batching else "unbatched"
        for series in ("pio_query_latency_seconds_bucket",
                       "pio_query_phase_seconds_bucket",
                       "pio_http_request_duration_seconds_bucket",
                       "pio_xla_compiles_total",
                       "pio_transfer_guard_violations_total",
                       "pio_compiles_since_warm"):
            assert series in text, f"[{mode}] missing series {series}"
        if batching:
            assert "pio_batch_occupancy_bucket" in text
            assert "pio_queue_depth_bucket" in text
        status = json.loads(fetch(port, "/status.json"))
        assert status["recompile"]["compilesSinceWarm"] is not None
        assert "transferGuardViolations" in status
        assert status["latency"]["count"] >= 12
        assert status["latency"]["p99"] is not None
        print(f"[{mode}] /metrics valid, "
              f"{len(text.splitlines())} exposition lines, "
              f"latency count={status['latency']['count']}")
    finally:
        srv.shutdown()


def main() -> int:
    boot_and_probe(batching=False)
    boot_and_probe(batching=True)
    print("metrics smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
