"""Data-path benchmark: event-log scan → RatingsCOO throughput.

Measures the VERDICT r1 top gap end to end on a MovieLens-20M-shaped
synthetic log in SQLite (the durable default backend):

- ``ingest``: bulk row ingest (one-time cost, executemany)
- ``encode``: first columnar read — sidecar delta encode (one-time)
- ``warm scan``: steady-state training read — mmap segments →
  filter pushdown → :func:`ratings_from_columnar` (what every
  ``ptpu train`` after the first pays)
- ``row path``: the round-1 per-event loop, for the same read, measured
  on a 1/20 subsample and scaled (it is ~two orders slower)

Usage: python benchmarks/data_path_bench.py [n_events] [--keep]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from predictionio_tpu.data.storage import App, EventFilter, Storage  # noqa: E402
from predictionio_tpu.data.store import EventStoreFacade  # noqa: E402
from predictionio_tpu.models.data import (  # noqa: E402
    ratings_from_columnar,
    ratings_from_events,
)

N_USERS = 138_000
N_ITEMS = 27_000


def build_db(path: str, n_events: int, seed: int = 7) -> Storage:
    """Synthetic rate-event log shaped like MovieLens-20M (zipf items)."""
    env = {
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": path,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
    }
    storage = Storage(env=env)
    if storage.apps().get_by_name("ml20m") is not None:
        return storage
    app_id = storage.apps().insert(App(0, "ml20m"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(seed)
    t0 = time.monotonic()
    conn = es.client.conn
    chunk = 500_000
    written = 0
    base_ms = 1_760_000_000_000
    while written < n_events:
        m = min(chunk, n_events - written)
        users = rng.integers(0, N_USERS, m)
        items = (rng.zipf(1.3, m) - 1) % N_ITEMS
        stars = rng.integers(1, 6, m).astype(np.float64)
        times = base_ms + rng.integers(0, 3_000_000_000, m)
        rows = [
            (f"e{written + j}", "rate", "user", f"u{users[j]}", "item",
             f"i{items[j]}", '{"rating": %.1f}' % stars[j],
             int(times[j]), "[]", None, int(times[j]))
            for j in range(m)
        ]
        with es.client.lock:
            conn.executemany(
                f"INSERT INTO events_{app_id} ({es.EVENT_COLS}) VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?)", rows)
            conn.commit()
        written += m
        print(f"  ingest {written}/{n_events} "
              f"({written / (time.monotonic() - t0):,.0f} ev/s)",
              flush=True)
    return storage


def build_segmentfs(path: str, n_events: int, seed: int = 7) -> Storage:
    """Same synthetic log via the shared-filesystem pod backend (events
    ingested through the public insert_batch API — segmentfs has no
    private fast lane)."""
    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event

    env = {
        "PIO_STORAGE_SOURCES_FS_TYPE": "segmentfs",
        "PIO_STORAGE_SOURCES_FS_PATH": path,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
    }
    storage = Storage(env=env)
    if storage.apps().get_by_name("ml20m") is not None:
        return storage
    app_id = storage.apps().insert(App(0, "ml20m"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(seed)
    t0 = time.monotonic()
    chunk = 100_000
    written = 0
    while written < n_events:
        m = min(chunk, n_events - written)
        users = rng.integers(0, N_USERS, m)
        items = (rng.zipf(1.3, m) - 1) % N_ITEMS
        stars = rng.integers(1, 6, m).astype(np.float64)
        es.insert_batch(
            [Event(event="rate", entity_type="user",
                   entity_id=f"u{users[j]}", target_entity_type="item",
                   target_entity_id=f"i{items[j]}",
                   properties=DataMap({"rating": float(stars[j])}))
             for j in range(m)], app_id)
        written += m
        print(f"  ingest {written}/{n_events} "
              f"({written / (time.monotonic() - t0):,.0f} ev/s)",
              flush=True)
    return storage


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000_000
    keep = "--keep" in sys.argv
    backend = "segmentfs" if "--backend=segmentfs" in sys.argv else "sqlite"
    root = os.environ.get("PIO_BENCH_DIR", "/tmp/pio_datapath_bench")
    os.makedirs(root, exist_ok=True)
    db = os.path.join(root, f"bench_{n}.db")

    print(f"== ingest ({n:,} events, {backend}) ==", flush=True)
    t0 = time.monotonic()
    if backend == "segmentfs":
        storage = build_segmentfs(os.path.join(root, f"segfs_{n}"), n)
    else:
        storage = build_db(db, n)
    ingest_s = time.monotonic() - t0
    fac = EventStoreFacade(storage)

    print("== first columnar read (sidecar encode, training flags) ==",
          flush=True)
    t0 = time.monotonic()
    batch = fac.find_columnar("ml20m", entity_type="user",
                              target_entity_type="item",
                              event_names=["rate", "buy"],
                              ordered=False, with_props=False)
    encode_s = time.monotonic() - t0
    assert batch.n == n, (batch.n, n)

    print("== props upgrade (first props-wanting read) ==", flush=True)
    t0 = time.monotonic()
    fac.find_columnar("ml20m", entity_type="user",
                      target_entity_type="item",
                      event_names=["rate", "buy"])
    props_upgrade_s = time.monotonic() - t0

    print("== warm scans (steady-state training read) ==", flush=True)
    warm = []
    for _ in range(3):
        t0 = time.monotonic()
        batch = fac.find_columnar("ml20m", entity_type="user",
                                  target_entity_type="item",
                                  event_names=["rate", "buy"],
                                  ordered=False, with_props=False)
        coo, user_ids, item_ids = ratings_from_columnar(batch)
        warm.append(time.monotonic() - t0)
    warm_s = min(warm)
    assert len(coo.users) == n

    print("== row path (1/20 subsample, scaled) ==", flush=True)
    sub = max(n // 20, 1)
    t0 = time.monotonic()
    it = storage.events().find(
        1, None, EventFilter(entity_type="user", target_entity_type="item",
                             event_names=["rate", "buy"], limit=sub))
    coo_r, _, _ = ratings_from_events(it)
    row_s_scaled = (time.monotonic() - t0) * (n / sub)

    result = {
        "backend": backend,
        "n_events": n,
        "ingest_events_per_s": round(n / ingest_s),
        "encode_s": round(encode_s, 2),
        "encode_events_per_s": round(n / encode_s),
        "props_upgrade_s": round(props_upgrade_s, 2),
        "warm_scan_s": round(warm_s, 3),
        "warm_scan_events_per_s": round(n / warm_s),
        "row_path_events_per_s": round(n / row_s_scaled),
        "speedup_vs_row_path": round(row_s_scaled / warm_s, 1),
        "nnz_check": int(len(coo.users)),
    }
    if backend == "segmentfs":
        # the pod payoff: a SECOND host mmaps the shared sidecar instead
        # of re-parsing jsonl (fresh client = fresh process-local caches)
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        es2 = SegmentFSEventStore(
            SegmentFSClient(os.path.join(root, f"segfs_{n}")))
        t0 = time.monotonic()
        b2 = es2.find_columnar(1, ordered=False, with_props=False)
        coo2, _, _ = ratings_from_columnar(b2)
        result["second_host_first_read_s"] = round(
            time.monotonic() - t0, 3)
        assert len(coo2.users) == n
    print(json.dumps(result))
    if not keep:
        storage.close()
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
