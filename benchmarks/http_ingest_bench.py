"""HTTP-path event-ingestion benchmark (VERDICT r1 "What's weak" #6).

Drives the REAL event server over HTTP (not the storage layer): N client
threads posting single events and ≤50-event batches
(the reference's cap, ``EventServer.scala:66,349``), SQLite backend.

Usage: python benchmarks/http_ingest_bench.py [n_events] [n_threads]
Prints one JSON line.
"""

import json
import os
import sys
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def post(url: str, payload) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def main() -> None:
    n_events = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    n_threads = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    import tempfile

    from predictionio_tpu.data.storage.base import AccessKey, App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.server.eventserver import create_event_server

    root = tempfile.mkdtemp(prefix="http_ingest_")
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": os.path.join(root, "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
    })
    app_id = storage.apps().insert(App(id=0, name="ingest"))
    storage.access_keys().insert(AccessKey(key="bkey", app_id=app_id))
    storage.events().init(app_id)

    server = create_event_server(storage, host="127.0.0.1", port=0)
    server.start_background()
    base = f"http://127.0.0.1:{server.port}"

    def run_phase(batch_size: int, total: int) -> float:
        per_thread = total // n_threads
        errs = []

        def worker(tid: int):
            try:
                if batch_size == 1:
                    for i in range(per_thread):
                        out = post(f"{base}/events.json?accessKey=bkey", {
                            "event": "rate", "entityType": "user",
                            "entityId": f"u{tid}-{i}",
                            "targetEntityType": "item",
                            "targetEntityId": f"i{i % 97}",
                            "properties": {"rating": float(i % 5 + 1)},
                            "eventTime": "2026-01-01T00:00:00.000Z"})
                        assert "eventId" in out, out
                else:
                    for s in range(0, per_thread, batch_size):
                        m = min(batch_size, per_thread - s)
                        out = post(
                            f"{base}/batch/events.json?accessKey=bkey",
                            [{"event": "rate", "entityType": "user",
                              "entityId": f"u{tid}-{s + i}",
                              "targetEntityType": "item",
                              "targetEntityId": f"i{i % 97}",
                              "eventTime": "2026-01-01T00:00:00.000Z"}
                             for i in range(m)])
                        assert all(r["status"] == 201 for r in out), out[:2]
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.monotonic() - t0
        if errs:
            raise RuntimeError(errs[:3])
        return (per_thread * n_threads) / dt

    single_rps = run_phase(1, max(n_events // 4, n_threads))
    batch_rps = run_phase(50, n_events)
    server.shutdown()

    print(json.dumps({
        "backend": "sqlite",
        "threads": n_threads,
        "single_events_per_s": round(single_rps, 1),
        "batch50_events_per_s": round(batch_rps, 1),
    }))


if __name__ == "__main__":
    main()
