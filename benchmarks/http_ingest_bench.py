"""HTTP-path event-ingestion benchmark (VERDICT r1 "What's weak" #6).

Drives the REAL event server over HTTP (not the storage layer): N client
threads posting single events and ≤50-event batches
(the reference's cap, ``EventServer.scala:66,349``), SQLite backend —
through the shared ``_loadgen`` worker pool (keep-alive connections,
one definition of the pool/accounting across the serving, ingest, and
mixed-traffic benchmarks).

``--columnar`` adds the ISSUE-19 race: the same event stream shipped
as zero-copy npz column blocks to ``/columnar/events.npz`` — one
block per POST, no per-event JSON on either side of the wire — and
reports the block lane's events/s next to the 50-event JSON batches
(acceptance floor: ≥ 5×, docs/streaming.md).

Usage: python benchmarks/http_ingest_bench.py [n_events] [n_threads]
                                              [--columnar]
Prints one JSON line.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _loadgen import json_post_sender, run_load  # noqa: E402


def event_body(entity: str, item: int) -> dict:
    return {"event": "rate", "entityType": "user", "entityId": entity,
            "targetEntityType": "item", "targetEntityId": f"i{item}",
            "properties": {"rating": float(item % 5 + 1)},
            "eventTime": "2026-01-01T00:00:00.000Z"}


def _check_single(status: int, payload: bytes):
    if status != 201:
        return f"status {status}"
    if b"eventId" not in payload:
        return f"no eventId in {payload[:120]!r}"
    return None


def _check_batch(status: int, payload: bytes):
    if status != 200:
        return f"status {status}"
    try:
        rows = json.loads(payload)
    except ValueError as e:
        return f"unparseable batch response: {e}"
    if not all(r.get("status") == 201 for r in rows):
        return f"batch rejects: {rows[:2]}"
    return None


def _check_columnar(status: int, payload: bytes):
    if status != 201:
        return f"status {status}"
    if b"accepted" not in payload:
        return f"no accepted count in {payload[:120]!r}"
    return None


def main() -> None:
    argv = [a for a in sys.argv[1:] if a != "--columnar"]
    columnar = "--columnar" in sys.argv[1:]
    n_events = int(argv[0]) if len(argv) > 0 else 20_000
    n_threads = int(argv[1]) if len(argv) > 1 else 8

    import tempfile

    from predictionio_tpu.data.storage.base import AccessKey, App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.server.eventserver import create_event_server

    root = tempfile.mkdtemp(prefix="http_ingest_")
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": os.path.join(root, "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
    })
    app_id = storage.apps().insert(App(id=0, name="ingest"))
    storage.access_keys().insert(AccessKey(key="bkey", app_id=app_id))
    storage.events().init(app_id)

    server = create_event_server(storage, host="127.0.0.1", port=0)
    server.start_background()
    port = server.port

    # phase 1: single-event POSTs
    n_single = max(n_events // 4, n_threads)
    single_sender = json_post_sender(
        port, "/events.json?accessKey=bkey",
        body_fn=lambda k: json.dumps(
            event_body(f"u{k}", k % 97)).encode(),
        check=_check_single, shed_status=())
    stats, wall = run_load(single_sender, n_single, n_threads)
    if stats.errors:
        raise RuntimeError(stats.errors[:3])
    single_rps = len(stats.lat) / wall

    # phase 2: 50-event batches (the reference's cap)
    batch = 50
    n_batches = max(n_events // batch, 1)
    batch_sender = json_post_sender(
        port, "/batch/events.json?accessKey=bkey",
        body_fn=lambda k: json.dumps(
            [event_body(f"b{k}-{i}", i % 97)
             for i in range(batch)]).encode(),
        check=_check_batch, shed_status=())
    stats, wall = run_load(batch_sender, n_batches, n_threads)
    if stats.errors:
        raise RuntimeError(stats.errors[:3])
    batch_rps = (len(stats.lat) * batch) / wall

    out = {
        "backend": "sqlite",
        "threads": n_threads,
        "single_events_per_s": round(single_rps, 1),
        "batch50_events_per_s": round(batch_rps, 1),
    }

    if columnar:
        # phase 3: the same stream as npz column blocks — encode once
        # per block size up front (the client-side cost the race is
        # about is the WIRE + server path, and a real producer amortizes
        # encoding across its buffering window)
        from predictionio_tpu.data.columnar import columnar_from_events
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.wire import batch_to_npz

        block = 2_000
        n_blocks = max(n_events // block, 1)
        payloads = [batch_to_npz(columnar_from_events(
            Event.from_json(event_body(f"c{j}-{i}", i % 97))
            for i in range(block))) for j in range(min(n_blocks, 4))]
        block_sender = json_post_sender(
            port, "/columnar/events.npz?accessKey=bkey",
            body_fn=lambda k: payloads[k % len(payloads)],
            check=_check_columnar, shed_status=(),
            content_type="application/octet-stream")
        stats, wall = run_load(block_sender, n_blocks, n_threads)
        if stats.errors:
            raise RuntimeError(stats.errors[:3])
        block_rps = (len(stats.lat) * block) / wall
        out["ingest_block_events_per_s"] = round(block_rps, 1)
        out["block_size"] = block
        # the acceptance floor (≥5×) is against the per-event JSON
        # path; the batch50 ratio is informational
        out["columnar_speedup_vs_single"] = round(
            block_rps / max(single_rps, 1e-9), 2)
        out["columnar_speedup_vs_batch50"] = round(
            block_rps / max(batch_rps, 1e-9), 2)

    server.shutdown()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
