"""Ablation profile of the FULL ALS iteration at bench scale.

iter_scaling (round 4) split the iteration into a rank-independent
~0.4s component and an r² math term — but per-stage microbenches
(gram_profile) show every stage at multi-TF/s on small batches, so the
bound hides at FULL problem scale. This probe times the real iteration
body (both halves, real bucketed layout, 20M entries) with stages
successively disabled, using gram_profile's DCE-proof fori_loop
technique. The difference between adjacent stages is that stage's true
full-scale cost, tunnel dispatch excluded.

Stages (cumulative): gather → gram → +rhs → +solve → full (+scatter).
Plus isolated: a standalone solve on a random SPD batch.

Usage: python benchmarks/iter_ablation.py
Env:   ABL_NNZ=20000000 ABL_RANK=64 ABL_REPS=2 ABL_INNER=3
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    nnz = int(os.environ.get("ABL_NNZ", "20000000"))
    rank = int(os.environ.get("ABL_RANK", "64"))
    reps = int(os.environ.get("ABL_REPS", "2"))
    K = int(os.environ.get("ABL_INNER", "3"))

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.als import (
        ALSParams,
        RatingsCOO,
        _auto_block_rows,
        pack_ratings,
    )
    from predictionio_tpu.ops.gram import gram_dispatch
    from predictionio_tpu.ops.ragged import BucketedHistories
    from predictionio_tpu.ops.solve import gramian, solve_spd_batch

    n_users = max(int(138_000 * nnz / 20_000_000), 64)
    n_items = max(int(27_000 * nnz / 20_000_000), 64)
    items = (np.random.default_rng(1).zipf(1.3, size=nnz)
             % n_items).astype(np.int32)
    users = np.random.default_rng(0).integers(
        0, n_users, nnz).astype(np.int32)
    ratings = RatingsCOO(users, items, np.ones(nnz, np.float32),
                         n_users, n_items)
    params = ALSParams(rank=rank, num_iterations=1,
                       implicit_prefs=True, alpha=40.0, reg=0.01,
                       seed=3)
    packed = pack_ratings(ratings, params)
    kinds = {s: ("bucket" if isinstance(
        getattr(packed, f"{s}_h"), BucketedHistories) else "pad")
        for s in ("user", "item")}
    print(json.dumps({"layout": kinds, "nnz": nnz, "rank": rank}),
          flush=True)

    uh = packed.blocked("user", 1, None)
    ih = packed.blocked("item", 1, None)
    rng = np.random.default_rng(2)
    key = jax.random.key(3)
    ku, ki = jax.random.split(key)

    def rows_padded(lay):
        if "buckets" in lay:
            return lay["n_rows_padded"]
        d, n_per, _ = lay["idx"].shape
        return d * n_per

    nu, ni = rows_padded(uh), rows_padded(ih)
    U = jax.random.normal(ku, (nu, rank), jnp.float32) * 0.01
    V = jax.random.normal(ki, (ni, rank), jnp.float32) * 0.01

    def buckets_of(lay, h):
        if "buckets" in lay:
            return list(lay["buckets"]), True
        d, n_per, L = lay["idx"].shape
        block = _auto_block_rows(n_per, L, rank)
        return [{"idx": lay["idx"], "val": lay["val"],
                 "cnt": lay["cnt"], "rid": None,
                 "block": block}], False

    def half(fixed, out0, lay, stage):
        """The real half-iteration body with later stages disabled.
        Returns (out, acc); acc folds every produced value so nothing
        is DCE'd."""
        G = gramian(fixed)
        acc = jnp.float32(0.0)
        out = out0
        bks, is_bucket = buckets_of(lay, None)
        for b in bks:
            d, n_per, L = b["idx"].shape
            block = b.get("block") or _auto_block_rows(n_per, L, rank)
            parts = []
            for s in range(0, n_per, block):
                e = min(s + block, n_per)
                idx = b["idx"][:, s:e]
                val = b["val"][:, s:e]
                cnt = b["cnt"][:, s:e]
                Lb = idx.shape[-1]
                valid = (jnp.arange(Lb)[None, None, :]
                         < cnt[:, :, None]).astype(jnp.float32)
                F = fixed[idx]
                if stage == "gather":
                    acc += jnp.sum(F)
                    continue
                c1 = params.alpha * val * valid
                A = G[None, None] + gram_dispatch(F, c1, mode="einsum")
                if stage == "gram":
                    acc += jnp.sum(A)
                    continue
                bv = jnp.einsum("dnlr,dnl->dnr", F, (c1 + 1.0) * valid)
                if stage == "gramrhs":
                    acc += jnp.sum(A) + jnp.sum(bv)
                    continue
                A = A + params.reg * jnp.eye(rank, dtype=A.dtype)
                new = solve_spd_batch(A, bv)
                if stage == "solve":
                    acc += jnp.sum(new)
                    continue
                parts.append(new)
            if stage in ("gather", "gram", "gramrhs", "solve"):
                continue
            new = parts[0] if len(parts) == 1 \
                else jnp.concatenate(parts, axis=1)
            if is_bucket:
                out = out.at[b["rid"]].set(
                    new.reshape(d * n_per, rank), mode="drop",
                    unique_indices=True)
            else:
                out = new.reshape(d * n_per, rank)
        return out, acc

    def iteration(U0, V0, stage):
        u_out, acc_u = half(V0, jnp.zeros_like(U0), uh, stage)
        fixed_next = u_out if stage == "full" else V0
        v_out, acc_v = half(
            (U0 if stage != "full" else u_out),
            jnp.zeros_like(V0), ih, stage)
        return (jnp.sum(u_out) + jnp.sum(v_out) + acc_u + acc_v
                if stage == "full"
                else acc_u + acc_v + jnp.sum(fixed_next[0, 0]))

    def sync(x):
        np.asarray(jax.device_get(jnp.ravel(x)[:1]))

    # empty-dispatch baseline
    _zero = jax.jit(lambda x: x + 1.0)
    z = jnp.float32(0.0)
    _zero(z)
    sync(_zero(z))
    t_zero = float("inf")
    for _ in range(max(reps, 3)):
        t0 = time.monotonic()
        sync(_zero(z))
        t_zero = min(t_zero, time.monotonic() - t0)
    print(json.dumps({"stage": "dispatch_baseline",
                      "ms": round(t_zero * 1e3, 1)}), flush=True)

    def timed_stage(stage):
        def looped(U0, V0):
            def body(_i, carry):
                return iteration(U0 + carry * 1e-30,
                                 V0 + carry * 1e-30, stage)
            return jax.lax.fori_loop(0, K, body, jnp.float32(0.0))

        lfn = jax.jit(looped)
        try:
            lfn(U, V)
            sync(lfn(U, V))
        except Exception as e:  # noqa: BLE001 — report, keep going
            print(json.dumps({"stage": stage,
                              "error": str(e)[:200]}), flush=True)
            return None
        best = float("inf")
        for _ in range(reps):
            t0 = time.monotonic()
            sync(lfn(U, V))
            best = min(best, time.monotonic() - t0)
        dt = (best - t_zero) / K
        print(json.dumps({"stage": stage,
                          "s_per_iter": round(dt, 4)}), flush=True)
        return dt

    known = ("gather", "gram", "gramrhs", "solve", "full")
    stages = os.environ.get("ABL_STAGES", ",".join(known)).split(",")
    for stage in stages:
        # an unknown name would trace the full body but fold NOTHING
        # into the carry — XLA then eliminates all the work and the
        # "measurement" is the dispatch baseline wearing a stage label
        if stage not in known:
            print(json.dumps({"stage": stage,
                              "error": f"unknown stage (known: {known})"
                              }), flush=True)
            continue
        timed_stage(stage)

    # isolated: solve on a random SPD batch the size of both sides
    B = nu + ni
    M = jnp.asarray(rng.standard_normal((B, rank, rank)),
                    jnp.float32) * 0.1
    eye = jnp.eye(rank, dtype=jnp.float32)

    def solve_only(Ms):
        A = jnp.einsum("brs,bts->brt", Ms, Ms) + eye[None]
        return solve_spd_batch(A, Ms[:, :, 0])

    def looped_solve(Ms):
        def body(_i, carry):
            return jnp.sum(solve_only(Ms + carry * 1e-30)).astype(
                jnp.float32)
        return jax.lax.fori_loop(0, K, body, jnp.float32(0.0))

    lfn = jax.jit(looped_solve)
    lfn(M)
    sync(lfn(M))
    best = float("inf")
    for _ in range(reps):
        t0 = time.monotonic()
        sync(lfn(M))
        best = min(best, time.monotonic() - t0)
    print(json.dumps({"stage": "solve_isolated", "batch": int(B),
                      "s": round((best - t_zero) / K, 4)}), flush=True)


if __name__ == "__main__":
    main()
