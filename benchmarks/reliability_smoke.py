"""Elastic-reliability failure drills (ISSUE 11) — the CI gate for
docs/reliability.md, and the source of the BENCH line's ``rto_ms``.

Two drills, both against REAL failure mechanics (fault registry +
``os._exit`` — no mocks):

1. **Train drill (2-process CPU mesh, gloo):** two worker processes
   join one JAX system, train mesh-sharded ALS with the distributed
   checkpointer, and process 1 is crash-injected (``os._exit(42)`` —
   the ``kill -9``/preemption simulator) at the entry of its 3rd save,
   leaving a TORN step on disk. The parent reaps both processes,
   relaunches the pair, and the run must resume from the last
   COMMITTED step and finish with factors BITWISE equal to an
   uninterrupted 2-process run. ``train_resume_ms`` measures
   relaunch→trained (the restart-side recovery cost).

2. **Serving drill (replicated lanes, real HTTP):** a replicated
   multi-lane server takes steady query load while lane 1 is
   fault-injected dead. Required: ZERO failed in-deadline queries
   (dispatch fails over to surviving lanes during detection), a
   visible degraded block on /status.json, ``pio_lane_restarts_total``
   counting the recovery, and ``rto_ms`` — lane-death→lane-rejoined,
   measured from the degraded transitions.

Prints one JSON line; exits non-zero on any violation. Needs >= 2
visible devices for the serving drill (CI forces host devices via
XLA_FLAGS); with one device that drill reports skipped=true.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request
from datetime import datetime, timezone

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_TRAIN_WORKER = textwrap.dedent("""
    import json, os, sys
    import numpy as np

    pid = int(sys.argv[1])
    port = sys.argv[2]
    ckdir = sys.argv[3]
    outdir = sys.argv[4]
    mode = sys.argv[5]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    if mode == "crash" and pid == 1:
        # preemption: process 1 vanishes at the entry of its 3rd save,
        # leaving step 3 TORN (its shards never written, no commit
        # marker) — the restart must fall back to committed step 2
        os.environ["PTPU_FAULTS"] = "checkpoint.save=crash,after=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=pid)
    assert jax.process_count() == 2

    from jax.sharding import NamedSharding, PartitionSpec as P
    from predictionio_tpu.models.als import (
        ALSParams, RatingsCOO, pack_ratings, train_als)
    from predictionio_tpu.parallel.multihost import global_mesh

    rng = np.random.default_rng(17)
    nnz, n_users, n_items = 800, 48, 32
    ratings = RatingsCOO(
        rng.integers(0, n_users, nnz).astype(np.int32),
        rng.integers(0, n_items, nnz).astype(np.int32),
        rng.random(nnz).astype(np.float32) * 4 + 1,
        n_users, n_items)
    mesh = global_mesh(data=8)
    params = ALSParams(rank=4, num_iterations=6, reg=0.05, seed=11)
    packed = pack_ratings(ratings, params, mesh)
    U, V = train_als(ratings, params, mesh=mesh, packed=packed,
                     checkpoint_dir=ckdir, checkpoint_every=1)

    rep = jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))
    if pid == 0:
        np.savez(os.path.join(outdir, f"factors_{mode}.npz"),
                 U=np.asarray(rep(U).addressable_data(0)),
                 V=np.asarray(rep(V).addressable_data(0)))
        json.dump({"ok": True},
                  open(os.path.join(outdir, f"ok_{mode}.json"), "w"))
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_pair(workdir: str, ckdir: str, mode: str, tag: str):
    worker = os.path.join(workdir, "drill_worker.py")
    with open(worker, "w") as f:
        f.write(_TRAIN_WORKER)
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PTPU_FAULTS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    return [subprocess.Popen(
        [sys.executable, worker, str(i), str(port), ckdir, workdir,
         mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)], tag


def train_drill(workdir: str) -> dict:
    """kill -9 one of two mesh processes mid-save → resume-from-commit
    parity (module docstring, drill 1)."""
    out: dict = {}
    os.makedirs(workdir, exist_ok=True)
    ck_ref = os.path.join(workdir, "ck_ref")
    ck_crash = os.path.join(workdir, "ck_crash")

    # uninterrupted 2-process reference
    procs, _ = _spawn_pair(workdir, ck_ref, "ref", "ref")
    for p in procs:
        stdout, _ = p.communicate(timeout=300)
        if p.returncode != 0:
            out["error"] = ("reference run failed: "
                            + stdout.decode()[-1500:])
            return out

    # crash-injected run: p1 exits 42 during save 3; p0 is left
    # waiting on a dead peer and gets reaped by the drill (the
    # surviving host of a preempted pair is torn down by the platform)
    procs, _ = _spawn_pair(workdir, ck_crash, "crash", "crash")
    p1_out, _ = procs[1].communicate(timeout=300)
    out["crash_exit_code"] = procs[1].returncode
    try:
        procs[0].wait(timeout=20)
    except subprocess.TimeoutExpired:
        procs[0].kill()  # the kill -9 of the surviving peer
        procs[0].wait(timeout=30)
    out["crash_injected"] = procs[1].returncode == 42
    if not out["crash_injected"]:
        out["error"] = "no injected crash: " + p1_out.decode()[-1500:]
        return out

    # the torn step is on disk (shards at most, never a commit marker);
    # committed steps end at 2
    from predictionio_tpu.workflow.checkpoint import (
        DistributedCheckpointer,
    )

    ck = DistributedCheckpointer(ck_crash, process_index=0,
                                 process_count=2)
    committed = ck.all_steps()
    out["committed_steps"] = committed
    out["resumed_from_step"] = max(committed) if committed else 0
    out["committed_before_crash"] = bool(committed) \
        and max(committed) == 2

    # relaunch the pair: resume from the last committed step
    t0 = time.monotonic()
    procs, _ = _spawn_pair(workdir, ck_crash, "resumed", "resumed")
    for p in procs:
        stdout, _ = p.communicate(timeout=300)
        if p.returncode != 0:
            out["error"] = ("resume run failed: "
                            + stdout.decode()[-1500:])
            return out
    out["train_resume_ms"] = round((time.monotonic() - t0) * 1000, 1)

    ref = np.load(os.path.join(workdir, "factors_ref.npz"))
    res = np.load(os.path.join(workdir, "factors_resumed.npz"))
    out["factors_bitwise_equal"] = bool(
        np.array_equal(ref["U"], res["U"])
        and np.array_equal(ref["V"], res["V"]))
    out["ok"] = out["crash_injected"] and out["committed_before_crash"] \
        and out["factors_bitwise_equal"]
    return out


# ---------------------------------------------------------------------------
# serving drill
# ---------------------------------------------------------------------------

def _call(port, method, path, body=None, timeout=60):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else (
        b"" if method == "POST" else None)
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def serving_drill(duration_s: float = 4.0) -> dict:
    """Kill a replicated serving lane under load over real HTTP
    (module docstring, drill 2); returns checks + rto_ms."""
    import jax

    from predictionio_tpu import faults
    from predictionio_tpu.controller import Context
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.data.storage.base import (
        STATUS_COMPLETED,
        EngineInstance,
    )
    from predictionio_tpu.models.als import ALSModel, ALSParams
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
        create_engine_server,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )

    out: dict = {}
    if len(jax.devices()) < 2:
        return {"skipped": True, "ok": True,
                "note": "one device visible; no lanes to kill (CI "
                        "forces host devices via XLA_FLAGS)"}

    rng = np.random.default_rng(1)
    n_users, n_items, rank = 2_000, 20_000, 16
    model = ALSModel(
        user_factors=jax.device_put(rng.standard_normal(
            (n_users, rank)).astype(np.float32)),
        item_factors=jax.device_put(rng.standard_normal(
            (n_items, rank)).astype(np.float32)),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "reldrill"))
    ctx = Context(app_name="reldrill", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="reldrill", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="reldrill", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    storage.engine_instances().insert(inst)
    qs = QueryServer(
        ctx, recommendation_engine(),
        default_engine_params("reldrill", rank=rank),
        [model], inst,
        ServerConfig(batching=True, max_batch=8, batch_window_ms=1.0,
                     serving_mode="replicated", warm_start=False,
                     queue_deadline_ms=30_000.0,
                     lane_fail_threshold=2,
                     lane_restart_backoff_ms=40.0))
    srv = create_engine_server(qs, "127.0.0.1", 0).start_background()
    n_lanes = len(qs.lane_models)
    out["lanes"] = n_lanes
    try:
        statuses: list = []
        statuses_lock = threading.Lock()
        stop = threading.Event()

        def load(i: int) -> None:
            k = 0
            while not stop.is_set():
                k += 1
                try:
                    code, _ = _call(srv.port, "POST", "/queries.json",
                                    {"user": f"u{(i * 97 + k) % 500}",
                                     "num": 5})
                except urllib.error.HTTPError as e:  # noqa: PERF203
                    code = e.code
                except Exception as e:  # noqa: BLE001
                    code = str(e)
                with statuses_lock:
                    statuses.append(code)

        import urllib.error

        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # steady state before the fault

        # kill lane 1: the next `lane_fail_threshold` dispatches on it
        # fail, then it is dead; the spent budget lets the FIRST
        # restart probe succeed — rto_ms is death→rejoined
        faults.inject("serving.lane", "error",
                      match={"lane": "1"}, times=2,
                      message="drill: lane 1 device lost")
        t_fault = time.monotonic()
        t_dead = t_recovered = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            _, status = _call(srv.port, "GET", "/status.json")
            degraded = status.get("degraded") or {}
            if t_dead is None and degraded.get("active"):
                t_dead = time.monotonic()
            if t_dead is not None and not degraded.get("active"):
                t_recovered = time.monotonic()
                break
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=30)

        out["detected_ms"] = (round((t_dead - t_fault) * 1000, 1)
                              if t_dead else None)
        out["rto_ms"] = (round((t_recovered - t_dead) * 1000, 1)
                         if t_dead and t_recovered else None)
        out["queries"] = len(statuses)
        out["failed_queries"] = sum(1 for s in statuses if s != 200)
        out["zero_failed_in_deadline"] = out["failed_queries"] == 0
        _, status = _call(srv.port, "GET", "/status.json")
        out["degraded_cleared"] = not (status.get("degraded")
                                       or {}).get("active", True)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=30) as resp:
            text = resp.read().decode()
        restarts = [ln for ln in text.splitlines()
                    if ln.startswith("pio_lane_restarts_total")
                    and 'lane="1"' in ln]
        out["lane_restart_counted"] = bool(
            restarts and float(restarts[0].rsplit(" ", 1)[1]) >= 1.0)
        out["fault_series_exported"] = \
            "pio_fault_injections_total" in text \
            and "pio_serving_degraded" in text
        out["ok"] = bool(
            out["zero_failed_in_deadline"] and out["rto_ms"] is not None
            and out["degraded_cleared"] and out["lane_restart_counted"]
            and out["queries"] > 20)
    finally:
        faults.clear()
        srv.shutdown()
    return out


def measure(duration_s: float = 4.0) -> dict:
    """The bench.py hook: the serving lane-kill drill's RTO on THIS
    process's devices (replicated lanes; needs >= 2)."""
    drill = serving_drill(duration_s)
    return {
        "rto_ms": drill.get("rto_ms"),
        "detected_ms": drill.get("detected_ms"),
        "zero_failed_in_deadline": drill.get("zero_failed_in_deadline"),
        "lanes": drill.get("lanes"),
        "skipped": drill.get("skipped", False),
        "ok": drill.get("ok", False),
    }


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    import tempfile

    with tempfile.TemporaryDirectory(prefix="reliability_drill_") as d:
        train = train_drill(d)
    serving = serving_drill()
    ok = bool(train.get("ok")) and bool(serving.get("ok"))
    print(json.dumps({"bench": "reliability_smoke", "ok": ok,
                      "train_drill": train,
                      "serving_drill": serving}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
