"""Stage profile of the ALS half-step on the attached device.

Answers the MFU question with measurements instead of guesses
(VERDICT r2 weak #2: the whole-iteration number alone cannot say
whether the bound is the gather, the gram einsum, the solves, or the
scatters). For the bench shape (and a rank sweep) it times, each
hard-synced via a device→host transfer:

- ``gather``: F = fixed[indices] materialization alone
- ``gram_einsum``: baseline batched weighted gram from pre-gathered F
- ``gram_pair``: the 2-rows-per-MXU-tile packing (ops/gram.py)
- ``gram_fused``/``gram_pair_fused``: gather + gram in ONE jit (what
  the half-step actually runs — XLA may fuse the gather)
- ``solve``: the Pallas lane-batched Cholesky on [B, r, r]
- bf16 variants of the gram stages

Prints one JSON line per (rank, stage).

Usage: python benchmarks/gram_profile.py [B] [L]
Env:   GRAM_RANKS="32,64,128", GRAM_REPS=3
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    L = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    ranks = [int(r) for r in
             os.environ.get("GRAM_RANKS", "32,64,128").split(",")]
    reps = int(os.environ.get("GRAM_REPS", "3"))
    n_fixed = 140_000

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    from predictionio_tpu.ops.gram import gram_pairs, gram_weighted
    from predictionio_tpu.ops.solve import solve_spd_batch

    dev = jax.devices()[0].device_kind
    rng = np.random.default_rng(0)
    idx_h = rng.integers(0, n_fixed, (1, B, L)).astype(np.int32)
    w_h = rng.random((1, B, L)).astype(np.float32)

    def sync(x):
        np.asarray(jax.device_get(jnp.ravel(x)[:1]))

    def timeit(fn, *args):
        fn(*args)  # compile + warm
        sync(fn(*args))
        best = float("inf")
        for _ in range(reps):
            t0 = time.monotonic()
            out = fn(*args)
            sync(out)
            best = min(best, time.monotonic() - t0)
        return best

    for r in ranks:
        fixed = jnp.asarray(
            rng.standard_normal((n_fixed, r)).astype(np.float32))
        idx = jnp.asarray(idx_h)
        w = jnp.asarray(w_h)

        gather = jax.jit(lambda f, i: f[i])
        F = gather(fixed, idx)
        F.block_until_ready()

        stages = {
            "gather": (gather, fixed, idx),
            "gram_einsum": (jax.jit(gram_weighted), F, w),
            "gram_pair": (jax.jit(gram_pairs), F, w),
            "gram_einsum_bf16": (
                jax.jit(lambda F, w: gram_weighted(F, w, bf16=True)),
                F, w),
            "gram_pair_bf16": (
                jax.jit(lambda F, w: gram_pairs(F, w, bf16=True)),
                F, w),
            "gram_fused": (
                jax.jit(lambda f, i, w: gram_weighted(f[i], w)),
                fixed, idx, w),
            "gram_pair_fused": (
                jax.jit(lambda f, i, w: gram_pairs(f[i], w)),
                fixed, idx, w),
            "gram_pair_fused_bf16": (
                jax.jit(lambda f, i, w: gram_pairs(f[i], w, bf16=True)),
                fixed, idx, w),
        }
        # useful FLOPs of the weighted gram (the pair layout does 2x the
        # multiplies; report against USEFUL work so variants compare)
        gram_flops = 2.0 * B * L * r * r
        for name, (fn, *args) in stages.items():
            dt = timeit(fn, *args)
            flops = gram_flops if "gram" in name else None
            print(json.dumps({
                "stage": name, "rank": r, "B": B, "L": L,
                "ms": round(dt * 1e3, 3),
                "useful_tflops": (round(gram_flops / dt / 1e12, 3)
                                  if flops else None),
                "device": dev,
            }), flush=True)

        # fused VMEM-table kernel: the user-half-step scenario (gather
        # from the ITEM table, which fits VMEM at MovieLens shapes)
        from predictionio_tpu.ops.gram import (
            gram_table_pallas,
            gram_table_supported,
        )
        n_small = 27_000
        skip = None
        if not gram_table_supported():
            skip = "lowering unsupported on this backend"
        elif n_small * r * 4 > 12 * 2**20:
            skip = "table exceeds the VMEM budget at this rank"
        if skip is None:
            tab_s = jnp.asarray(rng.standard_normal(
                (n_small, r)).astype(np.float32))
            idx_s = jnp.asarray(
                rng.integers(0, n_small, (B, L)).astype(np.int32))
            w2 = jnp.asarray(w_h[0])
            try:
                # the support probe runs a tiny shape; a size-dependent
                # Mosaic failure here must not kill the remaining stages
                dt = timeit(jax.jit(gram_table_pallas), tab_s, idx_s,
                            w2, w2)
            except Exception as e:  # noqa: BLE001 — report, keep going
                skip = f"compile/run failed at real shape: {e}"[:300]
            else:
                print(json.dumps({
                    "stage": "gram_table_pallas", "rank": r, "B": B,
                    "L": L, "ms": round(dt * 1e3, 3),
                    "useful_tflops": round(gram_flops / dt / 1e12, 3),
                    "device": dev}), flush=True)
        if skip is not None:
            print(json.dumps({
                "stage": "gram_table_pallas", "rank": r,
                "skipped": skip, "device": dev}), flush=True)

        A_h = rng.standard_normal((B, r, r)).astype(np.float32)
        A = jnp.asarray(A_h @ A_h.transpose(0, 2, 1)
                        + 10.0 * np.eye(r, dtype=np.float32))
        b = jnp.asarray(rng.standard_normal((B, r)).astype(np.float32))
        dt = timeit(jax.jit(solve_spd_batch), A, b)
        print(json.dumps({
            "stage": "solve_spd", "rank": r, "B": B,
            "ms": round(dt * 1e3, 3), "device": dev}), flush=True)


if __name__ == "__main__":
    main()
