"""Stage profile of the ALS half-step on the attached device.

Answers the MFU question with measurements instead of guesses
(VERDICT r2 weak #2: the whole-iteration number alone cannot say
whether the bound is the gather, the gram einsum, the solves, or the
scatters). For the bench shape (and a rank sweep) it times, each
hard-synced via a device→host transfer:

- ``gather``: F = fixed[indices] materialization alone
- ``gram_einsum``: baseline batched weighted gram from pre-gathered F
- ``gram_pair``: the 2-rows-per-MXU-tile packing (ops/gram.py)
- ``gram_fused``/``gram_pair_fused``: gather + gram in ONE jit (what
  the half-step actually runs — XLA may fuse the gather)
- ``solve``: the Pallas lane-batched Cholesky on [B, r, r]
- bf16 variants of the gram stages

Prints one JSON line per (rank, stage).

Usage: python benchmarks/gram_profile.py [B] [L]
Env:   GRAM_RANKS="32,64,128", GRAM_REPS=3
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    pos = [a for a in sys.argv[1:] if not a.startswith("-")]
    B = int(pos[0]) if len(pos) > 0 else 4096
    L = int(pos[1]) if len(pos) > 1 else 256
    ranks = [int(r) for r in
             os.environ.get("GRAM_RANKS", "32,64,128").split(",")]
    reps = int(os.environ.get("GRAM_REPS", "3"))
    n_fixed = 140_000

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    from predictionio_tpu.ops.gram import gram_pairs, gram_weighted
    from predictionio_tpu.ops.solve import solve_spd_batch

    dev = jax.devices()[0].device_kind
    rng = np.random.default_rng(0)
    idx_h = rng.integers(0, n_fixed, (1, B, L)).astype(np.int32)
    w_h = rng.random((1, B, L)).astype(np.float32)

    def sync(x):
        np.asarray(jax.device_get(jnp.ravel(x)[:1]))

    # Per-dispatch overhead through a REMOTE device tunnel is large
    # (~80-100ms RTT measured on the axon tunnel — same order as the
    # ops themselves), so a single-op timing would measure the tunnel.
    # Each stage therefore runs K times inside ONE jitted fori_loop —
    # the carry feeds the next rep's input so nothing is DCE'd or
    # hoisted — and per-rep time is (T_loop - T_zero)/K with T_zero a
    # measured empty-dispatch baseline.
    K = int(os.environ.get("GRAM_INNER_REPS", "16"))

    def timeit(fn, *args):
        # every stage's first arg is a float array; the carry feeds it
        # so reps can't be hoisted, and the carry is a FULL-output sum
        # so XLA can't slice-sink/DCE the op being timed
        assert args[0].dtype.kind == "f", "first arg must be float"

        def looped(*a):
            def body(_i, carry):
                out = fn(a[0] + carry * 1e-30, *a[1:])
                return jax.tree_util.tree_reduce(
                    lambda acc, leaf: acc + jnp.sum(leaf).astype(
                        jnp.float32),
                    out, jnp.float32(0.0))

            return jax.lax.fori_loop(0, K, body, jnp.float32(0.0))

        lfn = jax.jit(looped)
        lfn(*args)  # compile + warm
        sync(lfn(*args))
        best = float("inf")
        for _ in range(reps):
            t0 = time.monotonic()
            out = lfn(*args)
            sync(out)
            best = min(best, time.monotonic() - t0)
        dt = (best - t_zero) / K
        if dt <= t_zero * 0.5 / K:
            return None  # below measurement resolution — don't report
        return dt

    # empty-dispatch baseline: same jit/sync plumbing, ~no compute
    _zero = jax.jit(lambda x: x + 1.0)
    z = jnp.float32(0.0)
    _zero(z)
    sync(_zero(z))
    t_zero = float("inf")
    for _ in range(max(reps, 3)):
        t0 = time.monotonic()
        sync(_zero(z))
        t_zero = min(t_zero, time.monotonic() - t0)
    print(json.dumps({"stage": "dispatch_baseline",
                      "ms": round(t_zero * 1e3, 3)}), flush=True)

    def emit(stage, r, dt, flops=None, **extra):
        """One output contract for every stage: ms/useful_tflops are
        null with below_resolution=true when dt is None."""
        print(json.dumps({
            "stage": stage, "rank": r, "B": B, "L": L,
            "ms": (round(dt * 1e3, 3) if dt else None),
            **({"below_resolution": True} if dt is None else {}),
            "useful_tflops": (round(flops / dt / 1e12, 3)
                              if dt and flops else None),
            "device": dev, **extra}), flush=True)

    for r in ranks:
        fixed = jnp.asarray(
            rng.standard_normal((n_fixed, r)).astype(np.float32))
        idx = jnp.asarray(idx_h)
        w = jnp.asarray(w_h)

        gather = jax.jit(lambda f, i: f[i])
        F = gather(fixed, idx)
        F.block_until_ready()

        stages = {
            "gather": (gather, fixed, idx),
            "gram_einsum": (jax.jit(gram_weighted), F, w),
            "gram_pair": (jax.jit(gram_pairs), F, w),
            "gram_einsum_bf16": (
                jax.jit(lambda F, w: gram_weighted(F, w, bf16=True)),
                F, w),
            "gram_pair_bf16": (
                jax.jit(lambda F, w: gram_pairs(F, w, bf16=True)),
                F, w),
            "gram_fused": (
                jax.jit(lambda f, i, w: gram_weighted(f[i], w)),
                fixed, idx, w),
            "gram_pair_fused": (
                jax.jit(lambda f, i, w: gram_pairs(f[i], w)),
                fixed, idx, w),
            "gram_fused_bf16": (
                jax.jit(lambda f, i, w: gram_weighted(f[i], w,
                                                      bf16=True)),
                fixed, idx, w),
            "gram_pair_fused_bf16": (
                jax.jit(lambda f, i, w: gram_pairs(f[i], w, bf16=True)),
                fixed, idx, w),
        }
        # useful FLOPs of the weighted gram (the pair layout does 2x the
        # multiplies; report against USEFUL work so variants compare)
        gram_flops = 2.0 * B * L * r * r
        stage_ms: dict[str, float] = {}
        for name, (fn, *args) in stages.items():
            dt = timeit(fn, *args)
            emit(name, r, dt,
                 flops=(gram_flops if "gram" in name else None))
            if dt is not None:
                stage_ms[name] = dt

        # fused VMEM-table kernel: the user-half-step scenario (gather
        # from the ITEM table, which fits VMEM at MovieLens shapes)
        from predictionio_tpu.ops.gram import (
            gram_table_pallas,
            gram_table_supported,
        )
        n_small = 27_000
        skip = None
        if not gram_table_supported():
            skip = "lowering unsupported on this backend"
        elif n_small * r * 4 > 12 * 2**20:
            skip = "table exceeds the VMEM budget at this rank"
        if skip is None:
            tab_s = jnp.asarray(rng.standard_normal(
                (n_small, r)).astype(np.float32))
            idx_s = jnp.asarray(
                rng.integers(0, n_small, (B, L)).astype(np.int32))
            w2 = jnp.asarray(w_h[0])
            try:
                # the support probe runs a tiny shape; a size-dependent
                # Mosaic failure here must not kill the remaining stages
                dt = timeit(jax.jit(gram_table_pallas), tab_s, idx_s,
                            w2, w2)
            except Exception as e:  # noqa: BLE001 — report, keep going
                skip = f"compile/run failed at real shape: {e}"[:300]
            else:
                emit("gram_table_pallas", r, dt, flops=gram_flops)
        if skip is not None:
            print(json.dumps({
                "stage": "gram_table_pallas", "rank": r,
                "skipped": skip, "device": dev}), flush=True)

        # the HBM-streaming fused gather+gram kernel (ISSUE 7,
        # ops/fused_gram.py): the table STAYS in HBM, rows DMA into
        # double-buffered VMEM tiles — the gram_mode="fused"
        # realization, raced here at the same shapes so --record can
        # persist a three-way winner
        from predictionio_tpu.ops.fused_gram import (
            fused_gram,
            fused_gram_supported,
        )

        if fused_gram_supported():
            for kname, tab in (
                    ("gram_kernel_fused", fixed),
                    ("gram_kernel_fused_bf16",
                     fixed.astype(jnp.bfloat16))):
                try:
                    dt = timeit(jax.jit(fused_gram), tab, idx, w, w)
                except Exception as e:  # noqa: BLE001 — keep going
                    print(json.dumps({
                        "stage": kname, "rank": r,
                        "skipped": str(e)[:300], "device": dev}),
                        flush=True)
                else:
                    emit(kname, r, dt, flops=gram_flops)
                    if dt is not None:
                        stage_ms[kname] = dt
        else:
            print(json.dumps({
                "stage": "gram_kernel_fused", "rank": r,
                "skipped": "lowering unsupported on this backend",
                "device": dev}), flush=True)

        # --record: persist the fused-variant winners (the half-step's
        # actual realization: gather+gram in one jit) into the
        # shape-keyed autotune table consulted by gram_mode="auto"
        if "--record" in sys.argv:
            from predictionio_tpu.ops.gram_autotune import record

            for bf16, ein, pair, kern in (
                    (False, "gram_fused", "gram_pair_fused",
                     "gram_kernel_fused"),
                    (True, "gram_fused_bf16", "gram_pair_fused_bf16",
                     "gram_kernel_fused_bf16")):
                if ein in stage_ms and pair in stage_ms:
                    cands = {"einsum": stage_ms[ein],
                             "pair": stage_ms[pair]}
                    if kern in stage_ms:
                        # the Pallas kernel joins the race wherever it
                        # lowered; its absence (no TPU, Mosaic too old)
                        # keeps the two-way einsum/pair contest
                        cands["fused"] = stage_ms[kern]
                    win = min(cands, key=cands.get)
                    measured = {
                        "source": "gram_profile",
                        "einsum_ms": round(stage_ms[ein] * 1e3, 3),
                        "pair_ms": round(stage_ms[pair] * 1e3, 3),
                    }
                    if kern in stage_ms:
                        measured["fused_ms"] = round(
                            stage_ms[kern] * 1e3, 3)
                    persisted = record(r, win, bf16=bf16,
                                       device_kind=dev,
                                       measured=measured)
                    print(json.dumps({
                        "recorded": win if persisted else None,
                        "persisted": persisted, "rank": r,
                        "bf16": bf16, "device": dev}), flush=True)

        A_h = rng.standard_normal((B, r, r)).astype(np.float32)
        A = jnp.asarray(A_h @ A_h.transpose(0, 2, 1)
                        + 10.0 * np.eye(r, dtype=np.float32))
        b = jnp.asarray(rng.standard_normal((B, r)).astype(np.float32))
        dt = timeit(jax.jit(solve_spd_batch), A, b)
        emit("solve_spd", r, dt)


if __name__ == "__main__":
    main()
