"""Independent implicit-ALS oracle, written from the published papers.

This file deliberately shares NO code, init scheme, or data structures
with ``predictionio_tpu/models/als.py`` (VERDICT r4 missing #1: every
prior parity check compared the framework against an oracle *built by
the same author with the same semantics* — numerics proof, not an
external anchor). Everything here is implemented from the public
algorithm descriptions:

- Hu, Koren, Volinsky, "Collaborative Filtering for Implicit Feedback
  Datasets" (ICDM 2008): preference p_ui = 1 when r_ui > 0, confidence
  c_ui = 1 + alpha * r_ui, alternating per-row solves of
  ``x_u = (Y^T Y + Y^T (C_u - I) Y + lambda I)^{-1} Y^T C_u p(u)``.
- Zhou, Wilkinson, Schreiber, Pan, "Large-scale Parallel Collaborative
  Filtering for the Netflix Prize" (AAIM 2008): ALS-WR's weighted-
  lambda regularization, scaling lambda by each row's observation
  count n_u — the scheme Spark MLlib's ALS implements
  (``regParam * n`` per normal equation; the reference template trains
  through exactly that MLlib ALS,
  ``tests/pio_tests/engines/recommendation-engine/src/main/scala/
  ALSAlgorithm.scala:75-85``).

Init follows MLlib's convention (random normal scaled by 1/sqrt(rank))
but from numpy's PCG64 — NOT the framework's jax threefry draw — so
agreement between the two trainers can only come from both
implementing the same published math, never from shared arithmetic.
"""

from __future__ import annotations

import numpy as np


def train_implicit_als(user_idx: np.ndarray, item_idx: np.ndarray,
                       raw_ratings: np.ndarray, n_users: int,
                       n_items: int, rank: int = 64, iterations: int = 10,
                       lam: float = 0.01, alpha: float = 40.0,
                       seed: int = 20080101, weighted_lambda: bool = True):
    """Hu-Koren-Volinsky implicit ALS with ALS-WR weighted-lambda.

    Returns float64 ``(X, Y)`` — user and item factor matrices.
    ``weighted_lambda=True`` applies Zhou et al.'s lambda * n_row
    scaling (MLlib's behavior); False applies plain lambda.
    """
    rng = np.random.Generator(np.random.PCG64(seed))
    X = rng.standard_normal((n_users, rank)) / np.sqrt(rank)
    Y = rng.standard_normal((n_items, rank)) / np.sqrt(rank)

    by_user = _group(user_idx, item_idx, raw_ratings, n_users)
    by_item = _group(item_idx, user_idx, raw_ratings, n_items)

    for _ in range(iterations):
        _solve_side(X, Y, by_user, lam, alpha, weighted_lambda)
        _solve_side(Y, X, by_item, lam, alpha, weighted_lambda)
    return X, Y


def _group(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
           n_rows: int):
    """Per-row (cols, ratings) views, grouped with one lexsort."""
    order = np.lexsort((np.arange(len(rows)), rows))
    r_sorted = rows[order]
    c_sorted = cols[order]
    v_sorted = np.asarray(vals, dtype=np.float64)[order]
    starts = np.searchsorted(r_sorted, np.arange(n_rows + 1))
    return starts, c_sorted, v_sorted


def _solve_side(out: np.ndarray, fixed: np.ndarray, grouped,
                lam: float, alpha: float, weighted_lambda: bool) -> None:
    starts, cols, vals = grouped
    rank = fixed.shape[1]
    gram = fixed.T @ fixed  # Y^T Y, shared across rows (HKV sec. 4)
    ident = np.eye(rank)
    for u in range(out.shape[0]):
        lo, hi = starts[u], starts[u + 1]
        if lo == hi:
            out[u] = 0.0
            continue
        Yu = fixed[cols[lo:hi]]                  # [n_u, rank]
        conf_minus_1 = alpha * vals[lo:hi]       # c_ui - 1
        # A = Y^T Y + Y_u^T diag(c-1) Y_u + lambda(*n) I
        A = gram + Yu.T @ (Yu * conf_minus_1[:, None])
        reg = lam * (hi - lo) if weighted_lambda else lam
        A[np.diag_indices_from(A)] += reg
        # b = Y^T C_u p(u) = sum_i c_ui y_i   (p_ui = 1 on observed)
        b = (1.0 + conf_minus_1) @ Yu
        out[u] = np.linalg.solve(A, b)
