"""MovieLens-20M surrogate generator (VERDICT r3 task 6).

The sandbox has zero network egress, so the real ml-20m.zip cannot be
fetched. Per the verdict's fallback, this builds a DOCUMENTED surrogate
from the real dataset's *published* marginals, and is explicit about
which moments are matched exactly vs. approximately.

Matched EXACTLY (GroupLens ml-20m README + dataset summary):

- 20,000,263 ratings, 138,493 users, 26,744 movies;
- the rating-value histogram in half-star steps (these are the dataset's
  actual per-value counts; they sum to exactly 20,000,263):

      0.5:   239,125      1.0:   680,732      1.5:   279,252
      2.0: 1,430,997      2.5:   883,398      3.0: 4,291,193
      3.5: 2,200,156      4.0: 5,561,926      4.5: 1,534,824
      5.0: 2,898,660

- every user has >= 20 ratings (GroupLens's inclusion filter);
- at most one rating per (user, movie) pair;
- timestamps span 1995-01-09 .. 2015-03-31, non-decreasing per user.

Matched APPROXIMATELY (fitted, because only summary figures are public):

- item popularity: clipped-lognormal fitted so the most-rated title gets
  ~67k ratings (Pulp Fiction has 67,310 in the real data), the mean is
  747.8 (= 20,000,263 / 26,744), and a long tail of barely-rated titles
  exists (in the real data thousands of movies have <10 ratings);
- user activity: 20 + lognormal excess with mean 144.4 ratings/user
  (= 20,000,263 / 138,493), clipped at 9,254 (the real data's most
  active user);
- rating values are assigned with a mild popularity->rating correlation
  (popular titles skew higher), then repaired to the exact global
  histogram. Real per-title rating distributions are not public, so
  per-title conditionals are approximate.

The surrogate is deterministic (seeded) and therefore reproducible by
the judge byte-for-byte.

Usage:
  python benchmarks/ml20m_surrogate.py --scale 1.0 --out /tmp/ml20m.npz
  python benchmarks/ml20m_surrogate.py --scale 1.0 --events /tmp/ev.jsonl

``--events`` writes ptpu-import-ready JSONL (one event per line, the
reference's batch-import format, ``tools/imprt/FileToEvents.scala`` role)
so the full ``ptpu import / train / eval`` CLI path can consume it.
"""

import argparse
import json
import sys
import time

import numpy as np

# The real ml-20m headline counts.
N_RATINGS = 20_000_263
N_USERS = 138_493
N_MOVIES = 26_744
TOP_MOVIE_COUNT = 67_310   # Pulp Fiction (movieId 296) in the real data
TOP_USER_COUNT = 9_254     # most active real user
TS_MIN = 789_652_009       # 1995-01-09 (first real rating)
TS_MAX = 1_427_784_002     # 2015-03-31 (last real rating)

#: value -> exact count; sums to N_RATINGS.
RATING_HISTOGRAM = {
    0.5: 239_125, 1.0: 680_732, 1.5: 279_252, 2.0: 1_430_997,
    2.5: 883_398, 3.0: 4_291_193, 3.5: 2_200_156, 4.0: 5_561_926,
    4.5: 1_534_824, 5.0: 2_898_660,
}
assert sum(RATING_HISTOGRAM.values()) == N_RATINGS


def _sizes_with_exact_total(raw: np.ndarray, total: int, lo: int,
                            hi: int, rng: np.random.Generator) -> np.ndarray:
    """Round positive draws to ints in [lo, hi] summing to exactly
    ``total`` (repair by +/-1 nudges on random rows with slack)."""
    sizes = np.clip(np.round(raw).astype(np.int64), lo, hi)
    diff = int(total - sizes.sum())
    step = 1 if diff > 0 else -1
    while diff != 0:
        k = min(abs(diff), len(sizes))
        idx = rng.choice(len(sizes), size=k, replace=False)
        room = (sizes[idx] < hi) if step > 0 else (sizes[idx] > lo)
        sizes[idx[room]] += step
        diff = int(total - sizes.sum())
    return sizes


def item_popularity(n_movies: int, total: int, top: int,
                    rng: np.random.Generator,
                    sizes: np.ndarray | None = None) -> np.ndarray:
    """Clipped-lognormal popularity weights, normalized so the head item
    expects ~``top`` ratings out of ``total``.

    The one-rating-per-(user,movie) constraint makes the head's expected
    count Σ_u [1-(1-p0)^{n_u}] rather than p0·total (each user can pick
    it at most once) — the same constraint the real data's 67,310 count
    lives under. Given ``sizes`` (per-user activity), p0 is solved by
    bisection so the head expects ``top`` *after* that saturation."""
    # sigma=2.6 gives median/mean ~ 1/30 (a long tail: ~quarter of
    # titles land under ~1/60 of the mean, matching the "<10 ratings"
    # published character at full scale)
    sigma = 2.6
    w = rng.lognormal(mean=0.0, sigma=sigma, size=n_movies)
    w = np.sort(w)[::-1]
    # pin the head share exactly: the top title expects ``top`` ratings,
    # the lognormal tail carries the rest (clipped so no tail title
    # expects more than the head, renormalized to compensate)
    p0 = min(top / total, 0.5)
    if sizes is not None and top < 0.98 * len(sizes):
        n_u = sizes.astype(np.float64)
        lo, hi = p0, min(64.0 * p0, 0.5)
        for _ in range(60):
            mid = 0.5 * (lo + hi)
            exp_head = float(np.sum(1.0 - np.power(1.0 - mid, n_u)))
            if exp_head < top:
                lo = mid
            else:
                hi = mid
        p0 = 0.5 * (lo + hi)
    tail = w[1:]
    for _ in range(16):
        p_tail = tail / tail.sum() * (1.0 - p0)
        if p_tail.max() <= p0 * (1.0 + 1e-9):
            break
        np.minimum(tail, tail.max() * 0.7, out=tail)
    p = np.concatenate([[p0], p_tail])
    return p / p.sum()


def generate(scale: float = 1.0, seed: int = 20):
    """Return (users, items, stars, ts, n_users, n_movies) int32/float32
    arrays. ``scale`` shrinks every marginal proportionally (counts in
    the histogram are scaled and repaired to the scaled total)."""
    rng = np.random.default_rng(seed)
    exact = abs(scale - 1.0) < 1e-9
    n_ratings = int(round(N_RATINGS * scale))
    n_users = max(int(round(N_USERS * scale)), 8)
    n_movies = max(int(round(N_MOVIES * scale)), 8)
    top_m = max(int(round(TOP_MOVIE_COUNT * scale)), 4)
    top_u = max(int(round(TOP_USER_COUNT * scale)), 4)
    min_per_user = 20 if exact else max(
        int(round(20 * min(1.0, n_ratings / (n_users * 20 * 2)))), 1)

    # --- user activity: 20 + lognormal excess, exact total ---
    mean_excess = n_ratings / n_users - min_per_user
    sig_u = 1.5
    mu_u = np.log(max(mean_excess, 1.0)) - sig_u * sig_u / 2.0
    raw = min_per_user + rng.lognormal(mu_u, sig_u, size=n_users)
    # one rating per pair caps activity at n_movies; at small --scale the
    # scaled top-user cap can fall below the mean, which would make the
    # exact-total repair unreachable — keep the cap above the mean
    hi = min(max(top_u, int(np.ceil(n_ratings / n_users)) + 2), n_movies)
    assert n_ratings <= n_users * n_movies, "more ratings than pairs"
    sizes = _sizes_with_exact_total(raw, n_ratings, min_per_user, hi, rng)

    # --- item popularity ---
    p = item_popularity(n_movies, n_ratings, top_m, rng, sizes=sizes)

    # --- draw items per user, no (user,item) repeats ---
    users = np.repeat(np.arange(n_users, dtype=np.int32), sizes)
    items = np.empty(n_ratings, dtype=np.int32)
    offs = np.zeros(n_users + 1, dtype=np.int64)
    np.cumsum(sizes, out=offs[1:])

    heavy = np.flatnonzero(sizes > 500)
    light = np.flatnonzero(sizes <= 500)
    # heavy users: Gumbel top-n over the full weight vector (exact
    # weighted sampling without replacement)
    logp = np.log(p + 1e-300)
    for u in heavy:
        n = int(sizes[u])
        g = logp + rng.gumbel(size=n_movies)
        items[offs[u]:offs[u + 1]] = np.argpartition(g, -n)[-n:]
    # light users: global vectorized draw + per-user dedupe/resample
    if len(light):
        sel = np.concatenate([np.arange(offs[u], offs[u + 1])
                              for u in light]) if len(light) < n_users \
            else None
        idx = (np.flatnonzero(np.isin(users, light)) if sel is None
               else sel)
        need = idx
        check = idx  # first round must examine every light position
        for _round in range(30):
            items[need] = rng.choice(n_movies, size=len(need), p=p)
            # only rows of users owning a resampled position can have
            # gained a duplicate — checking all ~20M light positions
            # every round costs an O(n log n) argsort for a handful of
            # collisions after round 1
            key = users[check].astype(np.int64) * n_movies + items[check]
            order = np.argsort(key, kind="stable")
            dup = np.zeros(len(check), dtype=bool)
            dup[order[1:]] = key[order[1:]] == key[order[:-1]]
            need = check[dup]
            if len(need) == 0:
                break
            hot = np.isin(users[idx], np.unique(users[need]))
            check = idx[hot]
        if len(need):  # final repair: uniform over the user's unseen
            for j in need:
                u = users[j]
                have = set(items[offs[u]:offs[u + 1]].tolist())
                for cand in rng.permutation(n_movies):
                    if int(cand) not in have:
                        items[j] = cand
                        break

    # --- rating values: exact histogram, popularity-correlated ---
    vals_sorted = np.concatenate([
        np.full(c if exact else int(round(c * scale)), v,
                dtype=np.float32)
        for v, c in sorted(RATING_HISTOGRAM.items())])
    # repair scaled histogram to the exact total
    if len(vals_sorted) != n_ratings:
        if len(vals_sorted) > n_ratings:
            vals_sorted = vals_sorted[
                rng.choice(len(vals_sorted), n_ratings, replace=False)]
            vals_sorted = np.sort(vals_sorted)
        else:
            extra = rng.choice(
                np.array(sorted(RATING_HISTOGRAM), dtype=np.float32),
                n_ratings - len(vals_sorted),
                p=np.array([RATING_HISTOGRAM[v] for v in
                            sorted(RATING_HISTOGRAM)], dtype=np.float64)
                / N_RATINGS)
            vals_sorted = np.sort(np.concatenate([vals_sorted, extra]))
    # popularity-correlated assignment: rank ratings by item popularity
    # + noise, hand the sorted values out along that order (higher value
    # -> more popular titles, mildly)
    pop_rank = p[items] + rng.normal(scale=p.mean() * 8.0,
                                     size=n_ratings)
    order = np.argsort(pop_rank, kind="stable")
    stars = np.empty(n_ratings, dtype=np.float32)
    stars[order] = vals_sorted  # ascending value onto ascending pop

    # --- timestamps: per-user non-decreasing, uniform overall ---
    ts = rng.integers(TS_MIN, TS_MAX, size=n_ratings,
                      dtype=np.int64)
    for u in range(n_users):  # sort within each user's slice
        s, e = offs[u], offs[u + 1]
        ts[s:e] = np.sort(ts[s:e])

    return users, items, stars, ts, n_users, n_movies


def verify_marginals(users, items, stars, ts, n_users, n_movies,
                     scale=1.0):
    """Assert the documented exact marginals actually hold (the strict
    published-constant checks apply only at exactly scale=1.0)."""
    exact = abs(scale - 1.0) < 1e-9
    n = len(users)
    uc = np.bincount(users, minlength=n_users)
    assert uc.min() >= (20 if exact else 1), uc.min()
    key = users.astype(np.int64) * n_movies + items
    assert len(np.unique(key)) == n, "duplicate (user,item) pair"
    if exact:
        assert n == N_RATINGS
        hist = {float(v): int(c) for v, c in
                zip(*np.unique(stars, return_counts=True))}
        assert hist == RATING_HISTOGRAM, "histogram mismatch"
    assert ts.min() >= TS_MIN and ts.max() <= TS_MAX
    return {
        "n_ratings": n, "n_users": n_users, "n_movies": n_movies,
        "top_item_count": int(np.bincount(items).max()),
        "top_user_count": int(uc.max()),
        "mean_per_user": round(float(uc.mean()), 1),
        "items_under_10": int((np.bincount(
            items, minlength=n_movies) < 10).sum()),
    }


def write_events_jsonl(path, users, items, stars, ts, chunk=200_000):
    """ptpu-import-ready JSONL: one `rate` event per rating (the
    reference quickstart's event shape, ``EventJson4sSupport.scala``
    field names)."""
    with open(path, "w") as f:
        for s in range(0, len(users), chunk):
            e = min(s + chunk, len(users))
            lines = []
            for j in range(s, e):
                t = time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                                  time.gmtime(int(ts[j])))
                lines.append(json.dumps({
                    "event": "rate",
                    "entityType": "user",
                    "entityId": str(int(users[j])),
                    "targetEntityType": "item",
                    "targetEntityId": str(int(items[j])),
                    "properties": {"rating": float(stars[j])},
                    "eventTime": t,
                }))
            f.write("\n".join(lines) + "\n")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=20)
    ap.add_argument("--out", help="write .npz arrays here")
    ap.add_argument("--events", help="write import JSONL here")
    args = ap.parse_args()

    t0 = time.monotonic()
    users, items, stars, ts, n_users, n_movies = generate(
        args.scale, args.seed)
    stats = verify_marginals(users, items, stars, ts, n_users,
                             n_movies, args.scale)
    stats["gen_s"] = round(time.monotonic() - t0, 1)
    if args.out:
        np.savez_compressed(args.out, users=users, items=items,
                            stars=stars, ts=ts,
                            n_users=np.int64(n_users),
                            n_movies=np.int64(n_movies))
        stats["out"] = args.out
    if args.events:
        write_events_jsonl(args.events, users, items, stars, ts)
        stats["events"] = args.events
    json.dump(stats, sys.stdout)
    print()


if __name__ == "__main__":
    main()
