"""Cold-start smoke: deploy twice, gate the artifact-warmed second one.

The ISSUE-19 acceptance drill in miniature: train once, measure a cold
deploy warm (full compile ladder), `build` the AOT artifact store,
then deploy again from the artifacts and require (a) a true artifact
warm — every executable loaded, ZERO compile fallbacks — and (b) the
warm inside the gated budget. CPU-sized models serve from host numpy
and would never touch the device executables, so the smoke forces the
device path (``HOST_SERVE_WORK = 0``) exactly as docs/cold-start.md's
runbook describes.

Usage: python benchmarks/coldstart_smoke.py [--budget-ms 2000]
Prints one JSON line; exit 1 on a gate miss.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-ms", type=float, default=2000.0,
                    help="max warm-from-artifact wall time")
    ap.add_argument("--artifact-dir", default="",
                    help="store root (default: a temp dir)")
    args = ap.parse_args()

    import tempfile
    from datetime import datetime, timedelta, timezone

    import numpy as np

    import predictionio_tpu.models.als as als
    from predictionio_tpu import aot
    from predictionio_tpu.controller import Context
    from predictionio_tpu.data import DataMap, Event
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
        build_artifacts,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )
    from predictionio_tpu.workflow import core as wf
    from predictionio_tpu.workflow import run_train

    als.HOST_SERVE_WORK = 0  # force device-path serving on CPU
    root = args.artifact_dir or tempfile.mkdtemp(prefix="ptpu_coldstart_")

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, "coldapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(7)
    t = datetime(2026, 1, 1, tzinfo=timezone.utc)
    events = []
    for u in range(32):
        for i in rng.choice(24, size=8, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
                event_time=t))
            t += timedelta(seconds=10)
    es.insert_batch(events, app_id)

    ctx = Context(app_name="coldapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("coldapp", rank=8, num_iterations=4, seed=1)
    run_train(ctx, engine, ep, engine_id="cold", engine_version="1")

    config = ServerConfig(warm_start=False, streaming=False,
                          feedback=False, tracing=False,
                          slo_interval_ms=0.0, hot_keys_k=0,
                          batching=True, max_batch=32)

    def warm_once(artifact_dir):
        instance = ctx.storage.engine_instances().get_latest_completed(
            "cold", "1", "engine.json")
        models = wf.load_models_for_deploy(ctx, engine, instance, ep)
        from dataclasses import replace
        server = QueryServer(ctx, engine, ep, models, instance,
                             replace(config, artifact_dir=artifact_dir))
        t0 = time.perf_counter()
        try:
            server._warm_serving(server._warm_gen)
        finally:
            server.stop_slo()
        return (time.perf_counter() - t0) * 1e3, dict(server._warm_report)

    # deploy #1: cold — the full compile ladder
    aot.deactivate()
    cold_ms, cold_report = warm_once(None)

    # build: capture the ladder into the artifact store
    t0 = time.perf_counter()
    built = build_artifacts(ctx, engine, ep, root, engine_id="cold",
                            config=config)
    build_ms = (time.perf_counter() - t0) * 1e3

    # deploy #2: warm from artifacts
    aot.deactivate()
    warm_ms, warm_report = warm_once(root)

    gates = {
        "artifact_warm": warm_report.get("artifact") is True,
        "zero_compiles": warm_report.get("compiledFallbacks") == 0,
        "entries_loaded": warm_report.get("loadedEntries", 0) > 0,
        "within_budget": warm_ms <= args.budget_ms,
    }
    out = {
        "warm_cold_ms": round(cold_ms, 1),
        "build_aot_ms": round(build_ms, 1),
        "warm_from_artifact_ms": round(warm_ms, 1),
        "speedup": round(cold_ms / max(warm_ms, 1e-9), 2),
        "artifact_entries": built["entries"],
        "loaded_entries": warm_report.get("loadedEntries"),
        "compiled_fallbacks": warm_report.get("compiledFallbacks"),
        "budget_ms": args.budget_ms,
        "gates": gates,
        "ok": all(gates.values()),
    }
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
