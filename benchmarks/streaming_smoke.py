"""Streaming incremental-training smoke (ISSUE 10) — the CI gate for
the event→model loop.

End-to-end over REAL HTTP on whatever device is available (CI: CPU):

1. train + deploy a recommendation engine with the streaming trainer
   attached (``ServerConfig(streaming=True)``), and start an event
   server sharing the process-default invalidation bus (the bus wake
   path production uses for co-located servers);
2. for each trial, ingest a brand-new user's ratings through the event
   server's ``POST /events.json`` and poll the engine server's
   ``/queries.json`` until the recommendations reflect them — the
   wall-clock from first-accepted-ingest to first-correct-serve is the
   **event→servable** freshness sample. Gate: p50 under the smoke
   budget (default 5 s; ``STREAM_SMOKE_BUDGET_S`` overrides);
3. zero cursor gaps: after the loop the trainer must have consumed
   EXACTLY the relevant events ingested (none lost, none twice), with
   cursor lag 0 and the ``pio_stream_*`` series exported on /metrics.

With ``--with-load QPS`` (ISSUE 15), the SAME probe runs while an
open-loop query generator drives the engine server at the given rate —
the freshness number under concurrent serving load, not on an idle
box. ``measure(load_qps=...)`` is the importable form; the harness and
bench.py embed ``event_to_servable_under_load_ms`` beside the idle
number through it.

Prints one JSON line; exits non-zero on any violation. ``measure()``
is importable — bench.py embeds ``event_to_servable_ms`` in the BENCH
line through it.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from datetime import datetime, timedelta, timezone

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from predictionio_tpu.controller import Context  # noqa: E402
from predictionio_tpu.data import DataMap, Event  # noqa: E402
from predictionio_tpu.data.storage import App, Storage  # noqa: E402
from predictionio_tpu.data.storage.base import AccessKey  # noqa: E402
from predictionio_tpu.templates.recommendation import (  # noqa: E402
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import (  # noqa: E402
    get_latest_completed,
    load_models_for_deploy,
    run_train,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


def _call(port, method, path, body=None, timeout=60):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else (
        b"" if method == "POST" else None)
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _seed(storage, app_id, n_users=30):
    rng = np.random.default_rng(7)
    events, t = [], T0
    for u in range(n_users):
        group = range(0, 15) if u % 2 == 0 else range(15, 30)
        for i in rng.choice(list(group), size=8, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": 5.0}), event_time=t))
            t += timedelta(minutes=1)
    storage.events().insert_batch(events, app_id)


def measure(trials: int = 8, ratings_per_trial: int = 3,
            interval_ms: float = 100.0, timeout_s: float = 30.0,
            load_qps: float = 0.0, load_threads: int = 4) -> dict:
    """The ingest→fold-in→serve loop over real HTTP; returns the
    freshness samples + consistency checks (no printing, no exit —
    bench.py embeds this). ``load_qps > 0`` runs a concurrent
    open-loop query generator against the engine server for the whole
    trial loop — the freshness-under-load measurement (ISSUE 15)."""
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
        create_engine_server,
    )
    from predictionio_tpu.server.eventserver import (
        build_app as build_event_app,
    )
    from predictionio_tpu.server.http import AppServer

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, "streamsmoke"))
    storage.events().init(app_id)
    storage.access_keys().insert(
        AccessKey(key="sk", app_id=app_id, events=[]))
    _seed(storage, app_id)
    ctx = Context(app_name="streamsmoke", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("streamsmoke", rank=8, num_iterations=6,
                               reg=0.05, seed=11)
    run_train(ctx, engine, ep, engine_id="streamsmoke",
              engine_factory="templates.recommendation")
    inst = get_latest_completed(ctx, engine_id="streamsmoke")
    models = load_models_for_deploy(ctx, engine, inst, ep)
    qs = QueryServer(
        ctx, engine, ep, models, inst,
        ServerConfig(warm_start=False, streaming=True,
                     stream_app_name="streamsmoke",
                     stream_interval_ms=interval_ms,
                     stream_canary_probes=2))
    # the event server shares the process-default bus with the trainer
    # (build_app and StreamTrainer both fall back to default_bus), so
    # every accepted ingest wakes the fold-in loop immediately
    ev_srv = AppServer(build_event_app(storage), "127.0.0.1",
                       0).start_background()
    en_srv = create_engine_server(qs, "127.0.0.1", 0).start_background()

    out: dict = {"trials": trials}
    samples_ms = []
    ingested_relevant = 0
    load_stop = load_thread = load_box = None
    if load_qps > 0:
        # concurrent query load (ISSUE 15): an open-loop generator at
        # ``load_qps`` against the SAME serving binding the fold-ins
        # hot-swap into — freshness measured while the device/model is
        # actually contended, not idle
        from _loadgen import (
            expect_json_field,
            json_post_sender,
            run_load,
        )

        rng = np.random.default_rng(13)
        load_users = rng.integers(0, 30, 100_000)
        sender = json_post_sender(
            en_srv.port, "/queries.json",
            body_fn=lambda k: json.dumps(
                {"user": f"u{load_users[k]}", "num": 5}).encode(),
            check=expect_json_field("itemScores"))
        load_stop = threading.Event()
        load_box: list = []
        load_thread = threading.Thread(
            target=lambda: load_box.append(run_load(
                sender, len(load_users), load_threads,
                rate_qps=load_qps, stop=load_stop)),
            daemon=True, name="freshness-load")
        load_thread.start()
    try:
        for k in range(trials):
            user = f"smoke_user_{k}"
            items = [(k * 3 + j) % 15 for j in range(ratings_per_trial)]
            t0 = time.monotonic()
            for i in items:
                status, _ = _call(
                    ev_srv.port, "POST", f"/events.json?accessKey=sk",
                    {"event": "rate", "entityType": "user",
                     "entityId": user, "targetEntityType": "item",
                     "targetEntityId": f"i{i}",
                     "properties": {"rating": 5.0}})
                assert status == 201, f"ingest failed: {status}"
                ingested_relevant += 1
            deadline = time.monotonic() + timeout_s
            servable = None
            while time.monotonic() < deadline:
                _, got = _call(en_srv.port, "POST", "/queries.json",
                               {"user": user, "num": 5})
                if got.get("itemScores"):
                    servable = (time.monotonic() - t0) * 1000.0
                    break
                time.sleep(0.02)
            if servable is None:
                out[f"trial_{k}_timed_out"] = True
            else:
                samples_ms.append(servable)
        # settle, then check exactly-once consumption + zero lag
        deadline = time.monotonic() + 10
        stream = {}
        while time.monotonic() < deadline:
            _, stream = _call(en_srv.port, "GET", "/stream.json")
            if stream.get("cursorLag", 1) == 0 and \
                    stream.get("eventsConsumed", 0) >= \
                    240 + ingested_relevant:
                break
            time.sleep(0.1)
        out["events_ingested"] = ingested_relevant
        out["events_consumed"] = stream.get("eventsConsumed")
        out["cursor_lag"] = stream.get("cursorLag")
        out["applies"] = stream.get("applies")
        out["canary_rejects"] = stream.get("canaryRejects")
        # 240 seed events drain in the first pass; every ingested event
        # consumed exactly once on top of that = zero cursor gaps
        out["zero_cursor_gaps"] = (
            stream.get("eventsConsumed") == 240 + ingested_relevant
            and stream.get("cursorLag") == 0)
        _, status_json = _call(en_srv.port, "GET", "/status.json")
        lin = status_json.get("lineage") or {}
        out["lineage_generation"] = lin.get("incrementalGeneration")
        out["lineage_ok"] = (lin.get("baseInstanceId") == inst.id
                             and (lin.get("incrementalGeneration")
                                  or 0) >= 1)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{en_srv.port}/metrics",
                timeout=30) as resp:
            text = resp.read().decode()
        out["stream_series_exported"] = all(
            s in text for s in ("pio_stream_events_consumed_total",
                                "pio_stream_foldin_seconds",
                                "pio_stream_freshness_seconds",
                                "pio_stream_cursor_lag",
                                "pio_stream_drift_score"))
    finally:
        if load_stop is not None:
            load_stop.set()
            load_thread.join(timeout=30)
            if load_box:
                stats, wall = load_box[0]
                out["load"] = {"offered_qps": load_qps,
                               **stats.summary(wall)}
        qs.stop_stream()
        en_srv.shutdown()
        ev_srv.shutdown()
    if samples_ms:
        arr = np.sort(np.asarray(samples_ms))
        out["event_to_servable_p50_ms"] = round(
            float(np.percentile(arr, 50)), 1)
        out["event_to_servable_p90_ms"] = round(
            float(np.percentile(arr, 90)), 1)
        out["event_to_servable_max_ms"] = round(float(arr[-1]), 1)
    out["samples"] = len(samples_ms)
    return out


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    argv = sys.argv[1:]
    load_qps = 0.0
    if "--with-load" in argv:
        i = argv.index("--with-load")
        load_qps = float(argv[i + 1])
        del argv[i:i + 2]
    budget_ms = float(os.environ.get("STREAM_SMOKE_BUDGET_S",
                                     "5")) * 1000.0
    res = measure(trials=int(os.environ.get("STREAM_SMOKE_TRIALS", "8")),
                  load_qps=load_qps)
    checks = {
        "all_trials_servable": res.get("samples") == res["trials"],
        "p50_under_budget": (
            res.get("event_to_servable_p50_ms") is not None
            and res["event_to_servable_p50_ms"] < budget_ms),
        "zero_cursor_gaps": bool(res.get("zero_cursor_gaps")),
        "lineage_ok": bool(res.get("lineage_ok")),
        "stream_series_exported": bool(
            res.get("stream_series_exported")),
    }
    ok = all(checks.values())
    print(json.dumps({"bench": "streaming_smoke", "ok": ok,
                      "budget_ms": budget_ms, **checks, **res}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
