"""Serving-cache smoke (ISSUE 4) — the CI gate for the cache hierarchy.

End-to-end over real HTTP on whatever device is available (CI: CPU):

1. deploy a synthetic model with the serving cache ON; repeat a query
   and PROVE the second serve was a cache hit (and faster paths exist:
   /cache.json hit counters move);
2. ingest an event for that entity through the REAL event server and
   prove the bus invalidated the cached result (invalidations > 0 and
   the next serve is a recompute);
3. fire concurrent identical misses and prove singleflight collapsed
   them;
4. operator flush via POST /cache/flush empties every tier.

Prints one JSON line; exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from datetime import datetime, timezone

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from predictionio_tpu.controller import Context  # noqa: E402
from predictionio_tpu.data.bimap import BiMap  # noqa: E402
from predictionio_tpu.data.storage import App, Storage  # noqa: E402
from predictionio_tpu.data.storage.base import (  # noqa: E402
    STATUS_COMPLETED,
    AccessKey,
    EngineInstance,
)
from predictionio_tpu.models.als import ALSModel, ALSParams  # noqa: E402
from predictionio_tpu.server.engineserver import (  # noqa: E402
    QueryServer,
    ServerConfig,
    create_engine_server,
)
from predictionio_tpu.server.eventserver import (  # noqa: E402
    build_app as build_event_app,
)
from predictionio_tpu.server.http import AppServer  # noqa: E402
from predictionio_tpu.templates.recommendation import (  # noqa: E402
    default_engine_params,
    recommendation_engine,
)


def call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else (
        b"" if method == "POST" else None)
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    rng = np.random.default_rng(0)
    n_users, n_items, rank = 200, 200, 8
    model = ALSModel(
        user_factors=rng.standard_normal(
            (n_users, rank)).astype(np.float32),
        item_factors=rng.standard_normal(
            (n_items, rank)).astype(np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, "cachesmoke"))
    storage.access_keys().insert(
        AccessKey(key="SMOKE", app_id=app_id, events=()))
    ctx = Context(app_name="cachesmoke", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="smoke", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="smoke", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    storage.engine_instances().insert(inst)
    qs = QueryServer(
        ctx, recommendation_engine(),
        default_engine_params("cachesmoke", rank=rank), [model], inst,
        ServerConfig(warm_start=False, serving_cache=True,
                     cache_ttl_sec=600.0))
    srv = create_engine_server(qs, "127.0.0.1", 0).start_background()
    ev_srv = AppServer(build_event_app(storage), "127.0.0.1",
                       0).start_background()
    checks = {}
    try:
        # 1) hit
        q = {"user": "u7", "num": 5}
        r1 = call(srv.port, "POST", "/queries.json", q)
        t0 = time.monotonic()
        r2 = call(srv.port, "POST", "/queries.json", q)
        hit_ms = (time.monotonic() - t0) * 1000
        tiers = call(srv.port, "GET", "/cache.json")["tiers"]
        checks["hit"] = (r1 == r2 and tiers["query"]["hits"] >= 1)
        checks["hit_ms"] = round(hit_ms, 3)

        # 2) ingest → invalidation → recompute
        call(ev_srv.port, "POST", "/events.json?accessKey=SMOKE",
             {"event": "view", "entityType": "user", "entityId": "u7",
              "targetEntityType": "item", "targetEntityId": "i3"})
        tiers = call(srv.port, "GET", "/cache.json")["tiers"]
        checks["invalidated"] = tiers["query"]["invalidations"] >= 1
        misses_before = tiers["query"]["misses"]
        call(srv.port, "POST", "/queries.json", q)  # recompute
        tiers = call(srv.port, "GET", "/cache.json")["tiers"]
        checks["recomputed"] = tiers["query"]["misses"] > misses_before

        # 3) singleflight: concurrent identical misses collapse
        flights_q = {"user": "u42", "num": 5}
        threads = [threading.Thread(
            target=lambda: call(srv.port, "POST", "/queries.json",
                                flights_q)) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = call(srv.port, "GET", "/cache.json")
        checks["singleflight"] = (
            stats["singleflightCoalesced"] + stats["tiers"]["query"][
                "hits"] >= 2)

        # 4) operator flush
        removed = call(srv.port, "POST", "/cache/flush")["removed"]
        tiers = call(srv.port, "GET", "/cache.json")["tiers"]
        checks["flush"] = (removed.get("query", 0) >= 1
                           and tiers["query"]["entries"] == 0)
    finally:
        srv.shutdown()
        ev_srv.shutdown()

    ok = all(v for k, v in checks.items() if k != "hit_ms")
    print(json.dumps({"bench": "cache_smoke", "ok": ok, **checks}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
