"""Serving-path benchmark: the REAL engine server under concurrent load.

Measures `POST /queries.json` latency through the full deployed stack
(HTTP → QueryServer → template predict → top-k), the reference hot path
``CreateServer.scala:484-633``, in three configurations:

- ``host``: small catalog — the host fast path (numpy dot, the
  reference's in-JVM BLAS serving role)
- ``device``: a catalog past ``HOST_SERVE_WORK`` — every query is an
  MXU matmul + top-k dispatch
- ``device+batching``: same catalog with the serving micro-batcher
  coalescing concurrent queries into one ``batch_predict`` dispatch
  (``ServerConfig(batching=True)``; the reference served strictly
  per-request — ``CreateServer.scala:507-510`` "TODO: Parallelize")

Prints ONE JSON line with p50/p90/p99 (ms) and throughput per config.

With ``--canary FRACTION``, an extra config binds a second synthetic
model as a CANDIDATE release at that traffic fraction (the rollout
splitter's hash-of-entity cohort, health gate held) and reports
stable-vs-candidate p50/p99 side by side from the server's own per-arm
release series — the canary latency-overhead view.

With ``--zipf ALPHA``, the workload's users are drawn from a Zipf(α)
distribution instead of uniform — the hot-entity skew production
recommendation traffic actually has. With ``--cache`` (ISSUE 4), the
device per-query config runs TWICE on that skewed workload — serving
cache off vs on — and a trailing hot-query loop measures the pure
cache-hit latency; the emitted row reports cached-vs-uncached p50/p99
side by side plus the server's own /cache.json tier stats.

With ``--mesh`` (ISSUE 6), a device-scaling battery runs the same
burst workload against the micro-batcher in single mode, replicated
fan-out (a full model copy per device, per-device lanes), and the
row-sharded mesh — per-mode qps plus the replicated/single
``scaling_x`` ratio.

Since ISSUE 9 the micro-batch config runs twice — the staged
continuous-batching pipeline vs the serial drainer at the same load —
and a ``pipeline_overlap`` row embeds the qps/p99 ratios plus the
server's own device-idle / overlap fractions (the proof the device
stays busy while host stages run).

With ``--arrival-rate QPS``, an OPEN-LOOP fixed-rate generator replaces
the closed-loop battery (coordinated-omission-safe: latency is measured
from each request's scheduled arrival, so a stalling server accrues
latency instead of silently slowing the offered load). Sweep the rate
to trace the qps-vs-p99 knee — the first slice of ROADMAP's
load-harness item.

With ``--quant DTYPE`` (ISSUE 13), the device per-query and
micro-batch configs run again with row-quantized serving tables
(``serving_quant=DTYPE`` + the autotuned fused top-k kernel) and a
``serving_quant`` summary row reports quantized-vs-f32 per-query p50
and micro-batch qps/p99 ratios side by side — the row ``bench.py``
embeds in the BENCH line.

Usage: python benchmarks/serving_bench.py [n_items_device] [rank]
                                          [--canary FRACTION]
                                          [--zipf ALPHA] [--cache]
                                          [--mesh] [--quant DTYPE]
                                          [--arrival-rate QPS]
Env:   SERVE_THREADS (8), SERVE_REQUESTS (400 per config)
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request
from datetime import datetime, timezone

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _loadgen import (  # noqa: E402
    expect_json_field,
    json_post_sender,
    run_load,
    sample_entities,
)
from predictionio_tpu.controller import Context  # noqa: E402
from predictionio_tpu.data.bimap import BiMap  # noqa: E402
from predictionio_tpu.data.storage import App, Storage  # noqa: E402
from predictionio_tpu.data.storage.base import (  # noqa: E402
    EngineInstance,
    STATUS_COMPLETED,
)
from predictionio_tpu.models.als import (  # noqa: E402
    ALSModel,
    ALSParams,
    HOST_SERVE_WORK,
)
from predictionio_tpu.server.engineserver import (  # noqa: E402
    QueryServer,
    ServerConfig,
    create_engine_server,
)
from predictionio_tpu.templates.recommendation import (  # noqa: E402
    default_engine_params,
    recommendation_engine,
)


def synth_model(n_users: int, n_items: int, rank: int,
                device: bool) -> ALSModel:
    rng = np.random.default_rng(0)
    U = rng.standard_normal((n_users, rank)).astype(np.float32)
    V = rng.standard_normal((n_items, rank)).astype(np.float32)
    if device:
        import jax
        U = jax.device_put(U)
        V = jax.device_put(V)
        V.block_until_ready()
    return ALSModel(
        user_factors=U, item_factors=V, n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))


#: Zipf-or-uniform user draw — shared with the load harness
_sample_users = sample_entities


def _query_sender(port: int, users: np.ndarray, shed=()):
    """One keep-alive worker posting ``/queries.json`` for user k.
    ``shed`` lists statuses counted as load-shedding instead of
    errors (the open-loop knee sweep passes ``(503,)``; the
    closed-loop battery treats every non-200 as a failure)."""
    return json_post_sender(
        port, "/queries.json",
        body_fn=lambda k: json.dumps({"user": f"u{users[k]}",
                                      "num": 10}).encode(),
        check=expect_json_field("itemScores"), shed_status=shed)


def _boot_server(model: ALSModel, cfg: ServerConfig):
    """One deployed QueryServer over a synthetic COMPLETED instance —
    shared by the closed-loop configs and the open-loop generator."""
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "servebench"))
    ctx = Context(app_name="servebench", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("servebench", rank=model.params.rank)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="bench", status=STATUS_COMPLETED, start_time=now, end_time=now,
        engine_id="bench", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    qs = QueryServer(ctx, engine, ep, [model], inst, cfg)
    srv = create_engine_server(qs, host="127.0.0.1", port=0)
    srv.start_background()
    return qs, srv


def _wait_warm(port: int, label: str) -> None:
    """Block until the server-side warmup (ServerConfig.warm_start
    compiles the single-query + pow2 batch ladder) reports done."""
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status.json",
                timeout=30) as resp:
            if json.loads(resp.read()).get("servingWarm"):
                return
        time.sleep(0.5)
    raise RuntimeError(f"{label}: serving warmup did not finish")


def bench_config(model: ALSModel, cfg: ServerConfig, n_requests: int,
                 n_threads: int, label: str, zipf=None,
                 hot_hit_probe: int = 0) -> dict:
    qs, srv = _boot_server(model, cfg)
    port = srv.port
    rng = np.random.default_rng(1)
    users = _sample_users(rng, model.n_users, n_requests, zipf)

    _wait_warm(port, label)
    for u in users[:3]:
        body = json.dumps({"user": f"u{u}", "num": 10}).encode()
        urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json", data=body,
            headers={"Content-Type": "application/json"}), timeout=120
        ).read()

    # closed-loop burst through the shared generator (_loadgen): one
    # keep-alive connection per worker, latency from each send
    stats, wall = run_load(_query_sender(port, users), n_requests,
                           n_threads)
    lat, errors = stats.lat, stats.errors
    # hot-query probe (ISSUE 4): with the serving cache on, repeat ONE
    # hot user's query sequentially — after the first fill these are
    # pure cache hits, measuring the parse→cache→respond floor the
    # acceptance gate compares against the uncached device p50
    hot_hit = None
    if hot_hit_probe > 0:
        import http.client

        hot_body = json.dumps({"user": f"u{users[0]}",
                               "num": 10}).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=120)
        try:
            hot_lat = []
            for i in range(hot_hit_probe + 1):
                t0 = time.monotonic()
                conn.request("POST", "/queries.json", body=hot_body,
                             headers={"Content-Type":
                                      "application/json"})
                conn.getresponse().read()
                if i > 0:  # drop the (possible) fill miss
                    hot_lat.append(time.monotonic() - t0)
        finally:
            conn.close()
        arr_h = np.sort(np.asarray(hot_lat)) * 1e3
        hot_hit = {
            "n": len(arr_h),
            "p50_ms": round(float(np.percentile(arr_h, 50)), 3),
            "p99_ms": round(float(np.percentile(arr_h, 99)), 3),
        }
    cache_stats = None
    if cfg.serving_cache:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/cache.json",
                    timeout=30) as resp:
                tiers = json.loads(resp.read()).get("tiers") or {}
            cache_stats = {
                name: {"hits": t.get("hits"), "misses": t.get("misses"),
                       "hitRatio": round(t.get("hitRatio", 0.0), 4)}
                for name, t in tiers.items()}
        except Exception as e:  # noqa: BLE001 — stats are advisory
            cache_stats = {"error": str(e)[:200]}
    # scrape the server's own telemetry BEFORE shutdown (ISSUE 2): the
    # emitted bench line carries compilesSinceWarm + transfer-guard
    # violations so the perf trajectory captures recompile storms and
    # hidden host syncs, not just client-side latency
    telemetry = None
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status.json",
                timeout=30) as resp:
            status = json.loads(resp.read())
        lat_hist = status.get("latency") or {}
        telemetry = {
            "compilesSinceWarm":
                (status.get("recompile") or {}).get("compilesSinceWarm"),
            "transferGuardViolations":
                status.get("transferGuardViolations"),
            "server_p99_ms": (round(lat_hist["p99"] * 1000, 2)
                              if lat_hist.get("p99") is not None
                              else None),
            # the pipeline overlap proof (ISSUE 9): device idle /
            # overlap fractions + deadline sheds from the server's own
            # accounting, embedded beside the client-side percentiles
            "pipeline": status.get("pipeline"),
        }
    except Exception as e:  # noqa: BLE001 — telemetry is advisory
        telemetry = {"error": str(e)[:200]}
    srv.shutdown()
    if errors or not lat:
        raise RuntimeError(
            f"{label}: {len(errors)} failed requests of {n_requests} "
            f"(first: {errors[0] if errors else 'none'}) — latency "
            f"numbers would describe a degraded load, refusing")
    arr = np.sort(np.asarray(lat)) * 1e3
    out = {
        "config": label,
        "n": len(arr),
        "p50_ms": round(float(np.percentile(arr, 50)), 2),
        "p90_ms": round(float(np.percentile(arr, 90)), 2),
        "p99_ms": round(float(np.percentile(arr, 99)), 2),
        "qps": round(len(arr) / wall, 1),
        "telemetry": telemetry,
    }
    if zipf is not None:
        out["zipf"] = float(zipf)
    if hot_hit is not None:
        out["hot_hit"] = hot_hit
    if cache_stats is not None:
        out["cache"] = cache_stats
    return out


def pipeline_block(staged: dict, serial: dict) -> dict:
    """The ISSUE 9 acceptance view: staged vs serial drainer at the
    SAME offered load — qps/p99 ratios plus the staged server's own
    overlap accounting (device idle fraction proving the device stayed
    busy while host stages ran)."""
    out = {
        "config": "pipeline_overlap",
        "staged_qps": staged.get("qps"),
        "serial_qps": serial.get("qps"),
        "staged_p99_ms": staged.get("p99_ms"),
        "serial_p99_ms": serial.get("p99_ms"),
    }
    if serial.get("qps") and staged.get("qps"):
        out["qps_x"] = round(staged["qps"] / serial["qps"], 2)
    if serial.get("p99_ms") and staged.get("p99_ms"):
        out["p99_x"] = round(serial["p99_ms"] / staged["p99_ms"], 2)
    pipe = ((staged.get("telemetry") or {}).get("pipeline")) or {}
    ov = pipe.get("overlap") or {}
    out["device_idle_fraction"] = ov.get("deviceIdleFraction")
    out["overlap_fraction"] = ov.get("overlapFraction")
    out["overlapped_dispatches"] = ov.get("overlappedDispatches")
    out["deadline_exceeded"] = pipe.get("deadlineExceeded")
    return out


def standard_battery(n_items_dev: int, rank: int, n_req: int,
                     n_threads: int, hi_threads: int) -> dict:
    """The serving battery — ONE definition shared by this script's
    ``main()`` and ``bench.py``'s serving block (they drifted when each
    kept its own copy): host fast path, per-query at trickle load,
    per-query and micro-batcher at burst load (``hi_threads`` offered
    concurrency — the apples-to-apples pair). Since ISSUE 9 the
    micro-batcher runs TWICE at the same load — staged continuous-
    batching pipeline vs the serial drainer — and a ``pipeline``
    summary row carries the ratio + overlap proof."""
    from predictionio_tpu.server.engineserver import ServerConfig

    host_model = synth_model(2000, 2000, rank, device=False)
    dev_model = synth_model(50_000, n_items_dev, rank, device=True)
    hi_req = max(n_req, 8 * hi_threads)
    out = {
        "host_fast_path": bench_config(
            host_model, ServerConfig(), max(n_req, 300), n_threads,
            "host_fast_path"),
        # tracing A/B (ISSUE 12 acceptance: tracing adds ≤5% to the
        # host fast-path p50): the same load with the flight recorder
        # off — the ONLY config difference
        "host_fast_path_untraced": bench_config(
            host_model, ServerConfig(tracing=False), max(n_req, 300),
            n_threads, "host_fast_path_untraced"),
        "per_query": bench_config(
            dev_model, ServerConfig(), n_req, n_threads,
            "device_per_query"),
        "per_query_loaded": bench_config(
            dev_model, ServerConfig(), hi_req, hi_threads,
            "device_per_query_loaded"),
        "microbatch": bench_config(
            dev_model, ServerConfig(batching=True, max_batch=128,
                                    batch_window_ms=2.0),
            hi_req, hi_threads, "device_microbatch_staged"),
        "microbatch_serial": bench_config(
            dev_model, ServerConfig(batching=True, max_batch=128,
                                    batch_window_ms=2.0,
                                    serving_pipeline="serial"),
            hi_req, hi_threads, "device_microbatch_serial"),
    }
    out["pipeline"] = pipeline_block(out["microbatch"],
                                     out["microbatch_serial"])
    traced = out["host_fast_path"].get("p50_ms")
    untraced = out["host_fast_path_untraced"].get("p50_ms")
    if traced and untraced:
        out["trace_overhead_pct"] = round(
            (traced / untraced - 1.0) * 100.0, 2)
    return out


def bench_open_loop(model: ALSModel, cfg: ServerConfig, rate_qps: float,
                    n_requests: int, n_threads: int, label: str) -> dict:
    """Open-loop fixed-rate load (the first slice of ROADMAP's
    load-harness item): request k's INTENDED start time is
    ``t0 + k/rate`` regardless of how the server is doing, and latency
    is measured from that intended start — coordinated-omission-safe:
    a stalling server keeps accruing latency on every scheduled
    arrival instead of silently slowing the offered load the way a
    closed loop does. Sweep ``--arrival-rate`` to find the qps-vs-p99
    knee; past it, p99 grows without bound (or deadline sheds appear),
    which IS the capacity signal."""
    qs, srv = _boot_server(model, cfg)
    port = srv.port
    try:
        _wait_warm(port, label)
        rng = np.random.default_rng(3)
        users = rng.integers(0, model.n_users, n_requests)
        for u in users[:3]:
            body = json.dumps({"user": f"u{u}", "num": 10}).encode()
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=120).read()

        # the open-loop discipline lives in _loadgen.run_load now:
        # request k's intended start is t0 + k/rate and latency is
        # measured from that schedule (coordinated-omission-safe)
        stats, wall = run_load(
            _query_sender(port, users, shed=(503,)), n_requests,
            n_threads, rate_qps=rate_qps)
        lat, shed, errors = stats.lat, stats.shed, stats.errors
        pipe = None
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status.json",
                    timeout=30) as resp:
                pipe = json.loads(resp.read()).get("pipeline")
        except Exception as e:  # noqa: BLE001 — telemetry is advisory
            pipe = {"error": str(e)[:200]}
    finally:
        srv.shutdown()
    if errors:
        raise RuntimeError(
            f"{label}: {len(errors)} failed requests "
            f"(first: {errors[0]})")
    if not lat:
        raise RuntimeError(f"{label}: every request was shed; offered "
                           f"rate {rate_qps} is far past the knee")
    arr = np.sort(np.asarray(lat)) * 1e3
    return {
        "config": label,
        "open_loop": True,
        "offered_qps": rate_qps,
        "achieved_qps": round(len(lat) / wall, 1),
        "n": len(arr),
        "shed": len(shed),
        "p50_ms": round(float(np.percentile(arr, 50)), 2),
        "p90_ms": round(float(np.percentile(arr, 90)), 2),
        "p99_ms": round(float(np.percentile(arr, 99)), 2),
        "pipeline": pipe,
    }


def mesh_scaling_battery(n_items_dev: int, rank: int, n_req: int,
                         hi_threads: int) -> dict:
    """Per-mode device-scaling probe (ISSUE 6): the SAME burst workload
    against the micro-batcher in single mode, replicated fan-out
    (per-device lanes), and the row-sharded mesh — qps side by side
    plus ``scaling_x`` (replicated qps over single-lane qps, the
    near-linear-on-N-devices acceptance number). One device degrades
    to the single row alone."""
    import jax

    n_dev = len(jax.devices())
    dev_model = synth_model(50_000, n_items_dev, rank, device=True)
    hi_req = max(n_req, 8 * hi_threads)
    single = bench_config(
        dev_model, ServerConfig(batching=True, max_batch=128,
                                batch_window_ms=2.0),
        hi_req, hi_threads, "mesh_single_microbatch")
    out: dict = {"devices": n_dev, "single": single}
    if n_dev > 1:
        rep = bench_config(
            dev_model, ServerConfig(batching=True, max_batch=128,
                                    batch_window_ms=2.0,
                                    serving_mode="replicated"),
            hi_req, hi_threads, "mesh_replicated_microbatch")
        if single.get("qps"):
            rep["scaling_x"] = round(rep["qps"] / single["qps"], 2)
        out["replicated"] = rep
        sharded = bench_config(
            dev_model, ServerConfig(batching=True, max_batch=128,
                                    batch_window_ms=2.0,
                                    serving_mode="sharded"),
            n_req, min(hi_threads, 64), "mesh_sharded_microbatch")
        out["sharded"] = sharded
    return out


def bench_canary(model: ALSModel, candidate: ALSModel, fraction: float,
                 n_requests: int, n_threads: int) -> dict:
    """Stable + candidate bound side by side: the canary splitter
    routes ``fraction`` of the cohort to the candidate while the gate
    is held open (no ramp), then both arms' server-side latency series
    are reported together."""
    from predictionio_tpu.rollout import HealthPolicy

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "servebench"))
    ctx = Context(app_name="servebench", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("servebench", rank=model.params.rank)
    now = datetime.now(timezone.utc)
    for iid in ("bench-stable", "bench-cand"):
        storage.engine_instances().insert(EngineInstance(
            id=iid, status=STATUS_COMPLETED, start_time=now,
            end_time=now, engine_id="bench", engine_version="1",
            engine_variant="engine.json", engine_factory="synthetic"))
    qs = QueryServer(ctx, engine, ep, [model],
                     storage.engine_instances().get("bench-stable"),
                     ServerConfig())
    srv = create_engine_server(qs, host="127.0.0.1", port=0)
    srv.start_background()
    port = srv.port
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status.json",
                    timeout=30) as resp:
                if json.loads(resp.read()).get("servingWarm"):
                    break
            time.sleep(0.5)
        # hold the gate open for the whole bench: no ramp, no verdict
        qs.start_canary("bench-cand", fraction=fraction,
                        policy=HealthPolicy(window_sec=3600,
                                            min_queries=1 << 30),
                        models=[candidate], actor="serving-bench")
        qs._candidate.warm_done.wait(timeout=300)

        rng = np.random.default_rng(2)
        users = rng.integers(0, model.n_users, n_requests)
        stats, _wall = run_load(_query_sender(port, users),
                                n_requests, n_threads)
        errors = stats.errors
        arms = qs.release_arms()
    finally:
        srv.shutdown()
    if errors:
        raise RuntimeError(
            f"canary bench: {len(errors)} failed requests "
            f"(first: {errors[0]})")

    def arm_row(arm: dict) -> dict:
        lat = arm.get("latency") or {}
        return {
            "queries": arm["queries"],
            "errors": arm["errors"],
            "p50_ms": (round(lat["p50"] * 1000, 2)
                       if lat.get("p50") is not None else None),
            "p99_ms": (round(lat["p99"] * 1000, 2)
                       if lat.get("p99") is not None else None),
        }

    return {
        "config": "canary_split",
        "fraction": fraction,
        "stable": arm_row(arms["stable"]),
        "candidate": arm_row(arms["candidate"]),
    }


def quant_battery(n_items_dev: int, rank: int, n_req: int,
                  n_threads: int, hi_threads: int, quant: str,
                  f32_per_query: dict | None = None,
                  f32_micro: dict | None = None) -> list:
    """The --quant view (ISSUE 13): the SAME workload against the
    device per-query path and the micro-batched lane with
    ``serving_quant=DTYPE`` (+ the autotuned top-k kernel), side by
    side with the f32 einsum lane — reusing the standard battery's f32
    rows when the caller already measured them. Emits a
    ``serving_quant`` summary row (embedded in the BENCH line): the
    acceptance view is the quant/fused lane beating the f32 einsum
    lane on the benched path at equal p99."""
    dev_model = synth_model(50_000, n_items_dev, rank, device=True)
    hi_req = max(n_req, 8 * hi_threads)
    rows = []
    if f32_per_query is None:
        f32_per_query = bench_config(
            dev_model, ServerConfig(), n_req, n_threads,
            "device_per_query")
        rows.append(f32_per_query)
    if f32_micro is None:
        f32_micro = bench_config(
            dev_model, ServerConfig(batching=True, max_batch=128,
                                    batch_window_ms=2.0),
            hi_req, hi_threads, "device_microbatch_staged")
        rows.append(f32_micro)
    q_per_query = bench_config(
        dev_model, ServerConfig(serving_quant=quant), n_req,
        n_threads, f"device_per_query_{quant}")
    q_micro = bench_config(
        dev_model, ServerConfig(batching=True, max_batch=128,
                                batch_window_ms=2.0,
                                serving_quant=quant),
        hi_req, hi_threads, f"device_microbatch_{quant}")
    rows += [q_per_query, q_micro]
    summary = {
        "config": "serving_quant",
        "quant": quant,
        "per_query_f32_p50_ms": f32_per_query.get("p50_ms"),
        "per_query_quant_p50_ms": q_per_query.get("p50_ms"),
        "micro_f32_qps": f32_micro.get("qps"),
        "micro_quant_qps": q_micro.get("qps"),
        "micro_f32_p99_ms": f32_micro.get("p99_ms"),
        "micro_quant_p99_ms": q_micro.get("p99_ms"),
    }
    if f32_micro.get("qps") and q_micro.get("qps"):
        summary["qps_x"] = round(q_micro["qps"] / f32_micro["qps"], 2)
    if f32_micro.get("p99_ms") and q_micro.get("p99_ms"):
        summary["p99_x"] = round(
            f32_micro["p99_ms"] / q_micro["p99_ms"], 2)
    rows.append(summary)
    return rows


def bench_cached_pair(n_items_dev: int, rank: int, n_req: int,
                      n_threads: int, zipf) -> list:
    """The --cache view: the SAME Zipf-skewed workload against the
    device per-query config with the serving cache off vs on, plus the
    pure cache-hit probe — cached-vs-uncached p50/p99 side by side."""
    dev_model = synth_model(50_000, n_items_dev, rank, device=True)
    uncached = bench_config(
        dev_model, ServerConfig(), n_req, n_threads,
        "device_per_query_zipf", zipf=zipf)
    cached_cfg = ServerConfig(
        serving_cache=True, cache_ttl_sec=600.0,
        hot_entities=512, hot_refresh_every=64)
    cached = bench_config(
        dev_model, cached_cfg, n_req, n_threads,
        "device_per_query_cached", zipf=zipf,
        hot_hit_probe=max(100, n_req // 4))
    hit_p50 = (cached.get("hot_hit") or {}).get("p50_ms")
    if hit_p50 is not None and uncached["p50_ms"]:
        # the acceptance ratio: hot-query (cache-hit) p50 against the
        # UNCACHED device per-query p50
        cached["hit_vs_uncached_p50"] = round(
            hit_p50 / uncached["p50_ms"], 4)
    return [uncached, cached]


def main() -> None:
    argv = sys.argv[1:]
    canary_fraction = None
    if "--canary" in argv:
        i = argv.index("--canary")
        canary_fraction = float(argv[i + 1])
        del argv[i:i + 2]
    zipf_alpha = None
    if "--zipf" in argv:
        i = argv.index("--zipf")
        zipf_alpha = float(argv[i + 1])
        del argv[i:i + 2]
    with_cache = False
    if "--cache" in argv:
        with_cache = True
        argv.remove("--cache")
    with_mesh = False
    if "--mesh" in argv:
        with_mesh = True
        argv.remove("--mesh")
    arrival_rate = None
    if "--arrival-rate" in argv:
        i = argv.index("--arrival-rate")
        arrival_rate = float(argv[i + 1])
        del argv[i:i + 2]
    quant = None
    if "--quant" in argv:
        i = argv.index("--quant")
        quant = argv[i + 1]
        del argv[i:i + 2]
        if quant not in ("bf16", "int8"):
            raise SystemExit(f"--quant must be bf16 or int8, "
                             f"got {quant!r}")
    sys.argv[1:] = argv
    n_items_dev = int(sys.argv[1]) if len(sys.argv) > 1 else 1_200_000
    rank = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    n_threads = int(os.environ.get("SERVE_THREADS", "8"))
    n_requests = int(os.environ.get("SERVE_REQUESTS", "400"))

    assert n_items_dev * rank > HOST_SERVE_WORK, \
        "device catalog must exceed HOST_SERVE_WORK to force the MXU path"

    import jax

    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()
    device_kind = jax.devices()[0].device_kind

    hi = int(os.environ.get("SERVE_THREADS_HI", "256"))
    if arrival_rate is not None:
        # open-loop mode REPLACES the closed-loop battery: fixed-rate
        # arrivals against the staged and serial micro-batch paths at
        # the same offered qps — sweep the rate to trace the knee
        from predictionio_tpu.server.engineserver import ServerConfig

        dev_model = synth_model(50_000, n_items_dev, rank, device=True)
        n_open = max(n_requests, int(arrival_rate * 10))
        results = [
            bench_open_loop(
                dev_model, ServerConfig(batching=True, max_batch=128,
                                        batch_window_ms=2.0),
                arrival_rate, n_open, hi, "open_loop_staged"),
            bench_open_loop(
                dev_model, ServerConfig(batching=True, max_batch=128,
                                        batch_window_ms=2.0,
                                        serving_pipeline="serial"),
                arrival_rate, n_open, hi, "open_loop_serial"),
        ]
        print(json.dumps({
            "bench": "serving_queries_json_open_loop",
            "device": device_kind,
            "rank": rank,
            "n_items_device": n_items_dev,
            "offered_qps": arrival_rate,
            "results": results,
        }))
        return
    battery = standard_battery(n_items_dev, rank, n_requests,
                               n_threads, hi)
    results = list(battery.values())
    if quant is not None:
        results.extend(quant_battery(
            n_items_dev, rank, n_requests, n_threads, hi, quant,
            f32_per_query=battery.get("per_query"),
            f32_micro=battery.get("microbatch")))
    if with_mesh:
        scaling = mesh_scaling_battery(n_items_dev, rank, n_requests, hi)
        results.append({"config": "mesh_scaling", **scaling})
    if with_cache:
        results.extend(bench_cached_pair(n_items_dev, rank, n_requests,
                                         n_threads, zipf_alpha))
    if canary_fraction is not None:
        dev_model = synth_model(50_000, n_items_dev, rank, device=True)
        cand_model = synth_model(50_000, n_items_dev, rank, device=True)
        results.append(bench_canary(dev_model, cand_model,
                                    canary_fraction,
                                    max(n_requests, 200), n_threads))
    print(json.dumps({
        "bench": "serving_queries_json",
        "device": device_kind,
        "rank": rank,
        "n_items_device": n_items_dev,
        "threads": n_threads,
        "results": results,
    }))


if __name__ == "__main__":
    main()
