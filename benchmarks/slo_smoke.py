"""SLO-engine smoke (ISSUE 15) — the CI gate for burn-rate detection.

End-to-end over REAL HTTP on whatever device is available (CI: CPU),
against the committed ``slo/specs/ci.json`` (short smoke windows):

1. deploy a micro-batched engine server with the SLO engine evaluating
   the committed specs every 200 ms and the flight recorder on;
2. **baseline**: open-loop queries past the slow window — every spec
   must settle ``ok`` with zero violations (the committed baseline
   passes);
3. **seeded regression**: arm a latency fault at the PR 11
   ``serving.dispatch`` injection point (every batched dispatch sleeps
   past the latency spec's threshold) and keep the load coming — the
   fast AND slow windows must rise past their burn thresholds, the
   breach must be counted in ``pio_slo_violations_total``, and the
   flight recorder must hold a trace carrying the fault attribution
   (``faultPoint=serving.dispatch``) — every violation arrives with
   exemplar evidence;
4. **recovery**: clear the fault — the spec must leave ``breach``
   within the fast window's horizon (violations stay counted).

Prints one JSON line; exits non-zero on any violation of the above —
this is the demonstration that a real SLO regression FAILS CI while
the healthy baseline passes.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _loadgen import (  # noqa: E402
    expect_json_field,
    json_post_sender,
    run_load,
)

SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "slo", "specs", "ci.json")

#: the latency spec the injected fault must breach (slo/specs/ci.json)
LATENCY_SPEC = "queries-p99-latency"


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read())


def _spec_state(port: int, name: str) -> dict:
    for sp in (_get(port, "/slo.json").get("specs") or []):
        if sp["name"] == name:
            return sp
    raise RuntimeError(f"spec {name!r} not evaluated by the server")


def _drive(port: int, seconds: float, rate: float = 25.0) -> None:
    rng = np.random.default_rng(5)
    n = int(rate * seconds)
    users = rng.integers(0, 200, n)
    sender = json_post_sender(
        port, "/queries.json",
        body_fn=lambda k: json.dumps({"user": f"u{users[k]}",
                                      "num": 5}).encode(),
        check=expect_json_field("itemScores"))
    stats, _wall = run_load(sender, n, 8, rate_qps=rate)
    if stats.errors:
        raise RuntimeError(
            f"{len(stats.errors)} failed queries under smoke load "
            f"(first: {stats.errors[0]})")


def _await_state(port: int, name: str, want, timeout_s: float) -> dict:
    deadline = time.monotonic() + timeout_s
    sp = _spec_state(port, name)
    while time.monotonic() < deadline:
        sp = _spec_state(port, name)
        if sp["state"] in want:
            return sp
        time.sleep(0.25)
    return sp


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    from predictionio_tpu import faults
    from predictionio_tpu.server.engineserver import ServerConfig
    from serving_bench import _boot_server, _wait_warm, synth_model

    model = synth_model(200, 200, 8, device=False)
    qs, srv = _boot_server(model, ServerConfig(
        batching=True, max_batch=16, batch_window_ms=2.0,
        slo_specs=SPEC_PATH, slo_interval_ms=200.0,
        queue_deadline_ms=10_000.0))
    port = srv.port
    checks: dict = {}
    out: dict = {"bench": "slo_smoke", "specs": SPEC_PATH}
    try:
        _wait_warm(port, "slo_smoke")

        # phase 1 — committed baseline: drive past the slow window,
        # every spec settles ok with zero violations
        _drive(port, seconds=10.0)
        baseline = _await_state(port, LATENCY_SPEC, ("ok",), 5.0)
        states = {sp["name"]: sp["state"]
                  for sp in _get(port, "/slo.json")["specs"]}
        out["baseline"] = {"states": states,
                           "violations": baseline["violations"]}
        checks["baseline_ok"] = (
            baseline["state"] == "ok"
            and baseline["violations"] == 0
            and all(s in ("ok", "idle", "insufficient_data")
                    for s in states.values()))

        # phase 2 — seeded regression: every batched dispatch now
        # sleeps well past the latency spec's threshold
        faults.inject("serving.dispatch", "latency", delay_ms=400.0)
        t_inject = time.monotonic()
        _drive(port, seconds=12.0, rate=20.0)
        breached = _await_state(port, LATENCY_SPEC, ("breach",), 10.0)
        out["breach"] = {k: breached[k] for k in
                         ("state", "burnFast", "burnSlow",
                          "violations", "budgetRemaining")}
        out["detect_sec"] = round(time.monotonic() - t_inject, 1)
        checks["breach_detected"] = breached["state"] == "breach"
        checks["violation_counted"] = breached["violations"] >= 1
        metrics_text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ).read().decode()
        checks["violations_series_exported"] = any(
            ln.startswith("pio_slo_violations_total")
            and f'slo="{LATENCY_SPEC}"' in ln
            and not ln.rstrip().endswith(" 0")
            for ln in metrics_text.splitlines())
        checks["burn_series_exported"] = \
            "pio_slo_burn_rate" in metrics_text

        # the evidence contract: a retained trace carries the fault
        # attribution from the injected dispatch
        slowest = _get(port, "/trace.json?slowest=20").get("traces") or []
        fault_traces = [t for t in slowest
                        if (t.get("attrs") or {}).get("faultPoint")
                        == "serving.dispatch"]
        out["retained_traces"] = len(slowest)
        out["fault_attributed_traces"] = len(fault_traces)
        checks["trace_retained_with_fault_attr"] = bool(fault_traces)
        # while the breach burned, the tracer was in force-retention
        trace_status = _get(port, "/trace.json")
        retained_by = trace_status.get("retainedByReason") or {}
        out["retained_by_reason"] = retained_by
        checks["burn_force_retention"] = (
            trace_status.get("forcedReason") == "slo"
            or retained_by.get("slo", 0) > 0)

        # phase 3 — recovery: clear the fault, keep serving; the spec
        # leaves breach within the fast window's horizon
        faults.clear("serving.dispatch")
        _drive(port, seconds=8.0)
        recovered = _await_state(port, LATENCY_SPEC,
                                 ("ok", "idle"), 15.0)
        out["recovery"] = {"state": recovered["state"],
                           "violations": recovered["violations"]}
        checks["recovered"] = recovered["state"] in ("ok", "idle")
        checks["violations_persist"] = recovered["violations"] >= 1
    finally:
        faults.clear()
        srv.shutdown()
    ok = all(checks.values())
    print(json.dumps({"ok": ok, **out, **checks}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
