"""Sequential-recommendation training throughput on the attached device.

The sequential template (`templates/sequential.py`, beyond the
reference — it has no sequence model at all) trains a causal-attention
next-item model (`models/seqrec.py`); this probe gives it an on-chip
performance artifact like ALS's bench: steps/s, sequences/s, and an
attention+matmul FLOP estimate, plus a long-sequence datapoint that
exercises the attention path where the MXU actually works per token.

Prints one JSON line per configuration.

Usage: python benchmarks/seqrec_bench.py
Env:   SEQ_CONFIGS="N,L,dim,blocks;..." (default below)
       SEQ_STEPS=30 (timed steps per config)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def flops_per_step(B, L, d, blocks, n_items, n_neg):
    """Forward+backward matmul/attention FLOP estimate (3x forward)."""
    attn = 2 * 2 * B * L * L * d            # QK^T + AV
    proj = 4 * 2 * B * L * d * d            # q,k,v,o projections
    ffn = 2 * 2 * B * L * d * (4 * d)       # 2 matmuls, 4x hidden
    head = 2 * B * L * (n_neg + 1) * d      # sampled-softmax logits
    fwd = blocks * (attn + proj + ffn) + head
    return 3 * fwd


def main() -> None:
    cfgs = os.environ.get(
        "SEQ_CONFIGS",
        "8192,50,48,1;8192,200,64,2;2048,1024,64,2").split(";")
    steps = int(os.environ.get("SEQ_STEPS", "30"))

    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.seqrec import (
        SeqRecParams,
        _init_weights,
        _train_step,
    )

    dev = jax.devices()[0].device_kind
    for cfg in cfgs:
        try:  # parsing inside: one malformed entry must not kill the rest
            N, L, dim, blocks = (int(x) for x in cfg.split(","))
            n_items = 27_000
            rng = np.random.default_rng(0)
            lens = rng.integers(L // 2, L + 1, N)
            seqs = np.full((N, L), -1, np.int32)
            for i, ln in enumerate(lens):  # host-side synthetic seqs
                seqs[i, :ln] = rng.integers(0, n_items, ln)
            p = SeqRecParams(dim=dim, heads=max(dim // 32, 1),
                             num_blocks=blocks, max_len=L, num_epochs=1,
                             batch_size=min(N, 256 if L <= 200 else 32),
                             seed=7)
            key = jax.random.key(7)
            w = _init_weights(key, n_items, p)
            opt_m = {k: jnp.zeros_like(v) for k, v in w.items()}
            opt_v = {k: jnp.zeros_like(v) for k, v in w.items()}
            step = jnp.zeros((), jnp.int32)
            B = p.batch_size
            xb = jnp.asarray(seqs[:B])
            key, sub = jax.random.split(key)
            w, opt_m, opt_v, step, loss = _train_step(
                w, opt_m, opt_v, step, xb, sub, p, n_items)  # compile
            float(loss)
            t0 = time.monotonic()
            for s in range(steps):
                rows = (np.arange(B) + s * B) % N
                xb = jnp.asarray(seqs[rows])
                key, sub = jax.random.split(key)
                w, opt_m, opt_v, step, loss = _train_step(
                    w, opt_m, opt_v, step, xb, sub, p, n_items)
            float(loss)  # hard sync
            dt = time.monotonic() - t0
            fl = flops_per_step(B, L, dim, blocks, n_items,
                                p.n_negatives)
            print(json.dumps({
                "metric": "seqrec_train",
                "batch": B, "seq_len": L, "dim": dim,
                "blocks": blocks,
                "steps_per_s": round(steps / dt, 2),
                "sequences_per_s": round(steps * B / dt, 1),
                "tokens_per_s": round(steps * B * L / dt, 1),
                "model_tflops": round(fl * steps / dt / 1e12, 3),
                "loss": round(float(loss), 4),
                "device": dev,
            }), flush=True)
        except Exception as e:  # noqa: BLE001 — next config
            print(json.dumps({"config": cfg,
                              "error": str(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
