"""Benchmark: implicit-ALS training throughput on the flagship workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload is a synthetic MovieLens-20M-shaped problem (the BASELINE.md
target: 138k users × 27k items; here scaled by BENCH_SCALE so the default
run finishes in minutes on one chip). The reference publishes no numbers
(BASELINE.md: "none found"), so ``vs_baseline`` is measured against a
recorded MLlib-ALS-equivalent throughput estimate below; until the
reference is benchmarked on equal hardware this is a bookkeeping ratio,
not a claim.
"""

import json
import os
import time

import numpy as np

#: Spark-MLlib-local ALS throughput on the same synthetic shape, in rated
#: entries per second per iteration. Placeholder until measured (the
#: reference ships no numbers); recorded here so the ratio is stable
#: across rounds.
BASELINE_RATINGS_PER_SEC = 2_000_000.0


def main():
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    n_users = int(138_000 * scale)
    n_items = int(27_000 * scale)
    nnz = int(20_000_000 * scale)
    rank = 64
    iterations = 5

    import jax

    from predictionio_tpu.models.als import (
        ALSParams,
        RatingsCOO,
        pack_ratings,
        train_als,
    )

    rng = np.random.default_rng(0)
    # zipf-ish popularity for items, uniform users — MovieLens-like skew
    items = (np.random.default_rng(1).zipf(1.3, size=nnz) % n_items).astype(np.int32)
    users = rng.integers(0, n_users, nnz).astype(np.int32)
    vals = np.ones(nnz, dtype=np.float32)
    ratings = RatingsCOO(users, items, vals, n_users, n_items)

    params = ALSParams(rank=rank, num_iterations=1, implicit_prefs=True,
                       alpha=40.0, reg=0.01, seed=3, max_history=256)

    # pack once (the COO→device transfer + sort; sweeps amortize this),
    # then warm up the compiled half-steps
    packed = pack_ratings(ratings, params)
    U, V = train_als(ratings, params, packed=packed)
    jax.block_until_ready((U, V))

    params_run = ALSParams(rank=rank, num_iterations=iterations,
                           implicit_prefs=True, alpha=40.0, reg=0.01,
                           seed=3, max_history=256)
    # best of 3 timed runs — the shared-tunnel TPU shows run-to-run noise
    dt = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        U, V = train_als(ratings, params_run, packed=packed)
        jax.block_until_ready((U, V))
        dt = min(dt, time.monotonic() - t0)

    ratings_per_sec = nnz * iterations / dt
    print(json.dumps({
        "metric": "als_implicit_train_throughput",
        "value": round(ratings_per_sec, 1),
        "unit": "ratings/s/iter",
        "vs_baseline": round(ratings_per_sec / BASELINE_RATINGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
