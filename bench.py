"""Benchmark: implicit-ALS training throughput on the flagship workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"cpu_baseline_measured", "dropped_entries", ...}.

Honesty model (BASELINE.md "bench accounting"):

- The workload is a synthetic MovieLens-20M-shaped problem (138k users ×
  27k items, 20M implicit ratings, zipf(1.3) item skew, rank 64).
- ``history_mode="split"`` trains on **every** rating regardless of skew
  (``dropped_entries`` is asserted 0) — the same contract as MLlib ALS,
  which uses every rating (reference ``ALSAlgorithm.scala:75-85``).
- ``vs_baseline`` divides by a CPU baseline **measured in this same
  process on this same host**: a numpy/BLAS implementation of the
  identical Hu-Koren-Volinsky + ALS-WR math (CSR per-row gemms + batched
  LAPACK solves — structurally what MLlib does inside each Spark task),
  run on a 1/10-scale slice and reported per-rating. The reference
  publishes no numbers of its own (BASELINE.md: "none found").
- ``mfu`` is achieved FLOP/s over the chip's peak, where achieved FLOP/s
  uses the padded-work FLOP model (`als_flops_per_iter`) — the work the
  device actually executes — and peak is the device's headline bf16
  matmul rate (conservative for this f32 run; see table below).
"""

import json
import os
import random
import re
import subprocess
import sys
import time

import numpy as np

#: CSI/SGR escape sequences (jax's colored tracebacks) — stripped from
#: error strings before they land in BENCH JSON, which must stay
#: greppable plain text (BENCH_LASTGOOD.json carried raw `\x1b[2m`)
_ANSI_RE = re.compile(r"\x1b\[[0-9;]*[A-Za-z]")
#: stray escape FRAGMENTS a mid-sequence truncation leaves behind
#: (BENCH_r05 race_errors ended in a bare `\x1b[2m<timestamp>`)
_ANSI_FRAG_RE = re.compile(r"\x1b\[?[0-9;]*")
#: log-line timestamps (ISO dates, times) — noise in a recorded error
_TS_RE = re.compile(
    r"\d{4}-\d{2}-\d{2}[T ]?(\d{2}:\d{2}(:\d{2}(\.\d+)?)?)?Z?")


def _strip_ansi(s: str) -> str:
    return _ANSI_FRAG_RE.sub("", _ANSI_RE.sub("", s))


def _clean_err(s: str, limit: int = 160) -> str:
    """One BENCH-safe line out of an arbitrary exception string: ANSI
    escapes (and truncation fragments) stripped, log timestamps
    dropped, whitespace collapsed, bounded length. Raw multi-line jax
    tracebacks previously leaked `\\n\\x1b[2m2026-07-31T20:57` tails
    into the recorded race_errors (BENCH_r05)."""
    s = _strip_ansi(str(s))
    s = _TS_RE.sub("", s)
    s = " ".join(s.split())
    return s[:limit].rstrip()

#: Headline peak matmul FLOP/s by TPU generation (bf16; public spec
#: sheets). MFU is reported against this even though the bench runs f32 —
#: a conservative (lower) MFU. Unknown devices → mfu null.
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops() -> float | None:
    """Peak for ONE device — the bench trains meshless on a single chip
    (the driver exposes one real TPU), so multi-device peaks would
    understate MFU."""
    import jax

    kind = jax.devices()[0].device_kind
    for name, peak in PEAK_FLOPS.items():
        if kind.startswith(name):
            return peak
    return None


def cpu_als_baseline(n_users: int, n_items: int, nnz: int, rank: int,
                     alpha: float, reg: float, seed: int = 7) -> float:
    """Measured same-host CPU throughput (ratings/s/iter) of the identical
    implicit-ALS math in numpy: per-row CSR gemms for the normal-equation
    blocks + one batched LAPACK solve per side. This is the MLlib-ALS
    structural equivalent (per-user solves inside tasks) on this machine's
    CPU/BLAS; timing excludes CSR packing, mirroring the TPU bench which
    times iterations with ``packed=`` reuse."""
    rng = np.random.default_rng(seed)
    items = (np.random.default_rng(seed + 1).zipf(1.3, size=nnz)
             % n_items).astype(np.int32)
    users = rng.integers(0, n_users, nnz).astype(np.int32)
    vals = np.ones(nnz, dtype=np.float32)

    def csr(rows, cols, v, n_rows):
        order = np.argsort(rows, kind="stable")
        r, c, w = rows[order], cols[order], v[order]
        counts = np.bincount(r, minlength=n_rows)
        indptr = np.zeros(n_rows + 1, dtype=np.int64)
        np.cumsum(counts, out=indptr[1:])
        return indptr, c, w

    u_indptr, u_cols, u_vals = csr(users, items, vals, n_users)
    i_indptr, i_cols, i_vals = csr(items, users, vals, n_items)

    U = (rng.standard_normal((n_users, rank)).astype(np.float32)
         / np.sqrt(rank))
    V = (rng.standard_normal((n_items, rank)).astype(np.float32)
         / np.sqrt(rank))

    def half_step(fixed, indptr, cols, w, n_rows):
        G = fixed.T @ fixed
        A = np.empty((n_rows, rank, rank), dtype=np.float32)
        b = np.zeros((n_rows, rank), dtype=np.float32)
        eye = np.eye(rank, dtype=np.float32)
        for i in range(n_rows):
            s, e = indptr[i], indptr[i + 1]
            n = e - s
            if n == 0:
                A[i] = G + reg * eye
                continue
            F = fixed[cols[s:e]]           # [n, r] gather
            c1 = alpha * w[s:e]            # c - 1
            A[i] = G + (F * c1[:, None]).T @ F + (reg * n) * eye
            b[i] = (c1 + 1.0) @ F
        return np.linalg.solve(A, b[..., None])[..., 0].astype(np.float32)

    t0 = time.monotonic()
    U = half_step(V, u_indptr, u_cols, u_vals, n_users)
    V = half_step(U, i_indptr, i_cols, i_vals, n_items)
    dt = time.monotonic() - t0
    return nnz / dt


def eval_ndcg_at_k(U, V, train_users, train_items, test_users, test_items,
                   n_items: int, k: int = 10, sample: int = 2048,
                   seed: int = 5) -> float:
    """NDCG@k of the trained factors on a held-out slice (binary
    relevance, train items masked out of the ranking) — closes the
    quality loop on the SAME device-trained factors the bench times
    (role of the reference template's MetricEvaluator quality check,
    ``Evaluation.scala:32-89``)."""
    import jax
    import jax.numpy as jnp

    users = np.unique(test_users)
    rng = np.random.default_rng(seed)
    if len(users) > sample:
        users = rng.choice(users, size=sample, replace=False)
    users = np.sort(users)
    row_of = {int(u): j for j, u in enumerate(users)}
    S = len(users)

    # top-(k + max_train) then host-filter the train items: masking the
    # [S, n_items] score matrix on device would need a huge scatter
    sel_tr = np.isin(train_users, users)
    tr_u = train_users[sel_tr]
    tr_i = train_items[sel_tr]
    counts = np.bincount(tr_u, minlength=0)
    max_tr = int(counts.max(initial=0))
    k_fetch = min(k + max_tr, n_items)

    @jax.jit
    def topk(U_s, V_all):
        scores = U_s @ V_all.T
        mask = jnp.arange(V_all.shape[0]) < n_items
        scores = jnp.where(mask[None, :], scores, -jnp.inf)
        return jax.lax.top_k(scores, k_fetch)[1]

    ids = np.asarray(topk(jnp.asarray(U)[jnp.asarray(users)],
                          jnp.asarray(V)))
    train_sets = [set() for _ in range(S)]
    for u, i in zip(tr_u, tr_i):
        train_sets[row_of[int(u)]].add(int(i))
    test_sets = [set() for _ in range(S)]
    for u, i in zip(test_users, test_items):
        j = row_of.get(int(u))
        if j is not None:
            test_sets[j].add(int(i))

    from predictionio_tpu.controller.metric import ndcg_at_k

    total = 0.0
    for j in range(S):
        ranked = [int(i) for i in ids[j]
                  if int(i) not in train_sets[j]][:k]
        score = ndcg_at_k(ranked, test_sets[j], k)
        total += score if score is not None else 0.0
    return total / max(S, 1)


def main():
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    cpu_scale = float(os.environ.get("BENCH_CPU_SCALE", "0.1"))
    n_users = int(138_000 * scale)
    n_items = int(27_000 * scale)
    nnz = int(20_000_000 * scale)
    rank = int(os.environ.get("BENCH_RANK", "64"))
    gram_mode = os.environ.get("BENCH_GRAM", "auto")
    iterations = 5
    alpha, reg = 40.0, 0.01

    import jax

    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    # Fail FAST when the device tunnel is hung: jax.devices() through a
    # dead tunnel blocks indefinitely (observed all of round 3), which
    # would burn the supervisor's whole attempt timeout per retry. Probe
    # in a daemon thread with its own bound; rc=3 tells the supervisor
    # this was an init hang, not a slow run.
    import concurrent.futures as _cf

    probe_s = float(os.environ.get("BENCH_INIT_TIMEOUT", "180"))
    with _cf.ThreadPoolExecutor(1) as _pool:
        fut = _pool.submit(jax.devices)
        try:
            devs = fut.result(timeout=probe_s)
        except _cf.TimeoutError:
            sys.stderr.write(
                f"device backend init exceeded {probe_s}s (hung "
                f"tunnel)\n")
            os._exit(3)  # the probe thread is stuck; no clean join
    sys.stderr.write(f"devices: {devs}\n")

    from predictionio_tpu.models.als import (
        ALSParams,
        RatingsCOO,
        als_flops_per_iter,
        pack_ratings,
        train_als,
    )

    def hard_sync(x):
        """Force completion with a real device→host transfer.
        ``block_until_ready`` returns early through remote-device
        tunnels (measured: a '32ms' run whose first output element then
        took 10s to arrive), which is exactly how round 1's headline
        number got inflated ~475×."""
        np.asarray(jax.device_get(x[0, :1]))

    rng = np.random.default_rng(0)
    # zipf-ish popularity for items, uniform users — MovieLens-like skew
    items = (np.random.default_rng(1).zipf(1.3, size=nnz) % n_items).astype(np.int32)
    users = rng.integers(0, n_users, nnz).astype(np.int32)
    vals = np.ones(nnz, dtype=np.float32)
    ratings = RatingsCOO(users, items, vals, n_users, n_items)

    # bucketed layout: every rating trains, whatever the skew (0 drops)
    params = ALSParams(rank=rank, num_iterations=1, implicit_prefs=True,
                       alpha=alpha, reg=reg, seed=3,
                       gram_mode=gram_mode)

    # pack once (the COO→device transfer + sort; sweeps amortize this),
    # then warm up the compiled half-steps
    packed = pack_ratings(ratings, params)
    def kept_entries(h):
        if hasattr(h, "buckets"):  # BucketedHistories
            return sum(int(np.asarray(b.counts, dtype=np.int64).sum())
                       for b in h.buckets)
        return int(np.asarray(h.counts, dtype=np.int64).sum())

    dropped = 2 * nnz - kept_entries(packed[0]) - kept_entries(packed[1])
    assert dropped == 0, f"bench must train on all ratings; dropped={dropped}"

    # gram-mode race: the packed layouts are gram-independent, so under
    # "auto" the bench times BOTH realizations (baseline einsum vs the
    # pair-packed MXU tiling) and reports the winner honestly
    peak = device_peak_flops()

    gather_env = os.environ.get("BENCH_GATHER", "auto")

    def race(rank_r: int, repeats: int = 3, *, ratings_in=None,
             packed_in=None, nnz_in=None, cands_override=None,
             block_rows=None):
        """Time the training run at ``rank_r`` across the gram-mode ×
        gather-dtype candidates; return the winner's numbers. The
        gather axis (round 4): gathering factor rows from a bf16
        shadow keeps the big table VMEM-resident alongside the Pallas
        solve — measured 1.48× whole-training at 20M/rank 64 — but the
        winner must be MEASURED, not assumed, and its quality flows
        into the ndcg10 the bench reports (the holdout retrain uses
        the winning params). A failed candidate is skipped, surfaced
        in the result's ``race_errors``, and BLOCKS the persistent
        gram_autotune record if it was an f32 candidate (a partial f32
        race must not write a winner the unmeasured mode might beat).
        ``ratings_in/packed_in/nnz_in`` let the rank-128 subsample
        fallback reuse this exact timing/accounting path."""
        r_in = ratings if ratings_in is None else ratings_in
        p_in = packed if packed_in is None else packed_in
        n_in = nnz if nnz_in is None else nnz_in
        if gram_mode == "auto":
            gram_cands = ["einsum", "pair"]
            # the fused gather+gram kernel joins the race wherever its
            # Pallas lowering compiles (ISSUE 7) — the measured winner,
            # not the roofline argument, is what gets persisted
            try:
                from predictionio_tpu.ops.fused_gram import (
                    fused_gram_supported,
                )

                if fused_gram_supported():
                    gram_cands.append("fused")
            except Exception:  # noqa: BLE001 — probe is advisory
                pass
        else:
            gram_cands = [gram_mode]
        gather_cands = ["float32", "bfloat16"] if gather_env == "auto" \
            else [gather_env]
        cands = cands_override or [(gm, gd) for gm in gram_cands
                                   for gd in gather_cands]
        # normalize to (gram, gather, block_rows); rank 128 adds the
        # small-blocks candidate: block_rows=1024 is the one config
        # that reliably COMPILES the full-size program through the
        # remote helper (auto tiling usually 500s), and it wins the
        # race when auto-tiled candidates do survive (32.3M vs 27.4M
        # ratings/s/iter in BENCH_LASTGOOD)
        cands = [c if len(c) == 3 else (*c, block_rows) for c in cands]
        if rank_r == 128 and cands_override is None \
                and gram_mode == "auto" \
                and gather_env in ("auto", "bfloat16"):
            # honor a forced-f32 sweep: this candidate is bf16-only,
            # so it must not smuggle bf16 into a BENCH_GATHER=float32
            # run (the fallback path keeps the honest-f32-error
            # contract there)
            cands.append(("einsum", "bfloat16", 1024))
        best_dt, best_gm, best_params = float("inf"), cands[0][0], None
        best_f32_dt, best_f32_gm = float("inf"), cands[0][0]
        cand_errors = []
        retried = 0
        f32_failed = False
        for gm, gd, br in cands:
            p_run = ALSParams(rank=rank_r, num_iterations=iterations,
                              implicit_prefs=True, alpha=alpha, reg=reg,
                              seed=3, gram_mode=gm, gather_dtype=gd,
                              block_rows=br)
            # bounded exponential backoff with jitter on transient
            # compile-service failures (BENCH_r05 race_errors: several
            # candidates died on `remote_compile: HTTP 500` bursts from
            # the shared tpu_compile_helper — a fixed single 10s retry
            # re-collided with the same burst; jitter decorrelates and
            # the cap bounds a dead helper's cost per candidate)
            max_retries = int(os.environ.get("BENCH_COMPILE_RETRIES",
                                             "3"))
            for attempt in range(max_retries + 1):
                try:
                    U, V = train_als(r_in, p_run, packed=p_in)  # warm
                    hard_sync(V)
                    # best-of-N — shared tunnels show run-to-run noise
                    for _ in range(repeats):
                        t0 = time.monotonic()
                        U, V = train_als(r_in, p_run, packed=p_in)
                        hard_sync(V)
                        d = time.monotonic() - t0
                        if d < best_dt:
                            best_dt, best_gm, best_params = d, gm, p_run
                        if gd == "float32" and d < best_f32_dt:
                            best_f32_dt, best_f32_gm = d, gm
                    break
                except Exception as ce:  # noqa: BLE001 — one candidate's
                    # compile failure (e.g. rank-128 f32 through the
                    # tunnel helper) must not kill candidates that work
                    transient = ("HTTP 500" in str(ce)
                                 or "remote_compile" in str(ce))
                    if attempt < max_retries and transient:
                        retried += 1
                        delay = min(5.0 * (2 ** attempt), 60.0)
                        time.sleep(delay * random.uniform(0.5, 1.5))
                        continue
                    cand_errors.append(
                        f"{gm}/{gd}{f'/br{br}' if br else ''}: "
                        f"{_clean_err(ce, 120)}")
                    f32_failed = f32_failed or gd == "float32"
                    break
        if best_params is None:
            raise RuntimeError("every race candidate failed: "
                               + " | ".join(cand_errors))
        if gram_mode == "auto" and len(gram_cands) > 1 \
                and best_f32_dt < float("inf") and not f32_failed \
                and cands_override is None:
            # persist the gram winner measured AT THE DEFAULT gather
            # dtype — gram_autotune consumers run gather_dtype=float32
            # unless told otherwise, so storing the global (possibly
            # bf16-combined) winner could hand them the slower mode.
            # Skipped when any f32 candidate FAILED: a partial race
            # must not cache a winner the unmeasured mode might beat.
            try:
                from predictionio_tpu.ops.gram_autotune import record
                record(rank_r, best_f32_gm,
                       device_kind=jax.devices()[0].device_kind,
                       measured={"source": "bench_race",
                                 "best_s": round(best_f32_dt, 3)})
            except Exception:  # noqa: BLE001 — advisory only
                pass
        fl = als_flops_per_iter(p_in[0], p_in[1], best_params)
        ach = fl * iterations / best_dt  # raw; display-rounded once
        # what gram_mode="auto" RESOLVES to for this rank (persistent
        # shape-keyed table → defaults → heuristic) — reported beside
        # the race's measured winner so a stale autotune entry is
        # visible in the BENCH line, not silently trained against
        try:
            from predictionio_tpu.ops.gram_autotune import best_mode
            autotune_pick = best_mode(
                rank_r, device_kind=jax.devices()[0].device_kind)
        except Exception:  # noqa: BLE001 — advisory only
            autotune_pick = None
        out = {
            "value": round(n_in * iterations / best_dt, 1),
            "achieved_tflops": round(ach / 1e12, 2),
            "mfu": round(ach / peak, 4) if peak else None,
            "gram_mode": best_gm,
            "autotune_pick": autotune_pick,
            "gather_dtype": best_params.gather_dtype,
            "_achieved_flops_raw": ach,
        }
        if best_params.block_rows is not None:
            out["block_rows"] = best_params.block_rows
        if cand_errors:
            out["race_errors"] = cand_errors
        if retried:
            out["race_retries"] = retried
        return out, best_dt, best_params

    r64, dt, params_run = race(rank)
    ratings_per_sec = nnz * iterations / dt
    achieved_flops = r64.pop("_achieved_flops_raw")
    mfu = r64["mfu"]
    gram_used = r64["gram_mode"]

    # rank-128 datapoint (VERDICT r3 task 1): the layouts are rank-
    # independent, so the same packing times a rank where the MXU is
    # naturally fuller. Never lets a failure kill the headline number.
    rank128 = None
    if os.environ.get("BENCH_RANK128", "1") == "1" and rank != 128:
        try:
            rank128, _, _ = race(128, repeats=2)
            rank128.pop("_achieved_flops_raw", None)
        except Exception as e:  # noqa: BLE001 — report, don't die
            # the tunnel's remote-compile helper dies on the FULL-size
            # rank-128 program at the auto-tiled block size — but
            # block_rows=1024 shrinks the per-block tensors enough to
            # compile AND runs FASTER than the 8M subsample (measured
            # 32.3M ratings/s/iter full-size vs 27.3M subsampled).
            # Try that first; subsample only if even the small blocks
            # fail.
            fb_gather = "bfloat16" \
                if gather_env in ("auto", "bfloat16") else gather_env
            fb_gram = "einsum" if gram_mode == "auto" else gram_mode
            try:
                if gram_mode == "auto" and gather_env in ("auto",
                                                          "bfloat16"):
                    # the primary race already included (einsum, bf16,
                    # br=1024) and it failed along with everything
                    # else — re-running the identical candidate here
                    # would just re-pay its failure; go to subsample
                    raise RuntimeError(
                        "small-blocks candidate already failed in the "
                        "primary race")
                # the pack is block_rows-independent: reuse the
                # existing packed problem (race defaults p_in to it)
                rank128, _, _ = race(
                    128, repeats=2,
                    cands_override=[(fb_gram, fb_gather)],
                    block_rows=1024)
                rank128.pop("_achieved_flops_raw", None)
                rank128.update(auto_block_error=_clean_err(e))
            except Exception as e_br:  # noqa: BLE001 — small blocks
                # failed too: last resort is an 8M subsample, honestly
                # labeled with its scale
                try:
                    sub_n = min(int(os.environ.get(
                        "BENCH_RANK128_NNZ", "8000000")), nnz)
                    rng_s = np.random.default_rng(5)
                    sel = rng_s.permutation(nnz)[:sub_n]
                    r_sub = RatingsCOO(users[sel], items[sel],
                                       vals[sel], n_users, n_items)
                    packed_sub = pack_ratings(r_sub, ALSParams(
                        rank=128, num_iterations=iterations,
                        implicit_prefs=True, alpha=alpha, reg=reg,
                        seed=3))
                    rank128, _, _ = race(
                        128, repeats=2, ratings_in=r_sub,
                        packed_in=packed_sub, nnz_in=sub_n,
                        cands_override=[(fb_gram, fb_gather)])
                    rank128.pop("_achieved_flops_raw", None)
                    rank128.update(nnz=sub_n, scaled=True,
                                   full_scale_error=_clean_err(e),
                                   small_blocks_error=_clean_err(e_br))
                except Exception as e2:  # noqa: BLE001
                    rank128 = {"error": _clean_err(e2, 300)}

    cpu_rps = cpu_als_baseline(
        n_users=max(int(n_users * cpu_scale), 64),
        n_items=max(int(n_items * cpu_scale), 64),
        nnz=max(int(nnz * cpu_scale), 4096),
        rank=rank, alpha=alpha, reg=reg)

    # quality loop (VERDICT r2 task 7): hold out ~1%, retrain on the
    # rest with the SAME params/device path, NDCG@10 on the holdout
    ndcg10 = None
    if os.environ.get("BENCH_SKIP_QUALITY") != "1":
        rng_q = np.random.default_rng(11)
        test_sel = rng_q.random(nnz) < 0.01
        tr = RatingsCOO(users[~test_sel], items[~test_sel],
                        vals[~test_sel], n_users, n_items)
        Uq, Vq = train_als(tr, params_run)
        hard_sync(Vq)
        ndcg10 = round(eval_ndcg_at_k(
            Uq, Vq, tr.users, tr.items, users[test_sel],
            items[test_sel], n_items=n_items), 4)

    # serving-latency probe (VERDICT r3 task 1 / weak #3): the engine
    # server's device path, ~200 HTTP queries through the REAL deployed
    # stack (CreateServer.scala:484-633 role), micro-batcher off vs on.
    serving = None
    if os.environ.get("BENCH_SERVING", "1") == "1":
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "benchmarks"))
            import serving_bench as sb

            n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", "200"))
            n_cat = int(os.environ.get("BENCH_SERVE_ITEMS", "1200000"))
            hi_threads = int(os.environ.get("BENCH_SERVE_THREADS_HI",
                                            "256"))
            # host fast path + per-query trickle + the apples-to-apples
            # burst pair (per-query vs micro-batcher at the same
            # offered concurrency) — one battery definition, shared
            # with serving_bench.main
            serving = sb.standard_battery(n_cat, 64, n_req, 8,
                                          hi_threads)
            # quantized-lane side-by-side (ISSUE 13): the same device
            # per-query + micro-batch workload with serving_quant on,
            # against the battery's f32 rows — the `serving_quant`
            # summary row lands in the BENCH line
            q_dtype = os.environ.get("BENCH_SERVING_QUANT", "int8")
            if q_dtype in ("bf16", "int8"):
                try:
                    qrows = sb.quant_battery(
                        n_cat, 64, n_req, 8, hi_threads, q_dtype,
                        f32_per_query=serving.get("per_query"),
                        f32_micro=serving.get("microbatch"))
                    serving["serving_quant"] = qrows[-1]
                    serving["quant_rows"] = qrows[:-1]
                except Exception as e:  # noqa: BLE001 — report
                    serving["serving_quant"] = {
                        "error": _clean_err(e, 300)}
        except Exception as e:  # noqa: BLE001 — report, don't die
            serving = {"error": _clean_err(e, 300)}

    # per-mode device-scaling block (ISSUE 6): the same burst workload
    # through the micro-batcher in single / replicated / sharded serving
    # — replicated's scaling_x against the single lane is the
    # near-linear-on-N-devices acceptance number (MULTICHIP_r05 shows 8
    # healthy devices; one HBM held the whole model until now)
    device_scaling = None
    if os.environ.get("BENCH_MESH", "1") == "1":
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "benchmarks"))
            import serving_bench as sb_mesh

            if len(jax.devices()) > 1:
                n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", "200"))
                n_cat = int(os.environ.get("BENCH_SERVE_ITEMS",
                                           "1200000"))
                hi_threads = int(os.environ.get(
                    "BENCH_SERVE_THREADS_HI", "256"))
                device_scaling = sb_mesh.mesh_scaling_battery(
                    n_cat, 64, n_req, hi_threads)
            else:
                device_scaling = {"devices": 1,
                                  "note": "one device visible; no "
                                          "fan-out to measure"}
        except Exception as e:  # noqa: BLE001 — report, don't die
            device_scaling = {"error": _clean_err(e, 300)}

    # streaming freshness (ISSUE 10): the real ingest→fold-in→serve
    # loop over HTTP — event→servable p50 is the freshness the
    # incremental trainer actually delivers vs the ~minutes a full
    # retrain cadence bounds it to
    streaming = None
    if os.environ.get("BENCH_STREAMING", "1") == "1":
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "benchmarks"))
            import streaming_smoke as stream_smoke

            streaming = stream_smoke.measure(
                trials=int(os.environ.get("BENCH_STREAM_TRIALS", "6")))
        except Exception as e:  # noqa: BLE001 — report, don't die
            streaming = {"error": _clean_err(e, 300)}

    # capacity model (ISSUE 15): the mixed-traffic load harness —
    # Zipf queries + event ingest + streaming fold-ins + a held canary
    # concurrently, offered rate swept to the knee per serving config,
    # freshness re-measured at 80% of the knee WHILE queries fly (the
    # number beside the idle event_to_servable_ms)
    capacity = None
    if os.environ.get("BENCH_CAPACITY", "1") == "1":
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "benchmarks"))
            import load_harness

            capacity = load_harness.measure(
                configs=os.environ.get("BENCH_CAPACITY_CONFIGS",
                                       "host,staged,cached"),
                rate_min=float(os.environ.get(
                    "BENCH_CAPACITY_RATE_MIN", "8")),
                rate_max=float(os.environ.get(
                    "BENCH_CAPACITY_RATE_MAX", "128")),
                step_sec=float(os.environ.get(
                    "BENCH_CAPACITY_STEP_SEC", "4")),
                freshness_trials=3)
        except Exception as e:  # noqa: BLE001 — report, don't die
            capacity = {"error": _clean_err(e, 300)}

    # cold start (ISSUE 19): deploy twice — build the AOT artifact
    # store, require the second warm to be artifact-load with zero
    # compile fallbacks; warm_from_artifact_ms is the BENCH-line
    # number the autoscaler's scale-out latency budget leans on
    coldstart = None
    if os.environ.get("BENCH_COLDSTART", "1") == "1":
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "benchmarks", "coldstart_smoke.py")],
                env=dict(os.environ), capture_output=True, text=True,
                timeout=900)
            line = next((ln for ln in
                         reversed(proc.stdout.splitlines())
                         if ln.startswith("{")), None)
            if line is None:
                tail = (proc.stderr or proc.stdout or "").strip()
                raise RuntimeError(
                    f"smoke rc={proc.returncode}: {tail[-200:]}")
            coldstart = json.loads(line)
        except Exception as e:  # noqa: BLE001 — report, don't die
            coldstart = {"error": _clean_err(e, 300)}

    # columnar block ingest (ISSUE 19): the zero-copy npz block lane
    # raced against per-event JSON over real HTTP — events/s at equal
    # (single-transaction-per-POST) durability
    ingest = None
    if os.environ.get("BENCH_INGEST", "1") == "1":
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "benchmarks", "http_ingest_bench.py"),
                 os.environ.get("BENCH_INGEST_EVENTS", "20000"), "8",
                 "--columnar"],
                env=dict(os.environ), capture_output=True, text=True,
                timeout=900)
            line = next((ln for ln in
                         reversed(proc.stdout.splitlines())
                         if ln.startswith("{")), None)
            if line is None:
                tail = (proc.stderr or proc.stdout or "").strip()
                raise RuntimeError(
                    f"bench rc={proc.returncode}: {tail[-200:]}")
            ingest = json.loads(line)
        except Exception as e:  # noqa: BLE001 — report, don't die
            ingest = {"error": _clean_err(e, 300)}

    # elastic reliability (ISSUE 11): the serving lane-kill drill —
    # inject a dead replicated lane under real HTTP load, require zero
    # failed in-deadline queries, and measure the recovery-time-
    # objective (lane death → lane rejoined) from the server's own
    # degraded transitions
    reliability = None
    if os.environ.get("BENCH_RELIABILITY", "1") == "1":
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "benchmarks"))
            import reliability_smoke as rel_smoke

            reliability = rel_smoke.measure()
        except Exception as e:  # noqa: BLE001 — report, don't die
            reliability = {"error": _clean_err(e, 300)}

    # roofline accounting (VERDICT r4 weak #3: "memory-bound" was an
    # excuse, not a measurement): XLA's post-fusion bytes-accessed over
    # the steady-state iteration time vs the chip's HBM peak, PLUS the
    # dual-roofline position (arithmetic intensity, which roof is
    # overhead) per gram mode — the einsum baseline block and a
    # `fused` sub-block side by side, so the kernel's bytes-accessed
    # drop is visible in the BENCH line, not just its throughput
    roofline = None
    if os.environ.get("BENCH_ROOFLINE", "1") == "1":
        probe_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks", "roofline_probe.py")
        keep = ("hbm_gbps", "hbm_peak_gbps", "hbm_utilization",
                "achieved_tflops", "mfu", "arithmetic_intensity",
                "attainable_tflops", "bound", "roofline_fraction",
                "steady_state_s_per_iter", "xla_bytes_accessed")

        def probe(gram_probe: str, repeats: str, timeout_s: int):
            proc = subprocess.run(
                [sys.executable, probe_path],
                env=dict(os.environ, PROBE_REPEATS=repeats,
                         PROBE_GRAM=gram_probe),
                capture_output=True, text=True, timeout=timeout_s)
            line = next((ln for ln in
                         reversed(proc.stdout.splitlines())
                         if ln.startswith("{")), None)
            if proc.returncode != 0 or line is None:
                tail = (proc.stderr or proc.stdout or "").strip()
                raise RuntimeError(
                    f"probe rc={proc.returncode}: {tail[-200:]}")
            rl = json.loads(line)
            if rl.get("error"):
                raise RuntimeError(str(rl["error"])[:200])
            return {k: rl.get(k) for k in keep if rl.get(k) is not None}

        try:
            # baseline block stays the materialized-gather einsum path
            # so the fused block has a fixed reference to move against
            roofline = probe("einsum", "2", 600)
        except Exception as e:  # noqa: BLE001 — report, don't die
            roofline = {"error": _clean_err(e, 200)}
        try:
            from predictionio_tpu.ops.fused_gram import (
                fused_gram_supported,
            )

            if fused_gram_supported():
                # one repeat and a tighter bound: the fused block rides
                # inside the same supervisor attempt budget as the
                # einsum baseline probe
                roofline["fused"] = probe("fused", "1", 360)
        except Exception as e:  # noqa: BLE001 — report, don't die
            roofline["fused"] = {"error": _clean_err(e, 200)}
        # serving-side roofline (ISSUE 13): the batched top-k dispatch
        # over f32 vs row-quantized tables — bytes-accessed ratio and
        # whether the serving bound moved off the HBM roof
        try:
            proc = subprocess.run(
                [sys.executable, probe_path],
                env=dict(os.environ, PROBE_SERVE="1"),
                capture_output=True, text=True, timeout=600)
            line = next((ln for ln in
                         reversed(proc.stdout.splitlines())
                         if ln.startswith("{")), None)
            if proc.returncode != 0 or line is None:
                tail = (proc.stderr or proc.stdout or "").strip()
                raise RuntimeError(
                    f"probe rc={proc.returncode}: {tail[-200:]}")
            roofline["serving"] = json.loads(line)
        except Exception as e:  # noqa: BLE001 — report, don't die
            roofline["serving"] = {"error": _clean_err(e, 200)}

    # telemetry tails (ISSUE 2): surface the serving battery's scraped
    # server-side signals as top-level keys so the perf trajectory
    # captures recompiles / hidden transfers / p99, not just means
    def _tele(cfg_key: str, field: str):
        tele = ((serving or {}).get(cfg_key) or {}).get("telemetry") or {}
        return tele.get(field)

    tele_cfg = "microbatch" if (serving or {}).get("microbatch") \
        else "per_query"

    print(json.dumps({
        "metric": "als_implicit_train_throughput",
        "value": round(ratings_per_sec, 1),
        "unit": "ratings/s/iter",
        "vs_baseline": round(ratings_per_sec / cpu_rps, 3),
        "mfu": mfu,
        "achieved_tflops": round(achieved_flops / 1e12, 2),
        "cpu_baseline_measured": round(cpu_rps, 1),
        "dropped_entries": dropped,
        "ndcg10": ndcg10,
        "rank": rank,
        "gram_mode": gram_used,
        "autotune_pick": r64.get("autotune_pick"),
        "gather_dtype": r64.get("gather_dtype"),
        "rank128": rank128,
        "device_scaling": device_scaling,
        "serving_p50_ms": (serving or {}).get(
            "per_query", {}).get("p50_ms"),
        "serving_p99_ms": (serving or {}).get(
            "per_query", {}).get("p99_ms"),
        "compiles_since_warm": _tele(tele_cfg, "compilesSinceWarm"),
        "transfer_guard_violations": _tele(tele_cfg,
                                           "transferGuardViolations"),
        # staged-vs-serial serving pipeline ratios + overlap proof
        # (ISSUE 9): qps_x / p99_x and the device-idle fraction from
        # the staged server's own accounting
        "serving_pipeline": (serving or {}).get("pipeline"),
        # flight-recorder overhead (ISSUE 12 acceptance ≤5%): host
        # fast-path p50 with tracing on vs off, same load
        "trace_overhead_pct": (serving or {}).get("trace_overhead_pct"),
        # quantized serving lane vs the f32 einsum lane at the same
        # offered load (ISSUE 13): per-query p50 pair + micro-batch
        # qps/p99 ratios
        "serving_quant": (serving or {}).get("serving_quant"),
        # event→servable freshness through the streaming trainer
        # (ISSUE 10): ingest to correct serve, real HTTP loop
        "event_to_servable_ms": (streaming or {}).get(
            "event_to_servable_p50_ms"),
        # the same freshness number measured at 80% of the staged
        # config's knee qps WITH queries in flight (ISSUE 15): the
        # idle number above says what the trainer can do, this one
        # says what it does while the server earns its keep
        "event_to_servable_under_load_ms": (
            ((capacity or {}).get("configs") or {})
            .get("staged", {}).get("freshness_under_load_ms")),
        "streaming": streaming,
        # the capacity model (ISSUE 15): knee qps + p99 at 80% of knee
        # per serving config under MIXED traffic — what `ptpu slo
        # check` gates against the committed slo/specs/ci.json
        "capacity": capacity,
        # lane-kill recovery-time-objective (ISSUE 11): degraded-mode
        # entry→exit with zero failed in-deadline queries required
        "rto_ms": (reliability or {}).get("rto_ms"),
        "reliability": reliability,
        # deploy-twice cold-start drill (ISSUE 19): second warm loads
        # the AOT artifacts — the ms here is what a scale-out replica
        # pays before taking traffic
        "warm_from_artifact_ms": (coldstart or {}).get(
            "warm_from_artifact_ms"),
        "coldstart": coldstart,
        # zero-copy columnar block ingest vs per-event JSON (ISSUE 19
        # acceptance floor: ≥5× the single-event path)
        "ingest_block_events_per_s": (ingest or {}).get(
            "ingest_block_events_per_s"),
        "ingest": ingest,
        "serving": serving,
        "roofline": roofline,
        "device": jax.devices()[0].device_kind,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
    }))


def supervise() -> int:
    """Run the bench in child subprocesses with bounded retry + backoff.

    The TPU tunnel is flaky at *backend init* time (round 2's driver run
    died with "backend 'axon' failed to initialize" inside ``device_put``
    and emitted nothing parseable). JAX caches a failed backend init for
    the life of the process, so a retry must be a fresh process. Each
    attempt also gets a hard timeout — the observed failure mode includes
    indefinite hangs, not just fast errors.

    On terminal failure this prints, in order of preference:

    - the committed last-good result (``BENCH_LASTGOOD.json``, written
      on every successful TPU run) explicitly marked ``"stale": true``
      with its original ``measured_at`` plus the fresh error — rc 0, so
      the driver's artifact still carries real measured numbers; or
    - the one JSON line with ``value: null`` and an ``error`` field, so
      the driver records *why* — rc 1.
    """
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "4"))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "1500"))
    lastgood_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_LASTGOOD.json")
    backoffs = [15.0, 45.0, 90.0]
    last_err = "unknown"
    for i in range(attempts):
        env = dict(os.environ, BENCH_CHILD="1")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True,
                timeout=attempt_timeout)
        except subprocess.TimeoutExpired:
            last_err = f"attempt {i + 1} timed out after {attempt_timeout}s"
            sys.stderr.write(last_err + "\n")
        else:
            json_line = next(
                (ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
            parsed = None
            if proc.returncode == 0 and json_line is not None:
                try:
                    parsed = json.loads(json_line)
                except json.JSONDecodeError:
                    # a stray '{'-prefixed stdout line (dict repr,
                    # diagnostic) — not the result; treat the attempt
                    # as failed rather than crash the supervisor
                    last_err = (f"attempt {i + 1}: unparseable result "
                                f"line: {json_line[:200]}")
                    sys.stderr.write(last_err + "\n")
            if parsed is not None:
                # only a FULL battery (quality + serving present) may
                # become the stale-fallback artifact; ad-hoc partial
                # runs (BENCH_SKIP_QUALITY, BENCH_SERVING=0, alternate
                # ranks) must not degrade the driver's last-good
                serving_ok = isinstance(
                    (parsed.get("serving") or {}).get("per_query"),
                    dict)
                full = (parsed.get("ndcg10") is not None
                        and serving_ok
                        and parsed.get("rank") == 64)
                if full and "TPU" in str(parsed.get("device", "")):
                    # remember the last real-chip result for the
                    # stale-fallback path (atomic: tmp + replace)
                    try:
                        tmp = lastgood_path + f".tmp.{os.getpid()}"
                        with open(tmp, "w") as f:
                            json.dump(parsed, f, indent=1)
                        os.replace(tmp, lastgood_path)
                    except OSError as e:
                        sys.stderr.write(f"lastgood write failed: {e}\n")
                print(json_line)
                return 0
            if proc.returncode != 0 or json_line is None:
                tail = (proc.stderr or proc.stdout or "") \
                    .strip().splitlines()
                last_err = (f"attempt {i + 1} rc={proc.returncode}: "
                            + " | ".join(tail[-6:]))
                sys.stderr.write(last_err + "\n")
        if i < attempts - 1:
            time.sleep(backoffs[min(i, len(backoffs) - 1)])
    try:
        with open(lastgood_path) as f:
            lastgood = json.load(f)
    except (OSError, json.JSONDecodeError):
        lastgood = None
    if lastgood is not None and "TPU" in str(lastgood.get("device", "")):
        lastgood["stale"] = True
        lastgood["fresh_error"] = last_err[:1000]
        lastgood["fresh_attempts"] = attempts
        print(json.dumps(lastgood))
        return 0
    print(json.dumps({
        "metric": "als_implicit_train_throughput",
        "value": None,
        "unit": "ratings/s/iter",
        "vs_baseline": None,
        "error": last_err[:2000],
        "attempts": attempts,
    }))
    return 1


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        sys.exit(supervise())
