"""Unified telemetry: streaming histograms, metric registry, exposition.

The cross-cutting observability layer (ISSUE 2): every server mounts a
:class:`MetricsRegistry` whose contents are served as Prometheus text
format on ``GET /metrics`` and as JSON inside ``/status.json``. See
docs/observability.md for the full metric catalog.
"""

from .guard import TransferGuardCounter
from .hotkeys import SpaceSaving, mount_hot_key_metrics
from .overlap import OverlapTracker
from .histogram import (
    DEFAULT_LATENCY_BOUNDS,
    POW2_COUNT_BOUNDS,
    StreamingHistogram,
    exponential_bounds,
    linear_bounds,
    window_quantile,
)
from .registry import (
    MetricsRegistry,
    escape_label_value,
    format_value,
    render_histogram_lines,
)
from .runtime import (
    build_info,
    hbm_stats,
    process_stats,
    register_process_metrics,
    register_runtime_metrics,
)
from .trace import (
    DeviceProfiler,
    FlightRecorder,
    Trace,
    Tracer,
    activate_traces,
    add_stage_spans,
    mark_active_traces,
)

__all__ = [
    "DEFAULT_LATENCY_BOUNDS",
    "POW2_COUNT_BOUNDS",
    "DeviceProfiler",
    "FlightRecorder",
    "MetricsRegistry",
    "OverlapTracker",
    "SpaceSaving",
    "StreamingHistogram",
    "Trace",
    "Tracer",
    "TransferGuardCounter",
    "activate_traces",
    "add_stage_spans",
    "build_info",
    "escape_label_value",
    "exponential_bounds",
    "format_value",
    "hbm_stats",
    "linear_bounds",
    "mark_active_traces",
    "mount_hot_key_metrics",
    "mount_span_metrics",
    "process_stats",
    "register_process_metrics",
    "register_runtime_metrics",
    "render_histogram_lines",
    "window_quantile",
]


def mount_span_metrics(reg: MetricsRegistry, span_registry=None,
                       metric_name: str = "pio_span_seconds") -> None:
    """Expose a :class:`..utils.tracing.SpanRegistry`'s bounded
    histograms as one labeled histogram family on ``reg`` (collector:
    spans are recorded outside the registry's family machinery)."""
    from ..utils.tracing import spans as default_spans

    sr = span_registry if span_registry is not None else default_spans
    mounted = getattr(reg, "_span_registries", None)
    if mounted is None:
        mounted = reg._span_registries = set()  # type: ignore[attr-defined]
    if id(sr) in mounted:  # idempotent: no duplicate series on remount
        return
    mounted.add(id(sr))

    def collect():
        lines = [f"# HELP {metric_name} Wall-clock spans recorded via "
                 f"utils.tracing.timed(name)",
                 f"# TYPE {metric_name} histogram"]
        for name, hist in sorted(sr.histograms().items()):
            items = (("span", name),)
            lines.extend(render_histogram_lines(metric_name, items,
                                                hist))
        return lines if len(lines) > 2 else []

    reg.register_collector(collect)
