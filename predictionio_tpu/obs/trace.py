"""End-to-end request tracing: W3C context, tail-sampled flight recorder.

The PR 2 histograms can say *that* p99 regressed; they cannot say *why
this query* was slow — the per-phase spans are aggregated and the
individual timeline is gone the moment it is recorded. This module is
the per-request attribution layer (PAPERS: Google's ads-serving infra
is explicit that at fleet scale per-request attribution and
profiling-driven triage dominate aggregate dashboards):

- **Every request is traced.** :meth:`Tracer.begin` parses (or mints) a
  W3C ``traceparent`` and hands back a :class:`Trace` that handlers and
  pipeline stages append :class:`Span` rows to. Cost per request is a
  handful of small allocations — no I/O, no locks on the span path
  beyond one list append.
- **Almost every trace is dropped.** :meth:`Tracer.finish` applies the
  tail-sampling policy: a trace is retained only when it was *slow*
  (adaptive threshold riding the live p99 of the tracer's own duration
  histogram), *errored* (5xx), *deadline-503'd*, *fault-injected*, or
  explicitly force-retained (stream fold-in passes). Retained traces
  land in a bounded ring (:class:`FlightRecorder`); everything else is
  garbage the moment the response goes out.
- **Export is Chrome/Perfetto trace-event JSON** — ``GET
  /trace.json?id=…`` (or ``ptpu trace``) produces a file that loads
  directly in ui.perfetto.dev / ``chrome://tracing`` with the full
  stage timeline (queue_wait → assemble → supplement → dispatch →
  device_wait → readback → serve).

Batch-stage spans are *reconstructed* timelines: the pipeline records
per-stage durations plus a few wall anchors (enqueue, pickup,
dispatch), and :func:`add_stage_spans` lays the stages out
sequentially from each anchor. Stages really do run sequentially
within a stage-thread, so the reconstruction is faithful to within the
inter-stage queue hops (which appear as gaps — exactly what you want
to see).

On-demand device profiling rides along: :class:`DeviceProfiler` wraps
``jax.profiler`` start/stop for a bounded window into a served
artifact directory (``POST /profile`` on the engine server, guarded by
the admin auth path).
"""

from __future__ import annotations

import json
import random
import re
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .histogram import StreamingHistogram

__all__ = [
    "Span",
    "Trace",
    "FlightRecorder",
    "Tracer",
    "DeviceProfiler",
    "add_stage_spans",
    "activate_traces",
    "mark_active_traces",
    "parse_traceparent",
    "format_traceparent",
]

#: W3C trace-context version-00 ``traceparent``:
#: ``00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>``
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

#: canonical serving-stage order — the sequence the pipeline actually
#: executes, used to lay reconstructed stage spans out on the timeline
STAGE_ORDER = ("queue_wait", "assemble", "supplement", "dispatch",
               "device_wait", "readback", "serve", "feedback")

_ids = random.Random()  # tracing ids need speed, not secrecy


def _new_trace_id() -> str:
    return f"{_ids.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_ids.getrandbits(64):016x}"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent`` header;
    None on absent/malformed/all-zero values (per spec, an invalid
    header is ignored and a fresh trace is started)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    version, trace_id, span_id, _flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


class Span:
    """One timed operation inside a trace. Times are ``time.monotonic``
    seconds; the owning trace carries the wall-clock anchor."""

    __slots__ = ("name", "span_id", "parent_id", "t_start", "t_end",
                 "attrs")

    def __init__(self, name: str, span_id: str,
                 parent_id: Optional[str], t_start: float,
                 t_end: Optional[float] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start = t_start
        self.t_end = t_end
        self.attrs = attrs


class Trace:
    """One request's (or fold-in pass's) span tree plus its retention
    flags. Span appends take the trace's own lock — traces hop threads
    through the staged pipeline, but contention is two threads at worst
    and the critical section is a list append."""

    __slots__ = ("trace_id", "name", "root_span_id", "parent_span_id",
                 "request_id", "t_mono", "t_wall", "t_end", "status",
                 "marks", "attrs", "spans", "pending_exemplars",
                 "retained_reason", "_lock")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 request_id: str = "",
                 attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id or _new_trace_id()
        self.name = name
        self.root_span_id = _new_span_id()
        self.parent_span_id = parent_span_id
        self.request_id = request_id
        self.t_mono = time.monotonic()
        self.t_wall = time.time()
        self.t_end: Optional[float] = None
        self.status: Optional[int] = None
        self.marks: set = set()
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.spans: List[Span] = []
        #: deferred exemplar writes: ``(histogram_child, value)`` pairs
        #: applied by :meth:`Tracer.finish` ONLY when the trace is
        #: retained — a /metrics bucket exemplar must point at a trace
        #: that ``/trace.json?id=`` can actually serve
        self.pending_exemplars: List[Tuple[Any, float]] = []
        self._lock = threading.Lock()

    # -- span recording ----------------------------------------------------
    def add_span(self, name: str, t_start: float, t_end: float,
                 parent_id: Optional[str] = None,
                 **attrs: Any) -> Span:
        """Record a completed span with explicit monotonic times."""
        span = Span(name, _new_span_id(),
                    parent_id or self.root_span_id, t_start, t_end,
                    attrs or None)
        with self._lock:
            self.spans.append(span)
        return span

    def span(self, name: str, **attrs: Any):
        """Context manager recording a span around a block."""
        return _SpanCtx(self, name, attrs)

    def mark(self, reason: str) -> None:
        """Flag the trace for retention (``fault``, ``stream``, …)."""
        self.marks.add(reason)

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def exemplar(self, hist_child: Any, value: float) -> None:
        """Defer an exemplar for ``hist_child`` (a
        :class:`~.histogram.StreamingHistogram`) until retention is
        decided."""
        self.pending_exemplars.append((hist_child, value))

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.root_span_id)

    @property
    def duration(self) -> Optional[float]:
        if self.t_end is None:
            return None
        return self.t_end - self.t_mono

    # -- export ------------------------------------------------------------
    def to_trace_events(self) -> Dict[str, Any]:
        """Chrome/Perfetto trace-event JSON (the ``X`` complete-event
        flavor): microsecond timestamps anchored to the trace's wall
        clock, span tree flattened with parent ids in ``args``."""
        base = self.t_wall - self.t_mono  # mono → wall

        def us(t_mono: float) -> float:
            return round((t_mono + base) * 1e6, 1)

        with self._lock:
            spans = list(self.spans)
        events: List[Dict[str, Any]] = [{
            "name": self.name, "ph": "X", "cat": "request",
            "ts": us(self.t_mono),
            "dur": round((self.duration or 0.0) * 1e6, 1),
            "pid": 1, "tid": 1,
            "args": {"traceId": self.trace_id,
                     "spanId": self.root_span_id,
                     "requestId": self.request_id,
                     "status": self.status,
                     **self.attrs},
        }]
        for s in spans:
            events.append({
                "name": s.name, "ph": "X", "cat": "stage",
                "ts": us(s.t_start),
                "dur": round(((s.t_end or s.t_start) - s.t_start) * 1e6,
                             1),
                "pid": 1, "tid": 1,
                "args": {"spanId": s.span_id,
                         "parentId": s.parent_id,
                         **(s.attrs or {})},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "traceId": self.trace_id,
                "traceparent": self.traceparent(),
                "requestId": self.request_id,
                "name": self.name,
                "retainedReason": self.retained_reason,
                "marks": sorted(self.marks),
            },
        }

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            n_spans = len(self.spans)
        d = self.duration
        return {
            "traceId": self.trace_id,
            "name": self.name,
            "requestId": self.request_id,
            "status": self.status,
            "durationMs": round(d * 1000, 3) if d is not None else None,
            "spans": n_spans,
            "reason": self.retained_reason,
            "marks": sorted(self.marks),
            "attrs": dict(self.attrs),
            "wallTime": self.t_wall,
        }


class _SpanCtx:
    __slots__ = ("trace", "name", "attrs", "t0")

    def __init__(self, trace: Trace, name: str, attrs: Dict[str, Any]):
        self.trace = trace
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> "_SpanCtx":
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.attrs = dict(self.attrs, error=str(exc)[:200])
        self.trace.add_span(self.name, self.t0, time.monotonic(),
                            **self.attrs)


def add_stage_spans(trace: Optional[Trace], anchor: float,
                    phases: Dict[str, float],
                    order: Iterable[str] = STAGE_ORDER,
                    parent_id: Optional[str] = None,
                    skip: Iterable[str] = (),
                    **attrs: Any) -> None:
    """Reconstruct a sequential stage timeline from a phases dict
    (stage → duration seconds, the shape ``query_batch`` and
    ``batch_predict`` already produce) laid out from ``anchor``
    onward in canonical ``order``. No-op on a None trace so call
    sites stay branch-free."""
    if trace is None:
        return
    t = anchor
    skipset = set(skip)
    for name in order:
        dur = phases.get(name)
        if dur is None or name in skipset:
            continue
        trace.add_span(name, t, t + dur, parent_id=parent_id, **attrs)
        t += dur


# -- thread-local activation (fault attribution) ---------------------------

_active = threading.local()


class activate_traces:
    """Mark ``traces`` as the ones being worked on by THIS thread, so a
    fault injection delivered here (:func:`mark_active_traces`, wired
    into the engine server's fault listener) flags exactly the traces
    of the batch it hit."""

    __slots__ = ("traces",)

    def __init__(self, traces: Iterable[Optional[Trace]]):
        self.traces = [t for t in traces if t is not None]

    def __enter__(self) -> "activate_traces":
        stack = getattr(_active, "stack", None)
        if stack is None:
            stack = _active.stack = []
        stack.append(self.traces)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        _active.stack.pop()


def mark_active_traces(reason: str, **attrs: Any) -> None:
    """Flag every trace active on the calling thread (fault-injection
    listeners run on the injected thread)."""
    stack = getattr(_active, "stack", None)
    if not stack:
        return
    for traces in stack:
        for t in traces:
            t.mark(reason)
            if attrs:
                t.attrs.update(attrs)


# -- the flight recorder ---------------------------------------------------


class FlightRecorder:
    """Bounded id-addressable ring of retained traces: O(1) insert,
    oldest evicted past capacity (``pio_trace_dropped_total`` counts
    the evictions — a busy tail means raise the ring, not lose data
    silently)."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(int(capacity), 1)
        self._ring: "OrderedDict[str, Trace]" = OrderedDict()
        self._lock = threading.Lock()
        self.dropped = 0

    def add(self, trace: Trace) -> None:
        with self._lock:
            self._ring[trace.trace_id] = trace
            self._ring.move_to_end(trace.trace_id)
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
                self.dropped += 1

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._ring.get(trace_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def slowest(self, n: int = 10) -> List[Trace]:
        with self._lock:
            traces = list(self._ring.values())
        traces.sort(key=lambda t: t.duration or 0.0, reverse=True)
        return traces[:max(int(n), 0)]

    def recent(self, n: int = 10) -> List[Trace]:
        with self._lock:
            return list(self._ring.values())[-max(int(n), 0):]


class Tracer:
    """Per-server tracer: begins/finishes traces and applies the
    tail-sampling retention policy.

    Retention classes (``pio_trace_retained_total{reason=}``):

    - ``error`` — response status >= 500
    - ``deadline`` — 503 (deadline shed / dependency outage)
    - ``fault`` — a fault injection was delivered during the request
    - ``slow`` — duration >= the adaptive threshold: the live p99 of
      this tracer's own duration histogram once ``min_samples`` have
      been seen (before that, ``slow_floor_ms`` when set, else nothing
      is "slow" yet). A fixed ``slow_ms`` overrides the adaptive rule.
    - ``slo`` (or whatever reason :meth:`force_retention` set) — the
      SLO engine is mid-breach and EVERY trace is evidence: retain
      unconditionally until the burn clears (ISSUE 15)
    - anything a caller passed to :meth:`Trace.mark` (e.g. ``stream``)
    """

    def __init__(self, ring: int = 512, slow_ms: float = 0.0,
                 slow_floor_ms: float = 0.0, min_samples: int = 200):
        self.recorder = FlightRecorder(ring)
        self.slow_ms = float(slow_ms)
        self.slow_floor_ms = float(slow_floor_ms)
        self.min_samples = int(min_samples)
        self._hist = StreamingHistogram()
        self._started = 0
        self._retained: Dict[str, int] = {}
        self._count_lock = threading.Lock()
        #: while set, finish() retains every trace the normal policy
        #: would drop, under this reason (the SLO engine's burn window:
        #: every violation must arrive with flight-recorder exemplars)
        self._force_reason: Optional[str] = None

    def force_retention(self, reason: Optional[str]) -> None:
        """Turn unconditional retention on (``reason``, e.g. ``"slo"``)
        or back off (None). The ring stays bounded either way — a long
        burn evicts its own oldest evidence, never grows memory."""
        self._force_reason = reason or None

    # -- lifecycle ---------------------------------------------------------
    def begin(self, name: str, traceparent: Optional[str] = None,
              request_id: str = "", **attrs: Any) -> Trace:
        parsed = parse_traceparent(traceparent)
        trace = Trace(
            name,
            trace_id=parsed[0] if parsed else None,
            parent_span_id=parsed[1] if parsed else None,
            request_id=request_id, attrs=attrs)
        with self._count_lock:
            self._started += 1
        return trace

    def slow_threshold(self) -> Optional[float]:
        """Current slow-retention threshold in seconds; None while the
        policy has nothing to compare against."""
        if self.slow_ms > 0:
            return self.slow_ms / 1000.0
        if self._hist.count >= self.min_samples:
            p99 = self._hist.quantile(0.99)
            if p99 is not None:
                return max(p99, self.slow_floor_ms / 1000.0)
        if self.slow_floor_ms > 0:
            return self.slow_floor_ms / 1000.0
        return None

    def finish(self, trace: Trace, status: Optional[int] = None,
               duration: Optional[float] = None,
               force_reason: Optional[str] = None
               ) -> Tuple[bool, Optional[str]]:
        """Close the trace, decide retention, apply deferred exemplars.
        Returns ``(retained, reason)``."""
        now = time.monotonic()
        trace.t_end = now
        if duration is None:
            duration = now - trace.t_mono
        else:
            trace.t_end = trace.t_mono + duration
        trace.status = status
        reason = force_reason
        if reason is None:
            if trace.marks:
                reason = sorted(trace.marks)[0]
            elif status is not None and status == 503:
                reason = "deadline"
            elif status is not None and status >= 500:
                reason = "error"
            else:
                threshold = self.slow_threshold()
                # STRICTLY above: the p99 estimate clamps to the
                # observed max, so a perfectly uniform workload would
                # otherwise retain every request as "slow"
                if threshold is not None and duration > threshold:
                    reason = "slow"
        if reason is None:
            # SLO-burn force-retention is the WEAKEST reason: a trace
            # that is also slow/errored keeps its specific attribution
            reason = self._force_reason
        # the duration feeds the adaptive threshold AFTER the verdict:
        # a single slow burst should be retained against the p99 that
        # preceded it, not against itself
        self._hist.record(duration)
        if reason is None:
            return False, None
        trace.retained_reason = reason
        self.recorder.add(trace)
        with self._count_lock:
            self._retained[reason] = self._retained.get(reason, 0) + 1
        for child, value in trace.pending_exemplars:
            try:
                child.record_exemplar(value, trace.trace_id,
                                      trace.t_wall)
            except Exception:  # noqa: BLE001 — exemplars are advisory
                pass
        return True, reason

    # -- observability -----------------------------------------------------
    def status(self) -> Dict[str, Any]:
        threshold = self.slow_threshold()
        with self._count_lock:
            retained = dict(self._retained)
            started = self._started
        return {
            "requests": started,
            "retained": len(self.recorder),
            "retainedByReason": retained,
            "ringCapacity": self.recorder.capacity,
            "evicted": self.recorder.dropped,
            "slowThresholdMs": (round(threshold * 1000, 3)
                                if threshold is not None else None),
            "forcedReason": self._force_reason,
            "recent": [t.summary() for t in self.recorder.recent(5)],
        }

    def register_metrics(self, registry) -> None:
        """Mount the ``pio_trace_*`` series on ``registry``."""
        registry.gauge(
            "pio_trace_requests_total",
            "Requests traced by the flight recorder (every request is; "
            "retention is the sampled part)",
            # ptpu: guarded-by[_count_lock] — scrape-time gauge
            # snapshot of a monotonically increasing int; a torn read
            # is at worst one request stale
            fn=lambda: float(self._started))
        retained_fam = registry.gauge(
            "pio_trace_retained_total",
            "Traces retained by the tail sampler, by reason "
            "(slow | error | deadline | fault | stream | slo)")

        def _bind(fam, reason):
            fam.labels(reason=reason).set_fn(
                lambda: float(self._retained.get(reason, 0)))

        for r in ("slow", "error", "deadline", "fault", "stream",
                  "slo"):
            _bind(retained_fam, r)
        registry.gauge(
            "pio_trace_ring_size",
            "Retained traces currently held in the flight-recorder "
            "ring", fn=lambda: float(len(self.recorder)))
        registry.gauge(
            "pio_trace_ring_evicted_total",
            "Retained traces evicted from the ring by newer ones",
            fn=lambda: float(self.recorder.dropped))
        registry.gauge(
            "pio_trace_slow_threshold_seconds",
            "Live slow-retention threshold (adaptive p99 of traced "
            "request durations; 0 until enough samples)",
            fn=lambda: float(self.slow_threshold() or 0.0))


# -- on-demand device profiling --------------------------------------------


class DeviceProfiler:
    """Bounded-window ``jax.profiler`` capture into a served artifact
    directory (``POST /profile``). One capture at a time; the capture
    thread stops the trace after the window so an operator curl can
    never leave the profiler running."""

    MAX_WINDOW_MS = 60_000.0

    def __init__(self, base_dir: Optional[str] = None):
        import os
        import tempfile

        self.base_dir = base_dir or os.environ.get(
            "PTPU_PROFILE_DIR") or os.path.join(
            tempfile.gettempdir(), "ptpu-profiles")
        self._lock = threading.Lock()
        self._active: Optional[Dict[str, Any]] = None
        self._history: List[Dict[str, Any]] = []

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active is not None

    def start(self, duration_ms: float = 1000.0) -> Dict[str, Any]:
        """Begin a capture; raises ``RuntimeError`` when one is already
        running or the profiler is unavailable."""
        import os

        duration_ms = float(duration_ms)
        if not 0 < duration_ms <= self.MAX_WINDOW_MS:
            raise ValueError(
                f"durationMs must be in (0, {self.MAX_WINDOW_MS:.0f}]")
        try:
            import jax
        except ImportError as e:
            raise RuntimeError(f"jax unavailable: {e}")
        with self._lock:
            if self._active is not None:
                raise RuntimeError(
                    "a profile capture is already running")
            stamp = time.strftime("%Y%m%d-%H%M%S")
            out_dir = os.path.join(self.base_dir,
                                   f"profile-{stamp}-{_new_span_id()}")
            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            info = {"dir": out_dir, "durationMs": duration_ms,
                    "startedAt": time.time(), "done": False}
            self._active = info
        threading.Thread(target=self._stop_after,
                         args=(duration_ms / 1000.0, info),
                         daemon=True, name="device-profiler").start()
        return dict(info)

    def _stop_after(self, seconds: float, info: Dict[str, Any]) -> None:
        time.sleep(seconds)
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — record, never raise on
            info["error"] = str(e)[:500]  # the capture thread
        info["done"] = True
        info["stoppedAt"] = time.time()
        with self._lock:
            self._history.append(info)
            self._history = self._history[-20:]
            self._active = None

    def status(self) -> Dict[str, Any]:
        import os

        with self._lock:
            active = dict(self._active) if self._active else None
            history = [dict(h) for h in self._history]
        artifacts: List[Dict[str, Any]] = []
        try:
            if os.path.isdir(self.base_dir):
                for name in sorted(os.listdir(self.base_dir)):
                    path = os.path.join(self.base_dir, name)
                    if os.path.isdir(path):
                        size = sum(
                            os.path.getsize(os.path.join(root, f))
                            for root, _, files in os.walk(path)
                            for f in files)
                        artifacts.append({"name": name, "dir": path,
                                          "bytes": size})
        except OSError:
            pass
        return {"active": active, "history": history,
                "baseDir": self.base_dir, "artifacts": artifacts}


def write_trace_file(trace: Trace, path: str) -> None:
    """Dump one retained trace as a Perfetto-loadable JSON file (the
    ``ptpu trace -o`` path)."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace.to_trace_events(), f)
