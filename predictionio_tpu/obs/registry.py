"""Metric registry + Prometheus text-format exposition.

One :class:`MetricsRegistry` per server process backs both surfaces the
ISSUE asks for: ``GET /metrics`` (Prometheus text format 0.0.4, the
fleet-scrape lane) and the enriched ``/status.json`` (the same data as
JSON for humans and the bench). Counters, gauges (static or
callable-backed), and histogram families with labels; everything is
thread-safe and O(1) per observation (histograms are the fixed-bucket
streaming kind from :mod:`.histogram`).
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
)

from .histogram import StreamingHistogram

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

LabelItems = Tuple[Tuple[str, str], ...]


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    """Exposition value formatting (`+Inf`, integers bare, floats repr)."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f != f:  # NaN
        return "NaN"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(items: LabelItems,
               extra: Optional[str] = None) -> str:
    parts = [f'{k}="{escape_label_value(v)}"' for k, v in items]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_histogram_lines(name: str, items: LabelItems,
                           hist: StreamingHistogram,
                           openmetrics: bool = False) -> List[str]:
    """One labeled histogram child → its ``_bucket``/``_sum``/``_count``
    exposition lines (shared by the registry and the span collector).
    Under OpenMetrics, buckets carrying an exemplar (last retained
    trace id per bucket) render it as ``# {trace_id="…"} value ts`` —
    the grammar Prometheus scrapes exemplars from (exemplars are
    OpenMetrics-only; the 0.0.4 text format has no syntax for them)."""
    exemplars = hist.exemplars() if openmetrics else {}
    lines = []
    for i, (le, cum) in enumerate(hist.bucket_counts()):
        le_item = 'le="' + format_value(le) + '"'
        line = f"{name}_bucket{_label_str(items, le_item)} {cum}"
        ex = exemplars.get(i)
        if ex is not None:
            trace_id, value, ts = ex
            line += (f' # {{trace_id="{escape_label_value(trace_id)}"}}'
                     f" {format_value(value)} {ts:.3f}")
        lines.append(line)
    lines.append(f"{name}_sum{_label_str(items)} "
                 f"{format_value(hist.sum)}")
    lines.append(f"{name}_count{_label_str(items)} {hist.count}")
    return lines


def _labels_key(labels: Dict[str, str]) -> LabelItems:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter child."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Gauge child: ``set()`` a value or back it with a callable."""

    __slots__ = ("_value", "_fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._value = 0.0
        self._fn = fn

    def set(self, value: float) -> None:
        self._value = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a broken gauge reads 0,
                return 0.0     # it never breaks the scrape
        return self._value


class _Family:
    """A named metric family: children keyed by their label items."""

    def __init__(self, name: str, help: str, kind: str,
                 bounds: Optional[Sequence[float]] = None) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.kind = kind
        self._bounds = bounds
        self._children: Dict[LabelItems, Any] = {}
        self._lock = threading.Lock()

    def _make_child(self) -> Any:
        if self.kind == "counter":
            return Counter()
        if self.kind == "gauge":
            return Gauge()
        return StreamingHistogram(self._bounds)

    def labels(self, **labels: str) -> Any:
        key = _labels_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
        return child

    # Unlabeled convenience: family acts as its own sole child.
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        self.labels().set_fn(fn)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def children(self) -> List[Tuple[LabelItems, Any]]:
        with self._lock:
            return list(self._children.items())

    def remove_matching(self, **labels: str) -> int:
        """Drop every child whose label set CONTAINS the given items
        (``remove_matching(replica="h:p")`` removes that replica's
        children whatever other labels they carry). The fleet
        aggregator calls this when a replica is scaled in, so decades
        of membership churn never leak gauge cardinality; merged
        counters/histograms are left alone — their contributions are
        monotone history."""
        items = set(labels.items())
        with self._lock:
            doomed = [key for key in self._children
                      if items <= set(key)]
            for key in doomed:
                del self._children[key]
        return len(doomed)

    def render(self, openmetrics: bool = False) -> List[str]:
        # OpenMetrics names a counter family WITHOUT the _total suffix
        # (samples keep it); the 0.0.4 format uses the suffixed name
        # everywhere. Rendering both from one registry is why the
        # family keeps the suffixed name internally.
        meta_name = self.name
        if openmetrics and self.kind == "counter" \
                and meta_name.endswith("_total"):
            meta_name = meta_name[:-len("_total")]
        lines = [f"# HELP {meta_name} {_escape_help(self.help)}",
                 f"# TYPE {meta_name} {self.kind}"]
        for items, child in sorted(self.children()):
            if self.kind == "histogram":
                lines.extend(render_histogram_lines(
                    self.name, items, child, openmetrics=openmetrics))
            else:
                lines.append(f"{self.name}{_label_str(items)} "
                             f"{format_value(child.value)}")
        return lines

    def export(self) -> Dict[str, Any]:
        """Full-fidelity JSON view of the family — unlike
        :meth:`snapshot` (which reduces histograms to percentile
        summaries), this carries the raw cumulative buckets, so a
        fleet aggregator can rebuild and LOSSLESSLY merge the
        histogram (``StreamingHistogram.from_buckets``). ``inf``
        upper bounds render as the string ``"+Inf"`` (JSON has no
        Infinity literal)."""
        children: List[Dict[str, Any]] = []
        for items, child in sorted(self.children()):
            labels = {k: v for k, v in items}
            if self.kind == "histogram":
                buckets = [["+Inf" if math.isinf(le) else le, cum]
                           for le, cum in child.bucket_counts()]
                children.append({
                    "labels": labels,
                    "buckets": buckets,
                    "count": child.count,
                    "sum": child.sum,
                    "min": child.min,
                    "max": child.max,
                })
            else:
                children.append({"labels": labels,
                                 "value": child.value})
        return {"kind": self.kind, "help": self.help,
                "children": children}

    def snapshot(self) -> Any:
        """JSON-friendly view: scalar for the unlabeled child, else a
        ``{"label=value,...": sample}`` map."""
        def one(child: Any) -> Any:
            if self.kind == "histogram":
                return child.snapshot()
            return child.value

        children = self.children()
        if len(children) == 1 and children[0][0] == ():
            return one(children[0][1])
        return {",".join(f"{k}={v}" for k, v in items): one(child)
                for items, child in sorted(children)}


class MetricsRegistry:
    """Ordered family registry; renders 0.0.4 text exposition."""

    def __init__(self) -> None:
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[[], Iterable[str]]] = []
        self._lock = threading.Lock()
        self.start_time = time.time()

    def _family(self, name: str, help: str, kind: str,
                bounds: Optional[Sequence[float]] = None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, help, kind, bounds)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}")
        return fam

    def families(self) -> List[_Family]:
        """Every registered family (registration order) — the sweep
        surface for cross-family cleanup like
        :meth:`_Family.remove_matching`."""
        with self._lock:
            return list(self._families.values())

    def get(self, name: str) -> Optional[_Family]:
        """The registered family called ``name`` (None when absent) —
        the read side consumers like the SLO engine evaluate against:
        ``family.kind`` says how to read it, ``family.children()``
        yields ``(label items, child)`` pairs."""
        with self._lock:
            return self._families.get(name)

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, help, "counter")

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None) -> _Family:
        fam = self._family(name, help, "gauge")
        if fn is not None:
            fam.set_fn(fn)
        return fam

    def histogram(self, name: str, help: str = "",
                  bounds: Optional[Sequence[float]] = None) -> _Family:
        return self._family(name, help, "histogram", bounds)

    def register_collector(
            self, fn: Callable[[], Iterable[str]]) -> None:
        """Append raw (already escaped) exposition lines at render time —
        the hook the span-registry bridge uses."""
        with self._lock:
            self._collectors.append(fn)

    def render(self, openmetrics: bool = False) -> str:
        """Text exposition: Prometheus 0.0.4 by default; OpenMetrics
        1.0 (exemplars on histogram buckets, ``# EOF`` terminator,
        suffix-aware counter metadata) when ``openmetrics`` — the
        format ``Accept: application/openmetrics-text`` negotiates."""
        with self._lock:
            families = list(self._families.values())
            collectors = list(self._collectors)
        lines: List[str] = []
        for fam in families:
            lines.extend(fam.render(openmetrics=openmetrics))
        for fn in collectors:
            try:
                lines.extend(fn())
            except Exception:  # noqa: BLE001 — one bad collector must
                continue       # not take down the whole scrape
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            families = list(self._families.values())
        return {fam.name: fam.snapshot() for fam in families}

    def export(self) -> Dict[str, Any]:
        """Full-fidelity JSON exposition (``GET /metrics.json``): every
        family with kind/help and per-child labels, values, and — for
        histograms — the raw cumulative buckets plus exact
        sum/min/max. This is the fleet-scrape lane: the aggregator
        merges these exactly (counters sum, histogram buckets add),
        which the percentile-summary :meth:`snapshot` cannot support.
        Render-time collectors (build info, HBM) are exposition-only
        and deliberately absent here."""
        with self._lock:
            families = list(self._families.values())
        return {fam.name: fam.export() for fam in families}
