"""TPU-native runtime gauges: XLA compiles, HBM occupancy, guard hits.

ALX-style TPU serving treats HBM occupancy and recompile counts as
first-class signals (PAPERS: Google ads-serving infrastructure) — a
recompile storm or HBM creep shows up in the tail long before it shows
up in an error log. These helpers register the process-level series on
any :class:`.registry.MetricsRegistry`; everything degrades gracefully
off-TPU (gauges read 0 or are simply absent).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .guard import TransferGuardCounter
from .registry import MetricsRegistry


def hbm_stats() -> List[Dict[str, object]]:
    """Per-device HBM bytes in use / limit via ``device.memory_stats()``;
    empty off-TPU (CPU PJRT returns None), when jax is absent, or when
    no backend is initialized yet. NEVER initializes a backend itself:
    an event/storage server scraping /metrics must not acquire the TPU
    (operations.md "one chip, one tenant") just to report on it."""
    import sys

    if "jax" not in sys.modules:  # jax-free server: nothing to report,
        return []                 # and a scrape must not pay the import
    try:
        import jax
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized") \
                and not xla_bridge.backends_are_initialized():
            return []
        devices = jax.devices()
    except Exception:  # noqa: BLE001 — observability never requires jax
        return []
    out: List[Dict[str, object]] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-device degrade
            stats = None
        if not stats:
            continue
        out.append({
            "device": str(d.id),
            "kind": getattr(d, "device_kind", "unknown"),
            "bytesInUse": int(stats.get("bytes_in_use", 0)),
            "bytesLimit": int(stats.get("bytes_limit", 0) or
                              stats.get("bytes_reservable_limit", 0)),
            "peakBytesInUse": int(stats.get("peak_bytes_in_use", 0)),
        })
    return out


def build_info(server: str, version: Optional[str] = None
               ) -> Dict[str, object]:
    """The ``pio_build_info`` label set: package + jax versions, the
    live backend, process_count, and local/global device counts (the
    mesh denominators every bench line and trace is attributed
    against). Backend-dependent labels degrade to ``"none"`` rather
    than initializing a backend (the :func:`hbm_stats` discipline)."""
    import sys

    if version is None:
        try:
            from .. import __version__ as version
        except Exception:  # noqa: BLE001
            version = "unknown"
    info: Dict[str, object] = {"server": server, "version": version}
    if "jax" not in sys.modules:
        info.update(jax="none", backend="none", process_count=0,
                    devices=0)
        return info
    try:
        import jax

        info["jax"] = getattr(jax, "__version__", "unknown")
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized") \
                and not xla_bridge.backends_are_initialized():
            info.update(backend="none", process_count=0, devices=0)
            return info
        info["backend"] = jax.default_backend()
        info["process_count"] = int(jax.process_count())
        info["devices"] = int(jax.device_count())
    except Exception:  # noqa: BLE001 — build info must never fail a
        info.setdefault("jax", "unknown")        # scrape
        info.setdefault("backend", "none")
        info.setdefault("process_count", 0)
        info.setdefault("devices", 0)
    return info


def process_stats() -> Dict[str, float]:
    """Host-resource self-read off ``/proc`` (Linux only, no psutil —
    the ISSUE 17 constraint): RSS bytes, cumulative CPU seconds
    (user+sys), open fd count, thread count. Empty dict where /proc is
    absent (macOS CI shards) — the gauges simply read 0 there."""
    import os

    out: Dict[str, float] = {}
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        page = os.sysconf("SC_PAGESIZE")
        out["rss_bytes"] = float(int(fields[1]) * page)
    except Exception:  # noqa: BLE001 — absent /proc degrades to {}
        return {}
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # comm can contain spaces/parens: split after the LAST ")"
        rest = stat.rsplit(")", 1)[1].split()
        tck = os.sysconf("SC_CLK_TCK")
        # rest[0] is field 3 (state); utime/stime are fields 14/15
        out["cpu_seconds_total"] = (int(rest[11]) + int(rest[12])) \
            / float(tck)
        out["threads"] = float(int(rest[17]))
    except Exception:  # noqa: BLE001
        pass
    try:
        out["open_fds"] = float(len(os.listdir("/proc/self/fd")))
    except Exception:  # noqa: BLE001
        pass
    return out


def register_process_metrics(reg: MetricsRegistry) -> None:
    """Mount the ``pio_process_{rss_bytes,cpu_seconds_total,open_fds,
    threads}`` fn-gauges — the host-saturation half of a scale-out
    decision (a replica can be SLO-green but one fd leak or one core
    short of falling over). Callable-backed so every scrape reads the
    live /proc values; no-op registration where /proc is absent."""
    if not process_stats():
        return

    def _read(key: str):
        return lambda: process_stats().get(key, 0.0)

    reg.gauge("pio_process_rss_bytes",
              "Resident set size of this server process "
              "(/proc/self/statm)", fn=_read("rss_bytes"))
    reg.gauge("pio_process_cpu_seconds_total",
              "Cumulative user+system CPU seconds of this process "
              "(/proc/self/stat)", fn=_read("cpu_seconds_total"))
    reg.gauge("pio_process_open_fds",
              "Open file descriptors (/proc/self/fd)",
              fn=_read("open_fds"))
    reg.gauge("pio_process_threads",
              "OS threads in this process (/proc/self/stat)",
              fn=_read("threads"))


def register_runtime_metrics(reg: MetricsRegistry, server: str,
                             version: Optional[str] = None) -> None:
    """Mount the standard process-level series on ``reg``:

    - ``pio_build_info{server,version,jax,backend,process_count,
      devices}`` — constant-1 info gauge rendered at scrape time so
      bench lines and retained traces are attributable to the exact
      build/runtime that produced them; the jax/backend/device labels
      appear only once a backend is live (scraping NEVER initializes
      one) and refresh on the next scrape after deploy brings it up
    - ``pio_process_start_time_seconds``
    - ``pio_xla_compiles_total`` — lifetime XLA backend compiles
      (:class:`..server.stats.RecompileSentinel` listener)
    - ``pio_transfer_guard_violations_total`` — guard hits tallied by
      :class:`.guard.TransferGuardCounter`
    - ``pio_device_hbm_bytes{device,kind,stat=used|limit|peak}`` —
      per-device HBM occupancy, absent off-TPU
    - ``pio_process_{rss_bytes,cpu_seconds_total,open_fds,threads}``
      — /proc self-read host-resource gauges
      (:func:`register_process_metrics`), absent without /proc
    """
    # idempotent per registry: a second build_app over the same
    # registry must not double-register the hbm/span collectors
    # (duplicate series would make the exposition invalid)
    if getattr(reg, "_runtime_mounted", False):
        return
    reg._runtime_mounted = True  # type: ignore[attr-defined]
    if version is None:
        try:
            from .. import __version__ as version
        except Exception:  # noqa: BLE001
            version = "unknown"
    from .registry import escape_label_value as _esc

    def _build_info_lines() -> List[str]:
        # render-time collector, not a statically-bound gauge: the
        # jax/backend/mesh labels describe whatever is live AT SCRAPE
        # TIME (a backend deploy brings up after mount still shows),
        # and a jax-free server never pays the import
        info = build_info(server, str(version))
        labels = ",".join(f'{k}="{_esc(str(v))}"'
                          for k, v in sorted(info.items()))
        return ["# HELP pio_build_info Constant 1; identifies the "
                "build and runtime being scraped",
                "# TYPE pio_build_info gauge",
                "pio_build_info{%s} 1" % labels]

    reg.register_collector(_build_info_lines)
    reg.gauge("pio_process_start_time_seconds",
              "Unix time this server process started"
              ).set(reg.start_time)

    def _compiles_total() -> float:
        # storage-only servers never import jax (the CLI skips it on
        # purpose); a /metrics scrape must not be the thing that pays
        # the import. When jax IS loaded, the sentinel's listener
        # installs once and the gauge reads the shared tally.
        import sys

        if "jax" not in sys.modules:
            return 0.0
        from ..server.stats import RecompileSentinel

        RecompileSentinel()  # idempotent listener install
        return float(RecompileSentinel.total_compiles())

    reg.gauge("pio_xla_compiles_total",
              "XLA backend compiles observed in this process",
              fn=_compiles_total)

    TransferGuardCounter.install()
    reg.gauge("pio_transfer_guard_violations_total",
              "Transfer-guard hits (implicit device<->host transfers "
              "observed under transfer_guard)",
              fn=TransferGuardCounter.total)

    # HBM is a render-time collector, not statically bound gauges:
    # devices that come up AFTER the server mounts its registry (deploy
    # initializes the backend when models land in HBM) still appear on
    # the next scrape, and a device-less server emits nothing.
    from .registry import escape_label_value, format_value

    def _hbm_lines() -> List[str]:
        stats = hbm_stats()
        if not stats:
            return []
        lines = ["# HELP pio_device_hbm_bytes Per-device HBM occupancy "
                 "from device.memory_stats(); absent off-TPU",
                 "# TYPE pio_device_hbm_bytes gauge"]
        for e in stats:
            for key, stat in (("bytesInUse", "used"),
                              ("bytesLimit", "limit"),
                              ("peakBytesInUse", "peak")):
                lines.append(
                    'pio_device_hbm_bytes{device="%s",kind="%s",stat="%s"} %s'
                    % (escape_label_value(str(e["device"])),
                       escape_label_value(str(e["kind"])), stat,
                       format_value(float(e[key]))))  # type: ignore[arg-type]
        return lines

    reg.register_collector(_hbm_lines)
    register_process_metrics(reg)
