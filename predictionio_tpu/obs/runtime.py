"""TPU-native runtime gauges: XLA compiles, HBM occupancy, guard hits.

ALX-style TPU serving treats HBM occupancy and recompile counts as
first-class signals (PAPERS: Google ads-serving infrastructure) — a
recompile storm or HBM creep shows up in the tail long before it shows
up in an error log. These helpers register the process-level series on
any :class:`.registry.MetricsRegistry`; everything degrades gracefully
off-TPU (gauges read 0 or are simply absent).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .guard import TransferGuardCounter
from .registry import MetricsRegistry


def hbm_stats() -> List[Dict[str, object]]:
    """Per-device HBM bytes in use / limit via ``device.memory_stats()``;
    empty off-TPU (CPU PJRT returns None), when jax is absent, or when
    no backend is initialized yet. NEVER initializes a backend itself:
    an event/storage server scraping /metrics must not acquire the TPU
    (operations.md "one chip, one tenant") just to report on it."""
    import sys

    if "jax" not in sys.modules:  # jax-free server: nothing to report,
        return []                 # and a scrape must not pay the import
    try:
        import jax
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized") \
                and not xla_bridge.backends_are_initialized():
            return []
        devices = jax.devices()
    except Exception:  # noqa: BLE001 — observability never requires jax
        return []
    out: List[Dict[str, object]] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-device degrade
            stats = None
        if not stats:
            continue
        out.append({
            "device": str(d.id),
            "kind": getattr(d, "device_kind", "unknown"),
            "bytesInUse": int(stats.get("bytes_in_use", 0)),
            "bytesLimit": int(stats.get("bytes_limit", 0) or
                              stats.get("bytes_reservable_limit", 0)),
            "peakBytesInUse": int(stats.get("peak_bytes_in_use", 0)),
        })
    return out


def register_runtime_metrics(reg: MetricsRegistry, server: str,
                             version: Optional[str] = None) -> None:
    """Mount the standard process-level series on ``reg``:

    - ``pio_build_info{server,version}`` — constant 1
    - ``pio_process_start_time_seconds``
    - ``pio_xla_compiles_total`` — lifetime XLA backend compiles
      (:class:`..server.stats.RecompileSentinel` listener)
    - ``pio_transfer_guard_violations_total`` — guard hits tallied by
      :class:`.guard.TransferGuardCounter`
    - ``pio_device_hbm_bytes{device,kind,stat=used|limit|peak}`` —
      per-device HBM occupancy, absent off-TPU
    """
    # idempotent per registry: a second build_app over the same
    # registry must not double-register the hbm/span collectors
    # (duplicate series would make the exposition invalid)
    if getattr(reg, "_runtime_mounted", False):
        return
    reg._runtime_mounted = True  # type: ignore[attr-defined]
    if version is None:
        try:
            from .. import __version__ as version
        except Exception:  # noqa: BLE001
            version = "unknown"
    reg.gauge("pio_build_info",
              "Constant 1, labeled with server name and version"
              ).labels(server=server, version=str(version)).set(1)
    reg.gauge("pio_process_start_time_seconds",
              "Unix time this server process started"
              ).set(reg.start_time)

    def _compiles_total() -> float:
        # storage-only servers never import jax (the CLI skips it on
        # purpose); a /metrics scrape must not be the thing that pays
        # the import. When jax IS loaded, the sentinel's listener
        # installs once and the gauge reads the shared tally.
        import sys

        if "jax" not in sys.modules:
            return 0.0
        from ..server.stats import RecompileSentinel

        RecompileSentinel()  # idempotent listener install
        return float(RecompileSentinel.total_compiles())

    reg.gauge("pio_xla_compiles_total",
              "XLA backend compiles observed in this process",
              fn=_compiles_total)

    TransferGuardCounter.install()
    reg.gauge("pio_transfer_guard_violations_total",
              "Transfer-guard hits (implicit device<->host transfers "
              "observed under transfer_guard)",
              fn=TransferGuardCounter.total)

    # HBM is a render-time collector, not statically bound gauges:
    # devices that come up AFTER the server mounts its registry (deploy
    # initializes the backend when models land in HBM) still appear on
    # the next scrape, and a device-less server emits nothing.
    from .registry import escape_label_value, format_value

    def _hbm_lines() -> List[str]:
        stats = hbm_stats()
        if not stats:
            return []
        lines = ["# HELP pio_device_hbm_bytes Per-device HBM occupancy "
                 "from device.memory_stats(); absent off-TPU",
                 "# TYPE pio_device_hbm_bytes gauge"]
        for e in stats:
            for key, stat in (("bytesInUse", "used"),
                              ("bytesLimit", "limit"),
                              ("peakBytesInUse", "peak")):
                lines.append(
                    'pio_device_hbm_bytes{device="%s",kind="%s",stat="%s"} %s'
                    % (escape_label_value(str(e["device"])),
                       escape_label_value(str(e["kind"])), stat,
                       format_value(float(e[key]))))  # type: ignore[arg-type]
        return lines

    reg.register_collector(_hbm_lines)
