"""Space-Saving heavy-hitter sketch: hot-key telemetry in O(k) memory.

The signal the fleet's future consistent-hash router needs for
spill-on-hot-spot placement (ISSUE 17): which entity ids dominate the
query stream, per replica and fleet-wide. An exact per-key counter is
unbounded on a server that lives for weeks; the Space-Saving sketch
(Metwally, Agrawal, El Abbadi 2005) keeps exactly ``k`` monitored keys
and, on a miss, EVICTS the current minimum and adopts its count as the
newcomer's floor — guaranteeing every key whose true frequency exceeds
``N/k`` is monitored, with a per-key overestimate bound (``error``)
carried alongside so consumers can see how tight each count is.

``record`` is O(k) (a linear min-scan over a dict of ``k`` entries —
k defaults to 128, so this is a few hundred nanoseconds on the query
path, far below one JSON parse). Sketches merge: summing counts and
errors for shared keys and evict-min-inserting the rest preserves the
frequency guarantee fleet-wide, which is how the aggregator builds the
fleet-level top-K from per-replica sketches.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["SpaceSaving", "mount_hot_key_metrics"]


class SpaceSaving:
    """Thread-safe Space-Saving top-K sketch over string keys."""

    __slots__ = ("capacity", "_counts", "_errors", "_total", "_lock")

    def __init__(self, capacity: int = 128) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._counts: Dict[str, float] = {}
        self._errors: Dict[str, float] = {}
        self._total = 0.0
        self._lock = threading.Lock()

    def record(self, key: Optional[str], count: float = 1.0) -> None:
        """Count one occurrence of ``key`` (None/empty ignored — the
        query had no entity, nothing to place)."""
        if not key:
            return
        with self._lock:
            self._total += count
            self._insert_locked(str(key), count, 0.0)

    def _insert_locked(self, k: str, count: float,
                       error: float) -> None:
        if k in self._counts:
            self._counts[k] += count
            self._errors[k] = self._errors.get(k, 0.0) + error
            return
        if len(self._counts) < self.capacity:
            self._counts[k] = count
            self._errors[k] = error
            return
        # evict the minimum-count key; the newcomer inherits its
        # count as a floor (the Space-Saving overestimate) and
        # records that floor as its error bound
        victim = min(self._counts, key=self._counts.__getitem__)
        floor = self._counts.pop(victim)
        self._errors.pop(victim, None)
        self._counts[k] = floor + count
        self._errors[k] = floor + error

    @property
    def total(self) -> float:
        """Observations recorded (including evicted keys' mass)."""
        with self._lock:
            return self._total

    def top(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Hottest keys, descending: ``[{"key", "count", "error"}]``.
        ``count`` may overestimate by at most ``error``; the true
        frequency is in ``[count - error, count]``."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: kv[1], reverse=True)
            errors = dict(self._errors)
        if n is not None:
            items = items[:n]
        return [{"key": k, "count": c, "error": errors.get(k, 0.0)}
                for k, c in items]

    def merge_items(self, items: Iterable[Dict[str, Any]],
                    total: float = 0.0) -> None:
        """Fold another sketch's :meth:`top` export into this one —
        shared keys sum counts AND errors (both bounds stay valid);
        novel keys insert through the normal evict-min path, their
        incoming error carried on top of the eviction floor."""
        with self._lock:
            self._total += float(total)
            for item in items:
                k = str(item.get("key") or "")
                if not k:
                    continue
                self._insert_locked(k,
                                    float(item.get("count", 0.0)),
                                    float(item.get("error", 0.0)))

    def snapshot(self, n: int = 16) -> Dict[str, Any]:
        """JSON block for ``/status.json`` and the fleet scrape."""
        return {"capacity": self.capacity, "total": self.total,
                "top": self.top(n)}

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._errors.clear()
            self._total = 0.0


def mount_hot_key_metrics(reg: Any, sketch: SpaceSaving,
                          top_n: int = 10,
                          metric_name: str = "pio_hot_keys") -> None:
    """Expose the sketch's current top-N as ``pio_hot_keys{rank,key}``
    gauge lines via a render-time collector. A collector (not a gauge
    family) because the hot set CHURNS: family children are permanent,
    so yesterday's hot key would linger as a stale zero series forever;
    a collector re-emits only the current top-N each scrape."""
    from .registry import escape_label_value, format_value

    def collect():
        top = sketch.top(top_n)
        if not top:
            return []
        lines = [f"# HELP {metric_name} Space-Saving heavy-hitter "
                 f"counts of query entity ids (top-{top_n}; count "
                 f"overestimates by at most the paired error bound)",
                 f"# TYPE {metric_name} gauge"]
        for rank, item in enumerate(top, start=1):
            key = escape_label_value(item["key"])
            lines.append(
                f'{metric_name}{{key="{key}",rank="{rank}"}} '
                f'{format_value(item["count"])}')
        return lines

    reg.register_collector(collect)
