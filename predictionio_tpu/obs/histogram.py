"""Streaming fixed-bucket histograms: O(1) record, bounded memory.

The round-1 ``SpanRegistry`` kept every observation in a raw per-name
list — unbounded memory on a server that lives for weeks, and no
percentiles without a sort over the whole history. A fixed-log-bucket
histogram replaces it: ``record`` is one bisect plus one increment,
memory is ``len(bounds) + 1`` integers forever, and p50/p90/p99/max are
derivable at read time by linear interpolation inside the target bucket
(the same estimator Prometheus' ``histogram_quantile`` applies to the
scraped cumulative buckets, so the server-side numbers and the
fleet-side PromQL numbers agree on the same data).
"""

from __future__ import annotations

import math
import threading
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Sequence, Tuple


def exponential_bounds(start: float, factor: float,
                       count: int) -> List[float]:
    """``count`` log-spaced bucket upper bounds from ``start``."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return [start * factor ** i for i in range(count)]


def linear_bounds(start: float, width: float, count: int) -> List[float]:
    """``count`` evenly spaced bucket upper bounds from ``start``."""
    if width <= 0 or count < 1:
        raise ValueError("need width > 0, count >= 1")
    return [start + width * i for i in range(count)]


#: Default latency buckets: 100µs → ~105s, ×2 per bucket (21 buckets).
#: Wide enough for host fast-path serving (sub-ms) AND a cold XLA
#: compile paid on the query path (tens of seconds, the round-4 p99
#: pathology) to land inside the measured range rather than overflow.
DEFAULT_LATENCY_BOUNDS: Tuple[float, ...] = tuple(
    exponential_bounds(0.0001, 2.0, 21))

#: Small-integer buckets (batch occupancy, queue depth): pow2 ladder
#: 1..1024 — matches the micro-batcher's warmed shape ladder.
POW2_COUNT_BOUNDS: Tuple[float, ...] = tuple(
    float(1 << i) for i in range(11))


class StreamingHistogram:
    """Thread-safe fixed-bucket histogram.

    ``bounds`` are strictly increasing *inclusive* upper bounds
    (Prometheus ``le`` semantics); one overflow bucket is implicit.
    """

    __slots__ = ("bounds", "_counts", "_count", "_sum", "_min", "_max",
                 "_lock", "_exemplars")

    def __init__(self,
                 bounds: Optional[Sequence[float]] = None) -> None:
        bs = tuple(float(b) for b in
                   (bounds if bounds is not None
                    else DEFAULT_LATENCY_BOUNDS))
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError("bounds must be non-empty and strictly "
                             "increasing")
        self.bounds = bs
        self._counts = [0] * (len(bs) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()
        # OpenMetrics exemplars: lazily allocated {bucket index →
        # (trace_id, value, unix ts)} — histograms that never see a
        # retained trace pay one None slot, nothing more
        self._exemplars: Optional[Dict[int, Tuple[str, float,
                                                  float]]] = None

    @classmethod
    def from_buckets(cls, buckets: Sequence[Tuple[Any, float]],
                     sum: Optional[float] = None,
                     minimum: Optional[float] = None,
                     maximum: Optional[float] = None
                     ) -> "StreamingHistogram":
        """Rebuild a histogram from cumulative ``(le, count)`` pairs —
        the exact :meth:`bucket_counts` / exposition shape, with the
        last ``le`` ``inf`` (or the JSON-safe string ``"+Inf"``). The
        inverse of the scrape: a fleet aggregator that pulled a
        replica's cumulative buckets gets back a mergeable histogram.
        ``sum``/``minimum``/``maximum`` carry the replica's exact
        moments when known; absent, they are estimated from bucket
        edges (bucket-resolution truth, same as any quantile here)."""
        if len(buckets) < 2:
            raise ValueError("need at least one finite bucket + +Inf")
        les: List[float] = []
        cums: List[float] = []
        for le, cum in buckets:
            if isinstance(le, str):
                le = math.inf if le in ("+Inf", "inf", "Inf") \
                    else float(le)
            les.append(float(le))
            cums.append(float(cum))
        if not math.isinf(les[-1]):
            raise ValueError("last bucket upper bound must be +Inf")
        hist = cls(bounds=les[:-1])
        prev = 0.0
        counts: List[int] = []
        for cum in cums:
            d = cum - prev
            if d < 0:
                raise ValueError("cumulative bucket counts must be "
                                 "non-decreasing")
            counts.append(int(d))
            prev = cum
        hist._counts = counts
        n = 0
        for c in counts:
            n += c
        hist._count = n
        if n:
            # estimate missing moments from bucket edges: lowest
            # occupied bucket's lower edge / highest occupied bucket's
            # upper bound (overflow falls back to the last bound)
            lo_i = next(i for i, c in enumerate(counts) if c)
            hi_i = next(i for i in range(len(counts) - 1, -1, -1)
                        if counts[i])
            est_min = hist.bounds[lo_i - 1] if lo_i > 0 \
                else hist.bounds[0]
            est_max = hist.bounds[min(hi_i, len(hist.bounds) - 1)]
            hist._min = float(minimum) if minimum is not None \
                else est_min
            hist._max = float(maximum) if maximum is not None \
                else est_max
            if sum is not None:
                hist._sum = float(sum)
            else:
                s = 0.0
                for i, c in enumerate(counts):
                    if c:
                        s += c * hist.bounds[min(i, len(hist.bounds)
                                                 - 1)]
                hist._sum = s
        elif sum is not None:
            hist._sum = float(sum)
        return hist

    def merge(self, other: "StreamingHistogram") -> None:
        """Fold ``other``'s observations into this histogram —
        LOSSLESS at bucket resolution because both sides share fixed
        bounds: per-bucket counts ADD, so any quantile of the merged
        histogram is the pooled-population quantile, not an
        average-of-percentiles. Bounds must match exactly (merging
        mismatched bucket layouts would silently mis-bin). Locks are
        taken sequentially (snapshot ``other``, then update ``self``)
        — never nested, so merge can never deadlock against a
        concurrent ``record``."""
        if other.bounds != self.bounds:
            raise ValueError(
                "cannot merge histograms with different bounds "
                f"({len(other.bounds)} vs {len(self.bounds)} buckets)")
        with other._lock:
            counts = list(other._counts)
            n = other._count
            s = other._sum
            lo, hi = other._min, other._max
        if n == 0:
            return
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._count += n
            self._sum += s
            if lo < self._min:
                self._min = lo
            if hi > self._max:
                self._max = hi

    def record(self, value: float) -> None:
        """O(1): one bisect over the fixed bounds + one increment."""
        v = float(value)
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    # Prometheus naming for drop-in familiarity
    observe = record

    def record_exemplar(self, value: float, trace_id: str,
                        ts: Optional[float] = None) -> None:
        """Attach (or replace) the exemplar of the bucket ``value``
        falls in: last retained trace id per bucket, so a ``/metrics``
        p99 bucket links straight to a ``/trace.json?id=`` lookup
        (OpenMetrics exposition only renders these under
        ``Accept: application/openmetrics-text``)."""
        v = float(value)
        i = bisect_left(self.bounds, v)
        with self._lock:
            if self._exemplars is None:
                self._exemplars = {}
            self._exemplars[i] = (str(trace_id), v,
                                  ts if ts is not None else time.time())

    def exemplars(self) -> Dict[int, Tuple[str, float, float]]:
        """``{bucket index → (trace_id, value, ts)}``; index
        ``len(bounds)`` is the overflow (+Inf) bucket."""
        with self._lock:
            return dict(self._exemplars) if self._exemplars else {}

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def max(self) -> float:
        with self._lock:
            return self._max if self._count else 0.0

    @property
    def min(self) -> float:
        with self._lock:
            return self._min if self._count else 0.0

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs, ending with ``(inf, n)`` —
        exactly the Prometheus exposition shape."""
        with self._lock:
            counts = list(self._counts)
        out: List[Tuple[float, int]] = []
        cum = 0
        for b, c in zip(self.bounds, counts):
            cum += c
            out.append((b, cum))
        out.append((math.inf, cum + counts[-1]))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-quantile (``q`` in [0, 1]) by linear
        interpolation inside the target bucket; None when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        with self._lock:
            counts = list(self._counts)
            n = self._count
            lo_seen, hi_seen = self._min, self._max
        if n == 0:
            return None
        target = q * n
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.bounds[i - 1] if i > 0 else min(
                    lo_seen, self.bounds[0])
                hi = (self.bounds[i] if i < len(self.bounds)
                      else hi_seen)
                hi = max(hi, lo)
                v = lo + (hi - lo) * ((target - cum) / c)
                # never report outside the observed range
                return min(max(v, lo_seen), hi_seen)
            cum += c
        return hi_seen

    def snapshot(self) -> Dict[str, float]:
        """count/sum/mean/min/max plus the standard percentile trio."""
        with self._lock:
            n, s = self._count, self._sum
        if n == 0:
            return {"count": 0}
        return {
            "count": n,
            "sum": s,
            "mean": s / n,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf
            self._exemplars = None


def window_quantile(start: List[Tuple[float, int]],
                    now: List[Tuple[float, int]],
                    q: float) -> Optional[float]:
    """Quantile of the observations that landed BETWEEN two cumulative
    :meth:`StreamingHistogram.bucket_counts` snapshots of the same
    histogram — the sliding-window read (cumulative-count deltas per
    bucket ARE the window's own histogram; the rollout health gate
    windows candidate-vs-stable p99 this way). Interpolates inside the
    target bucket like :meth:`StreamingHistogram.quantile`; returns
    None on an empty window, mismatched snapshots, or a *wrapped*
    window (any per-bucket delta negative — the histogram was reset or
    swapped between the snapshots, so the delta is not a histogram of
    anything; before this guard a reset mid-window could synthesize
    quantiles out of garbage, and a lookback that predates the first
    sample could report "quantiles" from an empty delta instead of
    admitting it has no data — ISSUE 15 satellite)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError("q must be in [0, 1]")
    if len(start) != len(now):
        # bounds changed between snapshots (rebind with different
        # buckets): no sample rather than mis-mixing the two shapes
        return None
    deltas: List[Tuple[float, int]] = []
    prev_s = prev_n = 0
    for (le_s, cum_s), (le_n, cum_n) in zip(start, now):
        if le_s != le_n:
            return None
        d = (cum_n - prev_n) - (cum_s - prev_s)
        if d < 0:
            # the "now" snapshot has FEWER observations than "start"
            # in this bucket: reset/swap between snapshots — refuse
            return None
        deltas.append((le_n, d))
        prev_s, prev_n = cum_s, cum_n
    total = sum(c for _, c in deltas)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    lo = 0.0
    for le, c in deltas:
        if c > 0 and cum + c >= target:
            hi = lo * 2 if math.isinf(le) else le
            return lo + (max(hi, lo) - lo) * ((target - cum) / c)
        cum += c
        if not math.isinf(le):
            lo = le
    return lo
