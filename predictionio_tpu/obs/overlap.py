"""Overlap accounting for the staged serving pipeline (ISSUE 9).

The whole point of splitting the serving batch path into assemble →
dispatch → readback stages is that the device computes WHILE the host
parses/supplements the next batch and serializes the previous one. A
claim like that needs a number, not an architecture diagram:
:class:`OverlapTracker` accrues wall-clock into per-track busy counters
and into an overlap counter whenever the device track and at least one
host track are simultaneously active. The engine server exports the
fractions as ``pio_pipeline_device_idle_fraction`` and
``pio_pipeline_overlap_fraction`` (docs/observability.md) — a serial
drainer shows overlap ≈ 0; the staged pipeline under load must not.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

#: the accelerator track; every other track name counts as host work
DEVICE_TRACK = "device"


class OverlapTracker:
    """O(1)-per-transition wall-clock accounting over named activity
    tracks. ``enter(track)``/``exit(track)`` bracket activity (tracks
    are counted, so concurrent batches nest); between any two
    transitions the elapsed time accrues into every active track's
    busy counter, and into the overlap counter when ``"device"`` and
    any host track were both active. The wall-clock origin is the
    FIRST ``enter`` — idle time before traffic ever arrived does not
    dilute the fractions."""

    def __init__(self, time_fn=time.monotonic):
        self._time = time_fn
        self._lock = threading.Lock()
        self._active: Dict[str, int] = {}
        self._busy: Dict[str, float] = {}
        self._overlap = 0.0
        self._t0 = None
        self._last = None

    # ptpu: guarded-by[_lock] — internal accrual step, only ever called
    # with self._lock held by enter/exit/snapshot
    def _accrue(self, now: float) -> None:
        if self._last is None:
            return
        dt = now - self._last
        if dt <= 0:
            return
        device = self._active.get(DEVICE_TRACK, 0) > 0
        host = any(n > 0 for t, n in self._active.items()
                   if t != DEVICE_TRACK)
        for t, n in self._active.items():
            if n > 0:
                self._busy[t] = self._busy.get(t, 0.0) + dt
        if device and host:
            self._overlap += dt

    def enter(self, track: str) -> int:
        """Mark ``track`` active; returns the PRIOR active count (a
        dispatch stage uses ``enter("device") > 0`` as "this launch
        overlapped an in-flight batch")."""
        with self._lock:
            now = self._time()
            if self._t0 is None:
                self._t0 = now
            self._accrue(now)
            self._last = now
            prev = self._active.get(track, 0)
            self._active[track] = prev + 1
            return prev

    def exit(self, track: str) -> None:
        with self._lock:
            now = self._time()
            self._accrue(now)
            self._last = now
            self._active[track] = max(self._active.get(track, 0) - 1, 0)

    def active(self, track: str) -> int:
        with self._lock:
            return self._active.get(track, 0)

    def snapshot(self) -> dict:
        """Cumulative view: wall seconds since first activity, per-track
        busy seconds, device busy/idle fractions, and the overlap
        fraction (device ∧ host active). In-progress intervals are
        folded in up to now."""
        with self._lock:
            now = self._time()
            self._accrue(now)
            self._last = now
            wall = (now - self._t0) if self._t0 is not None else 0.0
            busy = dict(self._busy)
            overlap = self._overlap
        device_busy = busy.get(DEVICE_TRACK, 0.0)
        return {
            "wall_sec": wall,
            "busy_sec": busy,
            "device_busy_sec": device_busy,
            "device_busy_fraction": (device_busy / wall) if wall > 0
            else 0.0,
            "device_idle_fraction": (1.0 - device_busy / wall)
            if wall > 0 else 1.0,
            "overlap_sec": overlap,
            "overlap_fraction": (overlap / wall) if wall > 0 else 0.0,
        }

    def device_idle_fraction(self) -> float:
        return self.snapshot()["device_idle_fraction"]

    def overlap_fraction(self) -> float:
        return self.snapshot()["overlap_fraction"]
