"""Opt-in runtime NaN/Inf sentinels for the numeric serving stack.

The static dtype-flow rules and ``ptpu audit-numerics`` gate precision
*structure*; this module watches the *values* at the two seams where a
nonfinite can enter production silently: the streaming fold-in solve
(a NaN row hot-swapped into the serving table poisons every score it
touches) and the serving top-k scores themselves.

Design constraints (the fault-registry pattern,
:mod:`predictionio_tpu.faults.registry`):

- **Zero overhead off.** Every instrumented site goes through one
  module-global bool check; production pays nothing. Enabled via
  ``ServerConfig.debug_numerics`` or ``PTPU_DEBUG_NUMERICS=1``.
- **Device-side where it matters.** :func:`checked_call` wraps a
  jitted entry point with ``jax.experimental.checkify``
  (``float_checks``), so a NaN is attributed to the entry that
  *produced* it even when later ops would mask it (a ``jnp.where``
  or top-k can hide an upstream NaN from a host probe).
- **Host-side at the seams.** :func:`check_array` is a plain
  ``np.isfinite`` sweep for host-resident boundaries.
- **Listener fan-out.** The engine server subscribes a listener that
  bumps ``pio_numerics_checks_total`` /
  ``pio_numerics_nonfinite_total{entry=…}`` and flags ``nonfinite``
  in ``/status.json``'s degraded block (docs/observability.md).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Tuple

#: the ONE fast-path gate: False ⇒ instrumented sites return before
#: touching anything else — serving hot paths stay free in production
_ACTIVE = False

_lock = threading.Lock()
_stats: Dict[str, List[int]] = {}   # entry → [checks, nonfinite]
_listeners: List[Callable[[str, bool], None]] = []
_checked_cache: Dict[Tuple[str, int], Callable] = {}


def debug_env() -> bool:
    """``PTPU_DEBUG_NUMERICS=1`` — the no-config-change enable (the
    staging runbook path, mirroring ``PTPU_DEBUG_LOCKS``)."""
    return os.environ.get("PTPU_DEBUG_NUMERICS", "").strip().lower() \
        in ("1", "true", "yes", "on")


def enable() -> None:
    global _ACTIVE
    _ACTIVE = True


def disable() -> None:
    global _ACTIVE
    _ACTIVE = False


def active() -> bool:
    return _ACTIVE


def add_listener(cb: Callable[[str, bool], None]) -> None:
    """``cb(entry, nonfinite)`` after every delivered check."""
    with _lock:
        _listeners.append(cb)


def remove_listener(cb: Callable[[str, bool], None]) -> None:
    with _lock:
        try:
            _listeners.remove(cb)
        except ValueError:
            pass


def _record(entry: str, bad: bool) -> None:
    with _lock:
        st = _stats.setdefault(entry, [0, 0])
        st[0] += 1
        if bad:
            st[1] += 1
        listeners = list(_listeners)
    for cb in listeners:
        try:
            cb(entry, bad)
        except Exception:  # noqa: BLE001 — telemetry only
            pass


def check_array(entry: str, arr, *, nan_only: bool = False) -> bool:
    """Host finiteness probe; True when clean (or inactive). Forces a
    device sync for device arrays — the documented cost of the debug
    mode. ``nan_only`` is for seams where ±inf is a legitimate mask
    sentinel (top-k scores pad with -inf)."""
    if not _ACTIVE:
        return True
    import numpy as np

    a = np.asarray(arr)
    if a.dtype.kind != "f":
        bad = False
    elif nan_only:
        bad = bool(np.isnan(a).any())
    else:
        bad = bool(not np.isfinite(a).all())
    _record(entry, bad)
    return not bad


def checked_call(entry: str, fn: Callable, *args, **kwargs):
    """Run ``fn`` under checkify ``float_checks`` when active —
    transparent pass-through when off. The wrapped function is cached
    per ``(entry, fn)`` so the checkified trace compiles once; the
    error readback forces a device sync (debug-mode cost). Falls back
    to a plain call plus a host probe of the first output if checkify
    cannot trace the callable."""
    if not _ACTIVE:
        return fn(*args, **kwargs)
    key = (entry, id(fn))
    wrapped = _checked_cache.get(key)
    if wrapped is None:
        try:
            from jax.experimental import checkify

            wrapped = checkify.checkify(fn,
                                        errors=checkify.float_checks)
        except Exception:  # noqa: BLE001 — checkify unavailable
            wrapped = False
        _checked_cache[key] = wrapped
    if wrapped is False:
        out = fn(*args, **kwargs)
        first = out[0] if isinstance(out, tuple) and out else out
        check_array(entry, first)
        return out
    try:
        err, out = wrapped(*args, **kwargs)
    except Exception:
        # a callable checkify accepted at wrap time but cannot trace
        # (e.g. exotic static-arg plumbing): degrade to the host probe
        # permanently for this entry rather than failing the serve path
        _checked_cache[key] = False
        out = fn(*args, **kwargs)
        first = out[0] if isinstance(out, tuple) and out else out
        check_array(entry, first)
        return out
    bad = err.get() is not None
    _record(entry, bad)
    return out


def nonfinite_seen() -> bool:
    """Any sentinel check observed NaN/Inf since the last reset — the
    ``nonfinite`` flag of ``/status.json``'s degraded block."""
    with _lock:
        return any(st[1] for st in _stats.values())


def stats() -> Dict[str, Dict[str, int]]:
    with _lock:
        return {entry: {"checks": st[0], "nonfinite": st[1]}
                for entry, st in sorted(_stats.items())}


def reset_for_tests() -> None:
    global _ACTIVE
    with _lock:
        _stats.clear()
        _listeners.clear()
        _checked_cache.clear()
    _ACTIVE = False


__all__ = [
    "active",
    "add_listener",
    "check_array",
    "checked_call",
    "debug_env",
    "disable",
    "enable",
    "nonfinite_seen",
    "remove_listener",
    "reset_for_tests",
    "stats",
]
