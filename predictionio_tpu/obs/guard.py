"""Transfer-guard violation counter.

``ServerConfig.transfer_guard="log"`` wraps the post-warmup query path
in ``jax.transfer_guard("log")`` so every implicit device↔host transfer
is logged instead of silently stalling dispatch (PR 1). That made
violations *visible in the log stream* but not *countable*: an operator
watching ``/metrics`` had no series to alert on. This module closes the
loop with a ``logging.Handler`` installed across the ``jax`` logger
hierarchy (and the root logger, for guard messages that propagate) that
tallies records matching the guard's message shapes.

Caveat, documented rather than hidden: some jax builds emit log-mode
guard messages from the C++ PJRT layer straight to stderr, bypassing
Python ``logging`` entirely — there the counter stays at zero and the
log lines remain the source of truth. Python-side guard errors (the
``disallow`` level's exception text, re-logged by the server) and any
Python-logged guard message are always counted.
"""

from __future__ import annotations

import logging
import re
import threading

#: Message shapes of jax's transfer-guard diagnostics (log and
#: disallow levels; host↔device both directions, device→device).
_GUARD_RE = re.compile(
    r"(disallowed|guarded)?\s*"
    r"(host-to-device|device-to-host|device-to-device)\s+transfer",
    re.IGNORECASE)


class TransferGuardCounter(logging.Handler):
    """Process-wide tally of transfer-guard hits seen via ``logging``.

    Install once per process (:meth:`install`); every instance reads the
    same shared counter, mirroring :class:`..server.stats.RecompileSentinel`'s
    shape (cheap instances over one process-wide listener).
    """

    _lock = threading.Lock()
    _total = 0
    _installed = False

    def emit(self, record: logging.LogRecord) -> None:
        # the shared handler sits on both the `jax` logger and root: a
        # record logged under `jax` propagates to root and would fire
        # this handler twice — mark the record so it counts once
        if getattr(record, "_ptpu_guard_seen", False):
            return
        record._ptpu_guard_seen = True
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — a malformed record must not
            return         # crash the emitting thread
        if _GUARD_RE.search(msg):
            with TransferGuardCounter._lock:
                TransferGuardCounter._total += 1

    @classmethod
    def install(cls) -> "TransferGuardCounter":
        """Attach one shared handler to the ``jax`` logger and the root
        logger (idempotent)."""
        # constructed BEFORE taking _lock: Handler.__init__ acquires
        # logging's module lock, and nesting foreign locks under our
        # own is exactly what ptpu check's lock-order rule forbids
        handler = cls(level=logging.DEBUG)
        with cls._lock:
            if cls._installed:
                installed = cls._shared
            else:
                cls._installed = True
                cls._shared = installed = handler
        if installed is not handler:
            handler.close()  # lost the race: drop the spare
            return installed
        for name in ("jax", None):
            logger = logging.getLogger(name)
            if handler not in logger.handlers:
                logger.addHandler(handler)
        return handler

    _shared: "TransferGuardCounter"

    @classmethod
    def total(cls) -> int:
        with cls._lock:
            return cls._total

    @classmethod
    def count(cls, n: int = 1) -> None:
        """Direct tally for callers that catch a guard *exception*
        (``transfer_guard="disallow"``) rather than a log line."""
        with cls._lock:
            cls._total += n
