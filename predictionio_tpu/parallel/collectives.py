"""Collective-communication helpers over the framework mesh.

The TPU-native replacement for the reference's driver⇄executor
communication (Spark shuffle/broadcast/collect — SURVEY §2.3): inside a
``shard_map``-ped function these wrap XLA collectives that ride ICI
within a slice and DCN across slices; outside, the sharded-jit pattern
(annotate shardings, let XLA insert collectives) is usually preferable —
these exist for the cases where the schedule must be explicit (Gramian
all-reduce, halo exchanges, sharded top-k merge).
"""

from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import MODEL_AXIS

Axis = Union[str, Sequence[str]]


def shard_map_compat(fn: Callable, mesh: Mesh, in_specs, out_specs,
                     check: bool = False) -> Callable:
    """``shard_map`` across jax versions: new jaxes expose
    ``jax.shard_map(..., check_vma=)``, older ones only
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` — the
    replication-check knob was renamed along the way."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check)


def all_reduce_sum(x: jax.Array, axis: Axis = MODEL_AXIS) -> jax.Array:
    """``lax.psum`` — the Gramian/gradient all-reduce (NCCL allreduce
    role)."""
    return lax.psum(x, axis)


def gramian_allreduce(x: jax.Array, mesh: Mesh) -> jax.Array:
    """``xᵀx`` of a row-sharded ``[n, r]`` table as an EXPLICIT
    per-shard partial + ICI psum, replicated out.

    The fused-gram training path (``models/als.py::_fixed_gramian``)
    uses this instead of the plain einsum so the all-reduce is a
    structurally independent node: every update block's Pallas kernel
    builds its observed-entry system without touching G (the baseline
    Gramian is added to the kernel OUTPUT), which frees XLA's
    latency-hiding scheduler to run this collective on ICI underneath
    the next virtual-row block's gather DMAs and kernel launch rather
    than serializing each half-iteration behind it — the compute/
    collective overlap ALX builds its sharded trainer around
    (arXiv 2112.02194). Axis names come from the mesh, so the same
    program runs over a ``(data, model)`` training mesh and a
    ``(batch, model)`` serving mesh."""
    axes = tuple(mesh.axis_names)

    def part(t):
        return lax.psum(
            jax.lax.dot_general(t, t, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32),
            axes)

    return shard_map_compat(part, mesh, in_specs=P(axes),
                            out_specs=P(), check=False)(x)


def all_gather(x: jax.Array, axis: Axis = MODEL_AXIS,
               *, tiled: bool = True) -> jax.Array:
    """Gather shards along the leading dim (NCCL allgather role)."""
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: Axis = MODEL_AXIS) -> jax.Array:
    """Sum across the axis, scattering rows back (NCCL reduce-scatter)."""
    return lax.psum_scatter(x, axis, tiled=True)


def ring_permute(x: jax.Array, axis: Axis = MODEL_AXIS,
                 shift: int = 1) -> jax.Array:
    """Send each shard to its ring neighbor (``lax.ppermute``) — the
    building block for ring-structured algorithms (ring all-reduce,
    ring attention) on ICI."""
    # psum of a python 1 folds to the static axis size on every jax
    # this repo supports (lax.axis_size only exists on newer ones)
    n = lax.psum(1, axis) if not hasattr(lax, "axis_size") \
        else lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: Axis = MODEL_AXIS) -> jax.Array:
    return lax.axis_index(axis)


def sharded(mesh: Mesh, in_specs, out_specs,
            check_vma: bool = False) -> Callable:
    """Decorator: ``shard_map`` a function over the framework mesh.

        @sharded(mesh, in_specs=P("model"), out_specs=P())
        def global_norm(shard):
            return all_reduce_sum((shard ** 2).sum())
    """

    def deco(fn):
        return shard_map_compat(fn, mesh, in_specs, out_specs,
                                check=check_vma)

    return deco


def sharded_top_k(scores: jax.Array, k: int, mesh: Mesh,
                  axis: str = MODEL_AXIS) -> tuple:
    """Global top-k over a row-sharded score vector.

    Two-phase (the TPU shape of the reference's per-partition
    ``getTopN`` + driver merge): local ``lax.top_k`` per shard, then an
    all-gather of the k·n_shards candidates and a final top-k — the
    cross-device traffic is k·n_shards scalars instead of the full
    vector. Returns (global indices, values).
    """
    n_local = scores.shape[-1] // mesh.shape[axis]

    def local_then_merge(s):
        vals, idx = lax.top_k(s, min(k, s.shape[-1]))
        base = lax.axis_index(axis) * n_local
        idx = idx + base
        all_vals = lax.all_gather(vals, axis, tiled=True)
        all_idx = lax.all_gather(idx, axis, tiled=True)
        mvals, mpos = lax.top_k(all_vals, k)
        return mpos, mvals, all_idx

    fn = shard_map_compat(local_then_merge, mesh,
                          in_specs=P(axis), out_specs=(P(), P(), P()),
                          check=False)
    mpos, mvals, all_idx = fn(scores)
    return jnp.take(all_idx, mpos), mvals
