"""Parallelism layer: meshes, shardings, collective helpers."""

from .collectives import (
    all_gather,
    all_reduce_sum,
    reduce_scatter,
    ring_permute,
    sharded,
    sharded_top_k,
)
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    model_sharding,
    pad_to_multiple,
    replicated,
    single_device_mesh,
)
from .multihost import (
    from_process_local,
    global_mesh,
    host_shard,
    initialize_distributed,
)

__all__ = [
    "all_gather",
    "all_reduce_sum",
    "reduce_scatter",
    "ring_permute",
    "sharded",
    "sharded_top_k",
    "from_process_local",
    "global_mesh",
    "host_shard",
    "initialize_distributed",
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_sharding",
    "make_mesh",
    "model_sharding",
    "pad_to_multiple",
    "replicated",
    "single_device_mesh",
]
