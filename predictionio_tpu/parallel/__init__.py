"""Parallelism layer: meshes, shardings, collective helpers."""

from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    model_sharding,
    pad_to_multiple,
    replicated,
    single_device_mesh,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_sharding",
    "make_mesh",
    "model_sharding",
    "pad_to_multiple",
    "replicated",
    "single_device_mesh",
]
