"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's Spark substrate: where the
reference configured a SparkContext (`core/.../workflow/WorkflowContext.scala`)
and let Spark place RDD partitions, this framework lays out a
`jax.sharding.Mesh` over the available devices and annotates arrays with
`NamedSharding`s; XLA inserts the collectives (psum/all_gather/…) that ride
ICI within a slice and DCN across slices.

Axis convention used throughout the framework:
- ``data``  — batch/data parallelism (event shards, query micro-batches)
- ``model`` — model parallelism (factor-matrix rows, embedding shards)
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 2D ``(data, model)`` mesh over the devices.

    With no arguments, uses all devices on the data axis — the mesh-of-1
    case collapses to single-device jit, which is how the reference's
    L(local) controller variants map onto this framework (one API,
    mesh size 1..N).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs {data * model} devices, "
                         f"have {n}")
    dev = np.asarray(devices[: data * model]).reshape(data, model)
    return Mesh(dev, (DATA_AXIS, MODEL_AXIS))


def single_device_mesh() -> Mesh:
    return make_mesh(data=1, model=1, devices=jax.devices()[:1])


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard leading axis over the data axis, replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def model_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard leading axis over the model axis (factor/embedding rows)."""
    return NamedSharding(mesh, P(MODEL_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of ``k`` that is >= ``n`` (shard-even padding)."""
    return ((n + k - 1) // k) * k


@contextmanager
def maybe_mesh(mesh: Optional[Mesh]):
    """Enter the mesh context if given; no-op for the single-device path."""
    if mesh is None:
        yield
    else:
        with mesh:
            yield
