"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's Spark substrate: where the
reference configured a SparkContext (`core/.../workflow/WorkflowContext.scala`)
and let Spark place RDD partitions, this framework lays out a
`jax.sharding.Mesh` over the available devices and annotates arrays with
`NamedSharding`s; XLA inserts the collectives (psum/all_gather/…) that ride
ICI within a slice and DCN across slices.

Axis conventions used throughout the framework:
- ``data``  — batch/data parallelism (event shards) on the TRAINING mesh
- ``model`` — model parallelism (factor-matrix rows, embedding shards)
- ``batch`` — query-batch parallelism on the SERVING mesh (the
  ``(batch, model)`` GSPMD layout of SNIPPETS [3] / ALX): row-sharded
  factor tables spread over every axis, query micro-batches fan out
  along ``batch``

ALS row-shards factor tables over EVERY axis of whichever mesh it is
handed (:func:`rows_spec`), so the same training/serving code runs over
a ``(data, model)`` training mesh and a ``(batch, model)`` serving mesh
unchanged.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
BATCH_AXIS = "batch"

#: serving-mode names (ServerConfig.serving_mode / `ptpu deploy
#: --serving-mode`): "single" is today's one-device path, "replicated"
#: holds a full model copy per device and fans micro-batches out across
#: per-device lanes, "sharded" row-shards the factor tables over the
#: whole mesh (tables bigger than one HBM), "auto" picks by HBM sizing.
SERVING_MODES = ("auto", "single", "replicated", "sharded")

#: fraction of one device's HBM a model may occupy before "auto"
#: switches from replicated to sharded — factors are not the only
#: resident bytes (serving temps, pinned hot tier, XLA scratch), so a
#: full-copy-per-device plan needs real headroom
AUTO_SHARD_HBM_FRACTION = 0.6


def _build_mesh(shape: Tuple[int, int], names: Tuple[str, str],
                devices: Optional[Sequence[jax.Device]]) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    d0, d1 = shape
    if d0 is None:
        if n % d1 != 0:
            raise ValueError(f"{n} devices not divisible by "
                             f"{names[1]}={d1}")
        d0 = n // d1
    if d0 * d1 > n:
        raise ValueError(f"mesh {d0}x{d1} needs {d0 * d1} devices, "
                         f"have {n}")
    # ptpu: allow[host-sync-in-hot-path] — np.asarray over a host LIST
    # of Device handles (mesh topology), not a device array: no D2H
    dev = np.asarray(devices[: d0 * d1]).reshape(d0, d1)
    return Mesh(dev, names)


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 2D ``(data, model)`` TRAINING mesh over the devices.

    With no arguments, uses all devices on the data axis — the mesh-of-1
    case collapses to single-device jit, which is how the reference's
    L(local) controller variants map onto this framework (one API,
    mesh size 1..N).
    """
    return _build_mesh((data, model), (DATA_AXIS, MODEL_AXIS), devices)


def make_serving_mesh(batch: Optional[int] = None, model: int = 1,
                      devices: Optional[Sequence[jax.Device]] = None
                      ) -> Mesh:
    """Build the 2D ``(batch, model)`` SERVING mesh (SNIPPETS [3]).

    Default: every device on the batch axis. The row-sharded factor
    layout (:func:`rows_spec`) spreads rows over BOTH axes, so the
    split between them only matters to code that addresses one axis
    explicitly (e.g. batch fan-out with model-parallel ranking).
    """
    return _build_mesh((batch, model), (BATCH_AXIS, MODEL_AXIS), devices)


def rows_spec(mesh: Optional[Mesh]) -> P:
    """PartitionSpec sharding the leading (row) axis over EVERY axis of
    ``mesh`` — the ALX factor-table layout, mesh-shape agnostic: the
    same spec row-shards over a ``(data, model)`` training mesh and a
    ``(batch, model)`` serving mesh."""
    if mesh is None:
        return P()
    return P(tuple(mesh.axis_names))


def single_device_mesh() -> Mesh:
    return make_mesh(data=1, model=1, devices=jax.devices()[:1])


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard leading axis over the data axis, replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def model_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard leading axis over the model axis (factor/embedding rows)."""
    return NamedSharding(mesh, P(MODEL_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of ``k`` that is >= ``n`` (shard-even padding)."""
    return ((n + k - 1) // k) * k


def device_hbm_bytes(device: Optional[jax.Device] = None) -> Optional[int]:
    """One device's HBM capacity in bytes via ``memory_stats()``; None
    when the backend doesn't report it (CPU PJRT) — callers must treat
    None as "sizing unknown", not "infinite"."""
    try:
        if device is None:
            device = jax.devices()[0]
        stats = device.memory_stats()
        if not stats:
            return None
        limit = int(stats.get("bytes_limit", 0)
                    or stats.get("bytes_reservable_limit", 0))
        return limit or None
    except Exception:  # noqa: BLE001 — sizing is advisory
        return None


def resolve_serving_mode(mode: str, model_bytes: Optional[int],
                         n_devices: int,
                         hbm_limit: Optional[int] = None,
                         headroom: float = AUTO_SHARD_HBM_FRACTION) -> str:
    """Concrete serving mode for ``ServerConfig.serving_mode``.

    The HBM sizing math behind ``auto`` (docs/sharded-serving.md):
    a model whose resident factor bytes exceed ``headroom`` × one
    device's HBM cannot hold a full copy per device alongside serving
    temps → ``sharded``; otherwise N healthy devices each take a full
    copy for ~N× micro-batch throughput → ``replicated``; one device
    (or an unsized model on an unsized backend) stays ``single``/
    ``replicated`` conservatively.
    """
    if mode not in SERVING_MODES:
        raise ValueError(f"serving_mode must be one of {SERVING_MODES}, "
                         f"got {mode!r}")
    if mode != "auto":
        return mode
    if n_devices <= 1:
        return "single"
    if hbm_limit is None:
        hbm_limit = device_hbm_bytes()
    if model_bytes is not None and hbm_limit is not None \
            and model_bytes > headroom * hbm_limit:
        return "sharded"
    return "replicated"


@contextmanager
def maybe_mesh(mesh: Optional[Mesh]):
    """Enter the mesh context if given; no-op for the single-device path."""
    if mesh is None:
        yield
    else:
        with mesh:
            yield
