"""Multi-host (multi-slice) initialization and data feeding.

The reference scaled out by letting Spark place executors across a
cluster; the TPU-native equivalent is JAX multi-controller: every host
runs the same program, ``jax.distributed.initialize`` wires the hosts
into one system (ICI within a slice, DCN across slices), and each host
feeds its local shard of the global batch
(``jax.make_array_from_process_local_data``). SURVEY §2.3's
"host-side sharded scan → per-host feeding" lands here.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import numpy as np

from ..faults import declare, fire

log = logging.getLogger(__name__)

F_COLLECTIVE = declare(
    "multihost.collective",
    "entry of a host-side cross-process collective (allgather/"
    "broadcast/barrier); op= label names which")


def barrier(tag: str) -> None:
    """Rendezvous every process at ``tag`` (no-op single-process) —
    the commit fence of the distributed checkpointer: nothing after
    the barrier happens until everything before it (on every process)
    has."""
    import jax

    if jax.process_count() == 1:
        return
    fire(F_COLLECTIVE, op="barrier", tag=tag)
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Join the multi-host system (no-op when single-process).

    Arguments fall back to ``PIO_COORDINATOR`` / ``PIO_NUM_PROCESSES`` /
    ``PIO_PROCESS_ID`` env vars; on TPU pods the platform usually
    auto-detects everything, so bare ``initialize_distributed()`` is
    enough there.
    """
    import jax

    coordinator = coordinator_address or os.environ.get("PIO_COORDINATOR")
    n = num_processes if num_processes is not None else \
        int(os.environ.get("PIO_NUM_PROCESSES", "0")) or None
    pid = process_id if process_id is not None else \
        int(os.environ.get("PIO_PROCESS_ID", "-1"))
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        # CPU-host pods (and tests): cross-process collectives need the
        # gloo backend; must be configured before the backend exists,
        # and only a process that KNOWS it is joining a multi-host
        # system may decide this — platform.py cannot.
        try:
            # ptpu: allow[config-drift] — multi-host init owns this flag
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception as e:  # noqa: BLE001 — older/newer jax
            log.debug("gloo collectives config unavailable: %s", e)
    if coordinator is None and n is None:
        # single-process or TPU-pod auto-detect path
        try:
            jax.distributed.initialize()
        except Exception as e:  # noqa: BLE001 — single-host fallback
            log.debug("distributed auto-init unavailable (%s); "
                      "continuing single-process", e)
        return
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n,
                               process_id=pid if pid >= 0 else None)


def global_mesh(data: Optional[int] = None, model: int = 1):
    """A mesh over ALL processes' devices (``jax.devices()`` is global
    after ``initialize_distributed``)."""
    from .mesh import make_mesh

    return make_mesh(data=data, model=model)


def host_shard_bounds(size: int) -> tuple:
    """``(start, stop)`` of this process's contiguous slice of a
    host-global axis of the given size."""
    import jax

    n = jax.process_count()
    i = jax.process_index()
    per = (size + n - 1) // n
    start = min(i * per, size)
    return start, min(start + per, size)


def host_shard(array: np.ndarray, *, axis: int = 0) -> np.ndarray:
    """This process's contiguous slice of a host-global array — what the
    local event-store scan should yield before device feeding."""
    start, stop = host_shard_bounds(array.shape[axis])
    return np.take(array, np.arange(start, stop), axis=axis)


def from_process_local(local: np.ndarray, mesh, spec) -> "object":
    """Assemble a global sharded ``jax.Array`` from per-host shards
    (``jax.make_array_from_process_local_data``)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local)


# ---------------------------------------------------------------------------
# Host-side collectives for the sharded training read
# ---------------------------------------------------------------------------
#
# The storage layer hands each pod host 1/N of the log (``find_columnar
# (shard=(i, n))``); assembling per-factor-row histories from that needs
# a shuffle — the role Spark's exchange played in the reference. Here it
# rides the SAME collective fabric training uses (gloo on CPU hosts,
# ICI/DCN on pods), which is exactly where a TPU system wants bulk
# redistribution: storage bandwidth is the scarce resource, fabric
# bandwidth the abundant one. All helpers are SPMD-collective: every
# process must call them at the same point with same-shaped inputs.
# Payloads cross as raw bytes so int64 survives JAX's default-32-bit
# lowering.


def _allgather_parts(x: np.ndarray) -> list:
    """Collective: every process's same-shaped ``x``, in process order,
    dtype preserved exactly."""
    import jax

    x = np.ascontiguousarray(x)
    if jax.process_count() == 1:
        return [x]
    fire(F_COLLECTIVE, op="allgather")
    from jax.experimental import multihost_utils

    raw = np.frombuffer(x.tobytes(), dtype=np.uint8)
    g = np.asarray(multihost_utils.process_allgather(raw))
    return [np.frombuffer(g[p].tobytes(), dtype=x.dtype)
            .reshape(x.shape) for p in range(g.shape[0])]


def broadcast_str(s: str, max_len: int = 256) -> str:
    """Collective: process 0's string to everyone (the engine-instance
    id a single-writer workflow mints on process 0 and every process
    needs for manifest paths/logging)."""
    import jax

    if jax.process_count() == 1:
        return s
    fire(F_COLLECTIVE, op="broadcast")
    from jax.experimental import multihost_utils

    buf = np.zeros(max_len, np.uint8)
    b = s.encode("utf-8")[:max_len]
    buf[:len(b)] = np.frombuffer(b, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return bytes(out[out != 0]).decode("utf-8")


def allreduce_sum(x: np.ndarray) -> np.ndarray:
    """Collective element-wise sum across processes — the per-code
    count agreement that lets every host derive IDENTICAL factor-row
    indexation from its 1/N storage shard."""
    parts = _allgather_parts(np.ascontiguousarray(x))
    if len(parts) == 1:
        return parts[0]
    return np.sum(parts, axis=0, dtype=x.dtype)


def exchange_filtered(arrays: Sequence[np.ndarray], keep,
                      chunk: int = 4_000_000) -> list:
    """Collective shuffle with bounded memory: every process
    contributes parallel 1-D ``arrays`` (its local rows, any length —
    lengths may differ across processes); every process receives the
    union of every process's rows where ``keep(*column_chunks)`` → bool
    mask. Rounds are fixed-size (``chunk`` rows, padded), so peak
    transient memory is ``n_proc × chunk`` rows + the kept output,
    never the global log.

    ORDER IS NOT GUARANTEED: output is round-interleaved
    (``[p0 chunk0, p1 chunk0, ..., p0 chunk1, ...]``), so any caller
    that needs a deterministic order must carry a position column
    through the shuffle and sort on it afterwards (as
    ``ShardedColumnarRatingsSource`` does — packing truncation is
    order-sensitive).

    Returns the kept columns as a list of concatenated arrays (same
    order/dtypes as ``arrays``)."""
    import jax

    arrays = [np.ascontiguousarray(a) for a in arrays]
    n_local = len(arrays[0])
    assert all(len(a) == n_local for a in arrays), "parallel arrays"
    if jax.process_count() == 1:
        m = keep(*arrays)
        return [a[m] for a in arrays]
    lens = _allgather_parts(np.asarray([n_local], dtype=np.int64))
    rounds = int(max(int(p[0]) for p in lens) + chunk - 1) // chunk
    outs: list = [[] for _ in arrays]
    for r in range(rounds):
        lo = r * chunk
        padded = []
        for a in arrays:
            part = a[lo:lo + chunk]
            if len(part) < chunk:
                pad = np.zeros(chunk - len(part), dtype=a.dtype)
                part = np.concatenate([part, pad])
            padded.append(part)
        gathered = [_allgather_parts(p) for p in padded]
        for p in range(len(lens)):
            valid = min(max(int(lens[p][0]) - lo, 0), chunk)
            if valid == 0:
                continue
            cols = [g[p][:valid] for g in gathered]
            m = keep(*cols)
            for o, c in zip(outs, cols):
                o.append(c[m])
    return [np.concatenate(o) if o else
            np.empty(0, dtype=a.dtype)
            for o, a in zip(outs, arrays)]
