"""Multi-host (multi-slice) initialization and data feeding.

The reference scaled out by letting Spark place executors across a
cluster; the TPU-native equivalent is JAX multi-controller: every host
runs the same program, ``jax.distributed.initialize`` wires the hosts
into one system (ICI within a slice, DCN across slices), and each host
feeds its local shard of the global batch
(``jax.make_array_from_process_local_data``). SURVEY §2.3's
"host-side sharded scan → per-host feeding" lands here.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Join the multi-host system (no-op when single-process).

    Arguments fall back to ``PIO_COORDINATOR`` / ``PIO_NUM_PROCESSES`` /
    ``PIO_PROCESS_ID`` env vars; on TPU pods the platform usually
    auto-detects everything, so bare ``initialize_distributed()`` is
    enough there.
    """
    import jax

    coordinator = coordinator_address or os.environ.get("PIO_COORDINATOR")
    n = num_processes if num_processes is not None else \
        int(os.environ.get("PIO_NUM_PROCESSES", "0")) or None
    pid = process_id if process_id is not None else \
        int(os.environ.get("PIO_PROCESS_ID", "-1"))
    if coordinator is None and n is None:
        # single-process or TPU-pod auto-detect path
        try:
            jax.distributed.initialize()
        except Exception as e:  # noqa: BLE001 — single-host fallback
            log.debug("distributed auto-init unavailable (%s); "
                      "continuing single-process", e)
        return
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n,
                               process_id=pid if pid >= 0 else None)


def global_mesh(data: Optional[int] = None, model: int = 1):
    """A mesh over ALL processes' devices (``jax.devices()`` is global
    after ``initialize_distributed``)."""
    from .mesh import make_mesh

    return make_mesh(data=data, model=model)


def host_shard_bounds(size: int) -> tuple:
    """``(start, stop)`` of this process's contiguous slice of a
    host-global axis of the given size."""
    import jax

    n = jax.process_count()
    i = jax.process_index()
    per = (size + n - 1) // n
    start = min(i * per, size)
    return start, min(start + per, size)


def host_shard(array: np.ndarray, *, axis: int = 0) -> np.ndarray:
    """This process's contiguous slice of a host-global array — what the
    local event-store scan should yield before device feeding."""
    start, stop = host_shard_bounds(array.shape[axis])
    return np.take(array, np.arange(start, stop), axis=axis)


def from_process_local(local: np.ndarray, mesh, spec) -> "object":
    """Assemble a global sharded ``jax.Array`` from per-host shards
    (``jax.make_array_from_process_local_data``)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local)
