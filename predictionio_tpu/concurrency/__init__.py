"""Runtime concurrency-correctness layer (the ``ptpu check`` complement).

Static analysis (:mod:`..analysis`'s concurrency rule family) proves
lock discipline at review time; this package verifies it live:

- :func:`new_lock` / :func:`new_rlock` — the serving stack's only lock
  constructors. Plain stdlib locks when instrumentation is off (zero
  overhead); :class:`DebugLock` when on.
- :class:`DebugLock` / :class:`LockRegistry` — acquisition-order graph,
  live lock-order-inversion and same-thread-re-entry detection,
  wait/hold/contention telemetry.
- :func:`register_lock_metrics` — the ``pio_lock_*`` series (see
  docs/observability.md).
- :func:`dump_all_stacks` — the deadlock watchdog's all-thread stack
  dump into the access log.

Enable with ``ServerConfig(debug_locks=True)``, ``ptpu deploy
--debug-locks``, or ``PTPU_DEBUG_LOCKS=1`` (see docs/operations.md for
the staging runbook).
"""

from .locks import (
    DebugLock,
    LockRegistry,
    instrument_locks,
    lock_registry,
    locks_instrumented,
    new_lock,
    new_rlock,
    register_lock_metrics,
    watchdog_threshold_sec,
)
from .watchdog import dump_all_stacks, format_all_stacks

__all__ = [
    "DebugLock",
    "LockRegistry",
    "dump_all_stacks",
    "format_all_stacks",
    "instrument_locks",
    "lock_registry",
    "locks_instrumented",
    "new_lock",
    "new_rlock",
    "register_lock_metrics",
    "watchdog_threshold_sec",
]
