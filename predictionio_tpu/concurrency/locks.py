"""Instrumented locks: the runtime half of the concurrency rules.

``ptpu check``'s concurrency rule family (``unguarded-shared-state``,
``lock-order-inversion``, ``blocking-under-lock``,
``callback-under-lock``) proves lock discipline *statically*; this
module verifies the same discipline *live*. Every lock in the serving
stack (``server/``, ``cache/``, ``rollout/``) is created through
:func:`new_lock` / :func:`new_rlock`:

- **Disabled** (the default): the factory returns a plain
  ``threading.Lock`` / ``threading.RLock`` — literally the stdlib
  object, so the hot path carries zero instrumentation overhead (a
  test asserts the type).
- **Enabled** (``ServerConfig.debug_locks`` or ``PTPU_DEBUG_LOCKS=1``):
  the factory returns a :class:`DebugLock` that feeds one process-wide
  :class:`LockRegistry`:

  * the **acquisition-order graph** — acquiring B while holding A adds
    edge A→B; if the graph already proves B→…→A, that is a lock-order
    inversion (two threads interleaving those paths deadlock) and it
    is recorded with both stacks' worth of context;
  * **same-thread re-entry** on a non-reentrant lock raises
    immediately — the undebugged behavior is a silent permanent hang;
  * **hold-time and wait-time histograms** plus contention counters,
    exported as ``pio_lock_*`` metrics via
    :func:`register_lock_metrics`;
  * a **deadlock watchdog**: any single lock wait exceeding
    ``PTPU_LOCK_WATCHDOG_SEC`` (default 5s) dumps every thread's stack
    to the access log (``predictionio_tpu.access``) — the post-mortem
    you want when a deadlock does slip through.

The stress suites (cache + rollout) run once in CI with
``PTPU_DEBUG_LOCKS=1``; any inversion recorded during them fails the
build (see ``tests/conftest.py``), so an ordering regression dies in
CI, not in production.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

log = logging.getLogger(__name__)

__all__ = [
    "DebugLock",
    "LockRegistry",
    "instrument_locks",
    "lock_registry",
    "locks_instrumented",
    "new_lock",
    "new_rlock",
    "register_lock_metrics",
    "watchdog_threshold_sec",
]


def _env_enabled() -> bool:
    return os.environ.get("PTPU_DEBUG_LOCKS", "").strip().lower() in (
        "1", "true", "yes", "on")


_enabled = _env_enabled()


def instrument_locks(on: bool = True) -> None:
    """Globally switch the lock factories to (or from) debug mode.
    Only locks created AFTER the switch are instrumented — flip it
    before building the server (``ServerConfig.debug_locks`` does)."""
    global _enabled
    _enabled = bool(on)


def locks_instrumented() -> bool:
    return _enabled


def watchdog_threshold_sec() -> float:
    """Seconds a single lock wait may last before the watchdog dumps
    all thread stacks to the access log."""
    try:
        return max(float(os.environ.get("PTPU_LOCK_WATCHDOG_SEC", 5.0)),
                   0.05)
    except ValueError:
        return 5.0


# ---------------------------------------------------------------------------
# the process-wide registry
# ---------------------------------------------------------------------------

class LockRegistry:
    """Acquisition-order graph + contention/hold telemetry.

    One per process (:func:`lock_registry`); every :class:`DebugLock`
    reports here. Its own mutex is a plain ``threading.Lock`` held only
    for dict updates — it is deliberately NOT a DebugLock (the
    instrument must not observe itself).
    """

    def __init__(self) -> None:
        self._mutex = threading.Lock()
        #: held-lock name → names acquired while holding it
        self._edges: Dict[str, Set[str]] = {}
        #: (held, acquired) → first-seen "path:line" site
        self._edge_sites: Dict[Tuple[str, str], str] = {}
        self._inversions: List[dict] = []
        self._reported_pairs: Set[Tuple[str, str]] = set()
        self._reentries: List[dict] = []
        self._acquisitions = 0
        self._contended = 0
        self._watchdog_dumps = 0
        self._wait_hist: Dict[str, Any] = {}
        self._hold_hist: Dict[str, Any] = {}
        self._contention_by_lock: Dict[str, int] = {}
        #: thread id → stack of lock names it currently holds
        self._held: Dict[int, List[str]] = {}

    # -- histograms (lazy: obs import stays off the disabled path) ----------
    def _hist(self, table: Dict[str, Any], name: str) -> Any:
        h = table.get(name)
        if h is None:
            from ..obs.histogram import (
                DEFAULT_LATENCY_BOUNDS,
                StreamingHistogram,
            )
            h = table[name] = StreamingHistogram(DEFAULT_LATENCY_BOUNDS)
        return h

    # -- graph ---------------------------------------------------------------
    def _path_exists(self, src: str, dst: str) -> bool:
        """Is there a directed path src → … → dst in the order graph?"""
        seen = {src}
        frontier = [src]
        while frontier:
            nxt = frontier.pop()
            for n in self._edges.get(nxt, ()):
                if n == dst:
                    return True
                if n not in seen:
                    seen.add(n)
                    frontier.append(n)
        return False

    def note_acquire_attempt(self, name: str, held: List[str],
                             site: str) -> None:
        """Record order edges held→name; detect inversions BEFORE the
        caller blocks (a live deadlock would otherwise hide the
        report)."""
        with self._mutex:
            self._acquisitions += 1
            for h in held:
                if h == name:
                    continue
                self._edges.setdefault(h, set()).add(name)
                self._edge_sites.setdefault((h, name), site)
                # one report per cyclic pair, whichever direction
                # trips it first ({A,B} is one deadlock, not two)
                pair = (name, h) if name < h else (h, name)
                # inversion: the graph already proves name → … → h,
                # and this thread now wants name while holding h
                if pair not in self._reported_pairs \
                        and self._path_exists(name, h):
                    self._reported_pairs.add(pair)
                    inv = {
                        "held": h,
                        "acquiring": name,
                        "site": site,
                        "prior_site": self._edge_sites.get(
                            (name, h), "?"),
                        "thread": threading.current_thread().name,
                    }
                    self._inversions.append(inv)
                    log.error(
                        "lock-order inversion: thread %r acquiring %r "
                        "while holding %r at %s, but %r → %r was "
                        "established at %s",
                        inv["thread"], name, h, site, name, h,
                        inv["prior_site"])

    def note_acquired(self, name: str, waited_sec: float,
                      contended: bool) -> None:
        tid = threading.get_ident()
        with self._mutex:
            self._held.setdefault(tid, []).append(name)
            self._hist(self._wait_hist, name).observe(waited_sec)
            if contended:
                self._contended += 1
                self._contention_by_lock[name] = \
                    self._contention_by_lock.get(name, 0) + 1

    def note_released(self, name: str, held_sec: float) -> None:
        tid = threading.get_ident()
        with self._mutex:
            stack = self._held.get(tid, [])
            if name in stack:
                stack.reverse()
                stack.remove(name)  # innermost occurrence
                stack.reverse()
            if not stack:
                self._held.pop(tid, None)
            self._hist(self._hold_hist, name).observe(held_sec)

    def held_by_current_thread(self) -> List[str]:
        with self._mutex:
            return list(self._held.get(threading.get_ident(), ()))

    def note_reentry(self, name: str, site: str) -> None:
        with self._mutex:
            entry = {"lock": name, "site": site,
                     "thread": threading.current_thread().name}
            self._reentries.append(entry)

    def note_watchdog_dump(self) -> None:
        with self._mutex:
            self._watchdog_dumps += 1

    # -- reporting -----------------------------------------------------------
    @property
    def inversions(self) -> List[dict]:
        with self._mutex:
            return list(self._inversions)

    @property
    def reentries(self) -> List[dict]:
        with self._mutex:
            return list(self._reentries)

    def report(self) -> dict:
        with self._mutex:
            return {
                "acquisitions": self._acquisitions,
                "contended": self._contended,
                "watchdogDumps": self._watchdog_dumps,
                "inversions": list(self._inversions),
                "reentries": list(self._reentries),
                "edges": {k: sorted(v)
                          for k, v in sorted(self._edges.items())},
                "contentionByLock": dict(self._contention_by_lock),
            }

    def reset(self) -> None:
        """Drop all recorded state (tests)."""
        with self._mutex:
            self._edges.clear()
            self._edge_sites.clear()
            self._inversions.clear()
            self._reported_pairs.clear()
            self._reentries.clear()
            self._acquisitions = 0
            self._contended = 0
            self._watchdog_dumps = 0
            self._wait_hist.clear()
            self._hold_hist.clear()
            self._contention_by_lock.clear()
            self._held.clear()

    def _histogram_children(self) -> List[Tuple[str, str, Any]]:
        with self._mutex:
            out = [("pio_lock_wait_seconds", n, h)
                   for n, h in sorted(self._wait_hist.items())]
            out += [("pio_lock_hold_seconds", n, h)
                    for n, h in sorted(self._hold_hist.items())]
            return out


_registry: Optional[LockRegistry] = None
_registry_mutex = threading.Lock()


def lock_registry() -> LockRegistry:
    global _registry
    with _registry_mutex:
        if _registry is None:
            _registry = LockRegistry()
        return _registry


# ---------------------------------------------------------------------------
# the instrumented lock
# ---------------------------------------------------------------------------

def _caller_site(depth: int = 2) -> str:
    """``path:line`` of the frame acquiring the lock (skipping this
    module's own frames)."""
    for frame, lineno in traceback.walk_stack(None):
        fn = frame.f_code.co_filename
        if not fn.endswith(("locks.py",)):
            return f"{fn}:{lineno}"
    return "?"


class DebugLock:
    """A named lock that reports ordering, contention, and hold time
    to the process :class:`LockRegistry`, and dumps all thread stacks
    when a wait exceeds the watchdog threshold.

    ``reentrant=False`` wraps ``threading.Lock`` and RAISES on
    same-thread re-acquisition (the plain lock would hang forever);
    ``reentrant=True`` wraps ``threading.RLock`` and permits it.
    """

    def __init__(self, name: str, reentrant: bool = False,
                 registry: Optional[LockRegistry] = None,
                 watchdog_sec: Optional[float] = None) -> None:
        self.name = name
        self.reentrant = reentrant
        self._inner = (threading.RLock() if reentrant
                       else threading.Lock())
        self._registry = registry if registry is not None \
            else lock_registry()
        self._watchdog = (watchdog_sec if watchdog_sec is not None
                          else watchdog_threshold_sec())
        self._local = threading.local()

    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        reg = self._registry
        site = _caller_site()
        depth = self._depth()
        if depth:
            if not self.reentrant:
                reg.note_reentry(self.name, site)
                raise RuntimeError(
                    f"same-thread re-entry on non-reentrant lock "
                    f"{self.name!r} at {site} — the uninstrumented "
                    f"process would deadlock here")
        else:
            reg.note_acquire_attempt(
                self.name, reg.held_by_current_thread(), site)
        t0 = time.monotonic()
        contended = not self._inner.acquire(blocking=False)
        if contended:
            if not blocking:
                return False
            acquired = False
            deadline = (t0 + timeout) if timeout and timeout > 0 \
                else None
            while not acquired:
                step = self._watchdog
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    step = min(step, remaining)
                acquired = self._inner.acquire(timeout=step)
                if not acquired and time.monotonic() - t0 \
                        >= self._watchdog:
                    self._dump_stacks(site, time.monotonic() - t0)
        waited = time.monotonic() - t0
        if depth:  # re-entrant inner acquire: no new edge, no new hold
            self._local.depth = depth + 1
            return True
        self._local.depth = 1
        self._local.acquired_at = time.monotonic()
        reg.note_acquired(self.name, waited, contended)
        return True

    def release(self) -> None:
        depth = self._depth()
        if depth > 1:
            self._local.depth = depth - 1
            self._inner.release()
            return
        held_sec = time.monotonic() - getattr(
            self._local, "acquired_at", time.monotonic())
        self._local.depth = 0
        self._inner.release()
        self._registry.note_released(self.name, held_sec)

    def locked(self) -> bool:
        inner = self._inner
        locked = getattr(inner, "locked", None)
        if locked is not None:
            return locked()
        return False  # RLock has no locked(); best effort

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "rlock" if self.reentrant else "lock"
        return f"<DebugLock {self.name!r} ({kind})>"

    def _dump_stacks(self, site: str, waited: float) -> None:
        """The deadlock watchdog: a wait this long is either a deadlock
        or a pathological hold — either way the operator wants every
        thread's stack NOW, in the access log where the serving
        timeline already lives."""
        from .watchdog import dump_all_stacks

        self._registry.note_watchdog_dump()
        dump_all_stacks(
            reason=(f"lock {self.name!r} wait exceeded "
                    f"{self._watchdog:.1f}s (waited {waited:.1f}s so "
                    f"far) at {site}; thread "
                    f"{threading.current_thread().name!r} holds "
                    f"{self._registry.held_by_current_thread()}"))


# ---------------------------------------------------------------------------
# factories — the only lock constructors the serving stack uses
# ---------------------------------------------------------------------------

def new_lock(name: str):
    """A mutex for the serving stack: plain ``threading.Lock`` when
    instrumentation is off (zero overhead), :class:`DebugLock` when
    on. ``name`` keys the order graph and the ``pio_lock_*`` series —
    use ``Class.attr`` so static findings and runtime reports line
    up."""
    if _enabled:
        return DebugLock(name, reentrant=False)
    return threading.Lock()


def new_rlock(name: str):
    """Re-entrant variant of :func:`new_lock`."""
    if _enabled:
        return DebugLock(name, reentrant=True)
    return threading.RLock()


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def register_lock_metrics(registry) -> None:
    """Mount the ``pio_lock_*`` series on a server's
    :class:`~predictionio_tpu.obs.MetricsRegistry`: wait/hold
    histograms per lock plus contention/inversion/re-entry/watchdog
    counters. Safe to call when instrumentation is off — the series
    just stay at zero."""
    reg = lock_registry()
    registry.gauge(
        "pio_lock_instrumented",
        "1 when DebugLock instrumentation is live "
        "(ServerConfig.debug_locks or PTPU_DEBUG_LOCKS=1)",
        fn=lambda: 1.0 if _enabled else 0.0)
    registry.gauge(
        "pio_lock_acquisitions",
        "Lock acquisitions observed by the debug-lock registry "
        "(monotonic)",
        fn=lambda: reg.report()["acquisitions"])
    registry.gauge(
        "pio_lock_contention_total",
        "Acquisitions that had to wait for another holder (monotonic)",
        fn=lambda: reg.report()["contended"])
    registry.gauge(
        "pio_lock_inversions_total",
        "Lock-order inversions detected live — any nonzero value is a "
        "latent deadlock",
        fn=lambda: len(reg.inversions))
    registry.gauge(
        "pio_lock_reentries_total",
        "Same-thread re-entries on non-reentrant locks detected "
        "(each raised instead of deadlocking)",
        fn=lambda: len(reg.reentries))
    registry.gauge(
        "pio_lock_watchdog_dumps_total",
        "Times the deadlock watchdog dumped all thread stacks "
        "(lock wait exceeded PTPU_LOCK_WATCHDOG_SEC)",
        fn=lambda: reg.report()["watchdogDumps"])

    def collect():
        from ..obs.registry import render_histogram_lines

        children = reg._histogram_children()
        if not children:
            return []
        lines: List[str] = []
        last_fam = None
        for fam, lock_name, hist in children:
            if fam != last_fam:
                help_txt = ("Seconds spent waiting to acquire each "
                            "instrumented lock"
                            if fam.endswith("wait_seconds") else
                            "Seconds each instrumented lock was held")
                lines.append(f"# HELP {fam} {help_txt}")
                lines.append(f"# TYPE {fam} histogram")
                last_fam = fam
            lines.extend(render_histogram_lines(
                fam, (("lock", lock_name),), hist))
        return lines

    registry.register_collector(collect)
