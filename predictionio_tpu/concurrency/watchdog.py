"""Deadlock watchdog: dump every thread's stack to the access log.

When an instrumented lock wait exceeds the watchdog threshold
(:func:`~.locks.watchdog_threshold_sec`), :class:`~.locks.DebugLock`
calls :func:`dump_all_stacks`. The dump goes to the
``predictionio_tpu.access`` logger — the structured serving timeline —
so the post-mortem sits next to the requests that hung, and a log
shipper already collecting access lines gets the stacks for free.
"""

from __future__ import annotations

import logging
import sys
import threading
import traceback
from typing import Optional

__all__ = ["dump_all_stacks"]

#: the engine/event servers' structured access log (server/http.py)
access_log = logging.getLogger("predictionio_tpu.access")


def format_all_stacks(reason: str = "") -> str:
    """Every live thread's stack as one block, deadlock-report style:
    thread name/ident/daemon flag, then the frames, innermost last."""
    by_ident = {t.ident: t for t in threading.enumerate()}
    parts = []
    if reason:
        parts.append(f"=== lock watchdog: {reason} ===")
    for ident, frame in sorted(sys._current_frames().items()):
        thread = by_ident.get(ident)
        name = thread.name if thread is not None else "?"
        daemon = thread.daemon if thread is not None else "?"
        parts.append(f"--- thread {name!r} (ident={ident}, "
                     f"daemon={daemon}) ---")
        parts.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(parts)


def dump_all_stacks(reason: str = "",
                    logger: Optional[logging.Logger] = None) -> str:
    """Format and log all thread stacks; returns the formatted block
    (tests assert on it). Never raises — a watchdog that crashes the
    waiter it is diagnosing would be worse than no watchdog."""
    try:
        block = format_all_stacks(reason)
        (logger or access_log).error("%s", block)
        return block
    except Exception as e:  # noqa: BLE001 — diagnostics must not kill
        logging.getLogger(__name__).error(
            "watchdog stack dump failed: %s", e)
        return ""
