"""Declarative service-level objectives (ISSUE 15, docs/slo.md).

An :class:`SLOSpec` states a promise in the operator's terms —
"99.9% of ``/queries.json`` requests succeed", "99% of queries finish
within 150 ms", "95% of fold-ins are servable within 5 s of ingest" —
and names the telemetry it is checked against. The spec is pure data:
the :mod:`.engine` turns it into multi-window burn rates against the
live :class:`~predictionio_tpu.obs.MetricsRegistry`, and the
:mod:`.gate` turns the capacity section of a spec file into a CI merge
gate over ``load_harness``'s ``CAPACITY.json``.

Every objective reduces to the same error-budget arithmetic: a
*target* fraction of good events, so the budget is ``1 - target`` and
the burn rate is ``(bad events / total events) / budget`` over a
window. What counts as "bad" is the only per-objective part:

- ``availability`` — a 5xx-status request (counted off a labeled
  request counter such as ``pio_http_requests_total``)
- ``latency`` — a request slower than ``threshold_ms`` (counted off a
  latency histogram's cumulative buckets, interpolated inside the
  bucket the threshold lands in)
- ``freshness`` — an event→servable sample slower than
  ``threshold_ms`` (same bucket math over
  ``pio_stream_freshness_seconds``)
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

OBJECTIVES = ("availability", "latency", "freshness")

#: default metric family per objective; a spec's ``scope`` labels can
#: re-route latency to the per-route or per-release-arm series
_DEFAULT_METRICS = {
    "availability": "pio_http_requests_total",
    "freshness": "pio_stream_freshness_seconds",
}


@dataclass
class SLOSpec:
    """One service objective: what is promised, over which telemetry,
    at which burn-alert windows.

    The window pair follows the multi-window burn-rate alerting
    pattern (Google SRE workbook): a breach requires the *fast* window
    burning at ``burn_fast``× budget AND the *slow* window at
    ``burn_slow``× — the fast window proves the problem is happening
    now, the slow window proves it is big enough to matter, and the
    pair together is robust to both blips and slow bleeds.
    """

    name: str
    objective: str
    #: fraction of events that must be good (0.999 → 0.1% error budget)
    target: float = 0.999
    #: latency/freshness: a sample above this is a budget-burning event
    threshold_ms: Optional[float] = None
    #: metric family to evaluate against; None resolves per objective
    metric: Optional[str] = None
    #: label filters — only children carrying ALL of these label values
    #: are aggregated (``{"route": "/queries.json"}`` scopes the spec
    #: to one route; ``{"arm": "candidate"}`` to one release arm)
    scope: Dict[str, str] = field(default_factory=dict)
    window_fast_sec: float = 300.0
    window_slow_sec: float = 3600.0
    #: burn-rate alert thresholds (× budget) per window
    burn_fast: float = 14.4
    burn_slow: float = 6.0
    #: the compliance period the error budget is accounted over
    budget_window_sec: float = 86_400.0
    description: str = ""

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("SLOSpec needs a name")
        if self.objective not in OBJECTIVES:
            raise ValueError(
                f"objective must be one of {OBJECTIVES}, got "
                f"{self.objective!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"target must be in (0, 1), got {self.target}")
        if self.objective in ("latency", "freshness"):
            if self.threshold_ms is None or self.threshold_ms <= 0:
                raise ValueError(
                    f"{self.objective} SLO {self.name!r} needs a "
                    f"positive threshold_ms")
        if self.window_fast_sec <= 0 or self.window_slow_sec <= 0:
            raise ValueError("windows must be positive")
        if self.window_fast_sec > self.window_slow_sec:
            raise ValueError(
                f"window_fast_sec ({self.window_fast_sec}) must not "
                f"exceed window_slow_sec ({self.window_slow_sec})")
        if self.budget_window_sec < self.window_slow_sec:
            raise ValueError(
                "budget_window_sec must cover the slow window")
        self.scope = {str(k): str(v) for k, v in self.scope.items()}

    @property
    def budget(self) -> float:
        """Error budget: the allowed bad-event fraction."""
        return 1.0 - self.target

    def resolved_metric(self) -> str:
        """The metric family this spec reads (explicit ``metric`` wins;
        otherwise by objective, with latency picking the per-arm or
        per-route series when the scope names one)."""
        if self.metric:
            return self.metric
        if self.objective == "latency":
            if "arm" in self.scope:
                return "pio_release_latency_seconds"
            if "route" in self.scope:
                return "pio_http_request_duration_seconds"
            return "pio_query_latency_seconds"
        return _DEFAULT_METRICS[self.objective]

    def to_json(self) -> Dict[str, Any]:
        d = asdict(self)
        return {k: v for k, v in d.items()
                if v not in (None, "", {}) or k in ("name", "objective")}

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "SLOSpec":
        unknown = set(d) - set(cls.__dataclass_fields__)
        if unknown:
            raise ValueError(
                f"unknown SLOSpec field(s) {sorted(unknown)} in "
                f"{d.get('name', '?')!r}")
        return cls(**d)


def default_specs(streaming: bool = False) -> List[SLOSpec]:
    """The out-of-the-box objectives a deployed engine server watches
    when no spec file is given: request availability and end-to-end
    query latency on ``/queries.json``, plus event→servable freshness
    while the streaming trainer is attached. Deliberately loose — they
    exist so every deployment has burn-rate telemetry from minute one;
    a real deployment commits its own file (docs/slo.md)."""
    specs = [
        SLOSpec(
            name="queries-availability",
            objective="availability",
            target=0.999,
            scope={"route": "/queries.json"},
            description="99.9% of /queries.json requests answer "
                        "without a 5xx"),
        SLOSpec(
            name="queries-p99-latency",
            objective="latency",
            target=0.99,
            threshold_ms=500.0,
            scope={"route": "/queries.json"},
            description="99% of /queries.json requests finish within "
                        "500 ms"),
    ]
    if streaming:
        specs.append(SLOSpec(
            name="stream-freshness",
            objective="freshness",
            target=0.95,
            threshold_ms=5_000.0,
            description="95% of fold-ins are servable within 5 s of "
                        "ingest"))
    return specs


def load_specs(path: str) -> Tuple[List[SLOSpec], Dict[str, Any]]:
    """Parse a committed spec file (``slo/specs/*.json``)::

        {"specs": [{"name": ..., "objective": ..., ...}, ...],
         "capacity": {"<config>": {"min_knee_qps": ...,
                                   "max_p99_at_80pct_knee_ms": ...,
                                   "max_freshness_under_load_ms": ...},
                      ...}}

    Returns ``(specs, capacity_gates)``. The ``capacity`` section is
    the committed side of the CI capacity gate
    (:func:`~predictionio_tpu.slo.gate.gate_capacity`)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    raw = doc.get("specs")
    if not isinstance(raw, list) or not raw:
        raise ValueError(f"{path}: no 'specs' list")
    specs = [SLOSpec.from_json(d) for d in raw]
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate spec names")
    gates = doc.get("capacity") or {}
    if not isinstance(gates, dict):
        raise ValueError(f"{path}: 'capacity' must be an object")
    return specs, gates
