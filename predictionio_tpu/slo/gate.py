"""The CI capacity gate: committed SLOs vs a fresh ``CAPACITY.json``.

``benchmarks/load_harness.py`` emits a machine-readable capacity model
per serving config — knee qps, p99 at 80% of the knee, freshness under
load, device-idle fraction. The committed side lives in the
``capacity`` section of a spec file (``slo/specs/ci.json``); this
module diffs the two with **ratchet semantics**: a regression fails
naming the spec, the measurement window, and the measured value; the
committed floors/ceilings only ever tighten, and only through an
explicit ``ptpu slo check --update`` commit (mirroring the ``ptpu
check`` baseline and ``audit-hlo`` ratchets).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Tuple

#: gate key → (capacity-model key, direction). ``min``: measured must
#: be >= committed (throughput floors); ``max``: measured must be <=
#: committed (latency/staleness ceilings).
GATE_KEYS = {
    "min_knee_qps": ("knee_qps", "min"),
    "max_p99_at_80pct_knee_ms": ("p99_at_80pct_knee_ms", "max"),
    "max_freshness_under_load_ms": ("freshness_under_load_ms", "max"),
    "max_device_idle_fraction": ("device_idle_fraction", "max"),
}

#: how much better a fresh measurement must be before --update
#: tightens the committed value toward it (the slack absorbs run-to-run
#: noise so the ratchet follows real wins, not lucky runs)
RATCHET_SLACK = 0.8


def _window_of(entry: Dict[str, Any], capacity: Dict[str, Any]) -> str:
    """The measurement window a gate failure names: per-rate step
    duration + the sweep shape, so "regressed" is attributable to a
    concrete measurement, not a vibe."""
    step = entry.get("step_sec") or capacity.get("step_sec")
    rates = entry.get("frontier") or []
    lo = rates[0].get("offered_qps") if rates else None
    hi = rates[-1].get("offered_qps") if rates else None
    parts = []
    if step is not None:
        parts.append(f"{step}s/rate open-loop sweep")
    if lo is not None and hi is not None:
        parts.append(f"{lo}-{hi} qps offered")
    return ", ".join(parts) or "load_harness sweep"


def gate_capacity(capacity: Dict[str, Any],
                  gates: Dict[str, Any]) -> List[str]:
    """Every committed gate checked against the fresh capacity model;
    returns human-readable failure lines (empty = gate passes)."""
    failures: List[str] = []
    configs = capacity.get("configs") or {}
    for cfg_name, gate in sorted(gates.items()):
        entry = configs.get(cfg_name)
        if entry is None:
            failures.append(
                f"capacity gate {cfg_name!r}: no measurement in "
                f"CAPACITY.json (configs measured: "
                f"{sorted(configs) or 'none'})")
            continue
        window = _window_of(entry, capacity)
        for gkey, committed in sorted(gate.items()):
            spec = GATE_KEYS.get(gkey)
            if spec is None:
                failures.append(
                    f"capacity gate {cfg_name!r}: unknown gate key "
                    f"{gkey!r} (known: {sorted(GATE_KEYS)})")
                continue
            mkey, direction = spec
            measured = entry.get(mkey)
            if measured is None:
                failures.append(
                    f"capacity gate {cfg_name!r}: {mkey} was not "
                    f"measured (window: {window}) but {gkey}="
                    f"{committed} is committed")
                continue
            ok = (measured >= committed if direction == "min"
                  else measured <= committed)
            if not ok:
                cmp = "<" if direction == "min" else ">"
                failures.append(
                    f"capacity gate {cfg_name!r}: {mkey} {measured} "
                    f"{cmp} committed {gkey} {committed} "
                    f"(window: {window})")
    return failures


def ratchet_gates(capacity: Dict[str, Any], gates: Dict[str, Any],
                  slack: float = RATCHET_SLACK
                  ) -> Tuple[Dict[str, Any], List[str]]:
    """Tighten the committed gates toward a fresh (passing) run:
    floors rise to ``slack × measured`` when that beats the committed
    floor, ceilings drop to ``measured / slack`` when that beats the
    committed ceiling. Never loosens — a regressed run leaves the
    committed value alone (and should have failed the gate anyway).
    Returns ``(new_gates, change lines)``."""
    configs = capacity.get("configs") or {}
    out: Dict[str, Any] = {}
    changes: List[str] = []
    for cfg_name, gate in gates.items():
        entry = configs.get(cfg_name) or {}
        new_gate = dict(gate)
        for gkey, committed in gate.items():
            spec = GATE_KEYS.get(gkey)
            if spec is None:
                continue
            mkey, direction = spec
            measured = entry.get(mkey)
            if measured is None:
                continue
            if direction == "min":
                candidate = round(measured * slack, 3)
                if candidate > committed:
                    new_gate[gkey] = candidate
            else:
                candidate = round(measured / slack, 3)
                if candidate < committed:
                    new_gate[gkey] = candidate
            if new_gate[gkey] != committed:
                changes.append(
                    f"{cfg_name}.{gkey}: {committed} -> "
                    f"{new_gate[gkey]} (measured {mkey}={measured})")
        out[cfg_name] = new_gate
    return out, changes


def write_gates(path: str, gates: Dict[str, Any]) -> None:
    """Rewrite only the ``capacity`` section of a committed spec file,
    preserving the specs untouched. Temp+fsync+rename: a crash
    mid-ratchet must leave the committed gates readable, not torn."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    doc["capacity"] = gates
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
