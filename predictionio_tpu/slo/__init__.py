"""Service-level objectives: burn-rate accounting + the capacity gate.

The observability layer's enforcement half (ISSUE 15, docs/slo.md):
:class:`SLOSpec` declares what the service promises,
:class:`SLOEngine` continuously accounts the promise against the live
``pio_*`` telemetry with multi-window error-budget burn rates, and the
:mod:`.gate` turns ``load_harness``'s measured capacity model into a
CI merge gate with ratchet semantics.
"""

from .engine import SLOEngine
from .gate import GATE_KEYS, gate_capacity, ratchet_gates, write_gates
from .spec import OBJECTIVES, SLOSpec, default_specs, load_specs

__all__ = [
    "GATE_KEYS",
    "OBJECTIVES",
    "SLOEngine",
    "SLOSpec",
    "default_specs",
    "gate_capacity",
    "load_specs",
    "ratchet_gates",
    "write_gates",
]
