"""The SLO engine: multi-window error-budget accounting over live metrics.

The engine ticks on a clock (a background thread in a deployed server,
a synthetic clock in tests), and on every tick takes one *cumulative*
sample per spec from the :class:`~predictionio_tpu.obs.MetricsRegistry`
— total events, bad events, and (for histogram-backed objectives) the
cumulative bucket vector. Windows are then pure snapshot arithmetic:
the delta between the newest sample and the newest sample at least
``window`` old IS the window's own histogram (the same
cumulative-bucket-delta read :func:`~predictionio_tpu.obs.histogram.
window_quantile` does for the rollout health gate), so burn rates
never require storing per-event data.

States per spec:

- ``insufficient_data`` — a window reaches back past the first sample
  (engine just started) or a sample regressed (histogram reset). NOT a
  breach: a cold window says nothing about the service (ISSUE 15
  satellite — the empty-delta case must read as "no data", never as
  "quantile 0 ms, all good" or "breach").
- ``idle`` — windows covered but no traffic in the slow window.
- ``ok`` / ``breach`` — the multi-window verdict: breach while the
  fast window burns ≥ ``burn_fast``× budget AND the slow window
  ≥ ``burn_slow``×. ``pio_slo_violations_total`` counts ok→breach
  transitions; the transition hook lets the server force-retain
  flight-recorder traces for the duration of the burn.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..concurrency import new_lock
from ..obs.histogram import window_quantile
from .spec import SLOSpec

log = logging.getLogger(__name__)

Buckets = List[Tuple[float, int]]

#: hard cap on samples retained per spec: at the default 1 s tick this
#: covers a >2 h budget window at full resolution; a longer budget
#: window coarsens to the oldest retained sample (documented in
#: docs/slo.md) instead of growing memory forever
RING_CAP = 8192


class _Sample:
    """One cumulative observation: monotonic time, total events, bad
    events, and the summed cumulative buckets (histogram specs)."""

    __slots__ = ("t", "total", "bad", "buckets")

    def __init__(self, t: float, total: float, bad: float,
                 buckets: Optional[Buckets]):
        self.t = t
        self.total = total
        self.bad = bad
        self.buckets = buckets


def _bad_above(buckets: Buckets, threshold_s: float) -> float:
    """Events strictly above ``threshold_s`` in a cumulative bucket
    vector, interpolating inside the bucket the threshold lands in
    (the same estimator the quantile read uses, run in reverse)."""
    total = buckets[-1][1]
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        if threshold_s <= le:
            if math.isinf(le):
                return float(total - cum)  # threshold past the last
                # finite bound: only overflow-bucket events are bad,
                # and they are all in cum already → none measurable
            n = cum - prev_cum
            lo = prev_le
            frac = (threshold_s - lo) / (le - lo) if le > lo else 1.0
            good = prev_cum + n * min(max(frac, 0.0), 1.0)
            return float(total - good)
        prev_le, prev_cum = le, cum
    return 0.0


class _SpecState:
    """One spec's ring of samples plus its live verdict."""

    __slots__ = ("spec", "ring", "state", "burn_fast", "burn_slow",
                 "budget_remaining", "current", "violations",
                 "breach_since", "last_t")

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self.ring: deque = deque(maxlen=RING_CAP)
        self.state = "insufficient_data"
        self.burn_fast: Optional[float] = None
        self.burn_slow: Optional[float] = None
        self.budget_remaining: Optional[float] = None
        self.current: Dict[str, Any] = {}
        self.violations = 0
        self.breach_since: Optional[float] = None
        self.last_t: Optional[float] = None


class SLOEngine:
    """Evaluates :class:`SLOSpec`s against a live metrics registry.

    Thread-safe; drive it with :meth:`observe` (one tick, synthetic
    clocks welcome) or :meth:`start`/:meth:`stop` (a daemon ticker).
    ``on_transition(spec, breached, info)`` fires OUTSIDE the engine
    lock on every ok↔breach edge.
    """

    def __init__(self, registry, specs: List[SLOSpec],
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[
                     Callable[[SLOSpec, bool, Dict[str, Any]],
                              None]] = None):
        if not specs:
            raise ValueError("SLOEngine needs at least one spec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError("duplicate SLO spec names")
        self.registry = registry
        self.clock = clock
        self.on_transition = on_transition
        self._states = {s.name: _SpecState(s) for s in specs}
        self._lock = new_lock("SLOEngine._lock")
        self._ticks = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._viol_counter = None  # bound by register_metrics

    # -- sampling ----------------------------------------------------------
    def _matches(self, items: Tuple[Tuple[str, str], ...],
                 scope: Dict[str, str]) -> bool:
        d = dict(items)
        return all(d.get(k) == v for k, v in scope.items())

    def _sample(self, spec: SLOSpec) -> Optional[Tuple[float, float,
                                                       Optional[Buckets]]]:
        """One cumulative (total, bad, buckets) read for ``spec``;
        None while the metric family does not exist yet."""
        fam = self.registry.get(spec.resolved_metric())
        if fam is None:
            return None
        if fam.kind == "counter":
            # availability: status label >= 500 is the bad class
            # (includes deadline-shed 503s — an unanswered query is
            # unavailable no matter how gracefully it was shed)
            total = bad = 0.0
            for items, child in fam.children():
                if not self._matches(items, spec.scope):
                    continue
                v = float(child.value)
                total += v
                try:
                    code = int(dict(items).get("status", "0"))
                except ValueError:
                    code = 0
                if code >= 500:
                    bad += v
            return total, bad, None
        if fam.kind == "histogram":
            agg: Optional[Buckets] = None
            for items, child in fam.children():
                if not self._matches(items, spec.scope):
                    continue
                bc = child.bucket_counts()
                if agg is None:
                    agg = bc
                elif len(bc) == len(agg):
                    agg = [(le, c0 + c1) for (le, c0), (_, c1)
                           in zip(agg, bc)]
            if agg is None:
                return None
            total = float(agg[-1][1])
            thr = float(spec.threshold_ms or 0.0) / 1000.0
            return total, _bad_above(agg, thr), agg
        return None  # gauges carry no event counts to budget against

    # -- window arithmetic -------------------------------------------------
    @staticmethod
    def _window(st: _SpecState, now: float, window: float):
        """``(d_total, d_bad, anchor, covered)`` between the newest
        sample and the newest sample at least ``window`` old; None
        while fewer than two samples exist or a sample regressed
        (reset between snapshots — a wrapped window is no window)."""
        ring = st.ring
        if len(ring) < 2:
            return None
        latest = ring[-1]
        cutoff = now - window
        anchor = None
        for s in reversed(ring):
            if s.t <= cutoff:
                anchor = s
                break
        covered = anchor is not None
        if anchor is None:
            anchor = ring[0]
        d_total = latest.total - anchor.total
        d_bad = latest.bad - anchor.bad
        if d_total < 0 or d_bad < 0:
            return None
        return d_total, d_bad, anchor, covered

    # -- evaluation --------------------------------------------------------
    def observe(self, now: Optional[float] = None) -> None:
        """One tick: sample every spec, re-evaluate, fire transitions
        (outside the lock)."""
        t = self.clock() if now is None else float(now)
        transitions: List[Tuple[SLOSpec, bool, Dict[str, Any]]] = []
        with self._lock:
            self._ticks += 1
            for st in self._states.values():
                sampled = self._sample(st.spec)
                if sampled is not None:
                    total, bad, buckets = sampled
                    st.ring.append(_Sample(t, total, bad, buckets))
                    st.last_t = t
                edge = self._evaluate(st, t)
                if edge is not None:
                    transitions.append(edge)
        for spec, breached, info in transitions:
            if breached:
                log.warning(
                    "SLO BREACH %s: fast burn %.1fx over %gs, slow "
                    "burn %.1fx over %gs (budget %.4f)", spec.name,
                    info.get("burnFast") or 0.0, spec.window_fast_sec,
                    info.get("burnSlow") or 0.0, spec.window_slow_sec,
                    spec.budget)
            else:
                log.warning("SLO recovered: %s", spec.name)
            if self.on_transition is not None:
                try:
                    self.on_transition(spec, breached, info)
                except Exception:  # noqa: BLE001 — a broken hook must
                    log.exception(  # never stop the evaluator
                        "SLO transition hook failed for %s", spec.name)

    def _evaluate(self, st: _SpecState, now: float):
        """Re-derive one spec's verdict; returns a transition tuple on
        an ok↔breach edge, else None. Caller holds the lock."""
        spec = st.spec
        was_breaching = st.state == "breach"
        fast = self._window(st, now, spec.window_fast_sec)
        slow = self._window(st, now, spec.window_slow_sec)
        st.burn_fast = st.burn_slow = None
        st.current = {}
        if fast is None or slow is None:
            st.state = "insufficient_data"
            return self._edge(st, was_breaching, False)
        f_total, f_bad, f_anchor, f_cov = fast
        s_total, s_bad, s_anchor, s_cov = slow
        if f_total > 0:
            st.burn_fast = (f_bad / f_total) / spec.budget
        if s_total > 0:
            st.burn_slow = (s_bad / s_total) / spec.budget
        # budget accounting over the compliance window (event-based:
        # consumed = bad / (budget × total)); an uncovered budget
        # window accounts since engine start — the honest best effort
        budget_win = self._window(st, now, spec.budget_window_sec)
        st.budget_remaining = None
        if budget_win is not None and budget_win[0] > 0:
            consumed = (budget_win[1] / budget_win[0]) / spec.budget
            st.budget_remaining = max(0.0, 1.0 - consumed)
        # the human-facing "current" read per objective
        latest = st.ring[-1]
        if spec.objective == "availability":
            if f_total > 0:
                st.current["errorRatio"] = round(f_bad / f_total, 6)
        elif latest.buckets is not None and f_anchor.buckets is not None:
            q = window_quantile(f_anchor.buckets, latest.buckets, 0.99)
            if q is not None:
                st.current["p99Ms"] = round(q * 1000.0, 3)
            st.current["badFraction"] = (round(f_bad / f_total, 6)
                                         if f_total > 0 else None)
        if not (f_cov and s_cov):
            # the lookback predates the first sample: whatever burn we
            # can compute describes a shorter window than promised —
            # report it, but never breach off it
            st.state = "insufficient_data"
            return self._edge(st, was_breaching, False)
        if s_total <= 0:
            st.state = "idle"
            return self._edge(st, was_breaching, False)
        breaching = (st.burn_fast is not None
                     and st.burn_slow is not None
                     and st.burn_fast >= spec.burn_fast
                     and st.burn_slow >= spec.burn_slow)
        st.state = "breach" if breaching else "ok"
        return self._edge(st, was_breaching, breaching, now)

    def _edge(self, st: _SpecState, was: bool, is_now: bool,
              now: Optional[float] = None):
        if is_now and not was:
            st.violations += 1
            st.breach_since = now
            if self._viol_counter is not None:
                self._viol_counter.labels(slo=st.spec.name).inc()
            return st.spec, True, self._info(st)
        if was and not is_now:
            st.breach_since = None
            return st.spec, False, self._info(st)
        return None

    def _info(self, st: _SpecState) -> Dict[str, Any]:
        return {
            "name": st.spec.name,
            "objective": st.spec.objective,
            "state": st.state,
            "burnFast": st.burn_fast,
            "burnSlow": st.burn_slow,
            "budgetRemaining": st.budget_remaining,
            "violations": st.violations,
            "windows": {"fastSec": st.spec.window_fast_sec,
                        "slowSec": st.spec.window_slow_sec,
                        "budgetSec": st.spec.budget_window_sec},
            "target": st.spec.target,
            "thresholdMs": st.spec.threshold_ms,
            "scope": dict(st.spec.scope),
            "metric": st.spec.resolved_metric(),
            "current": dict(st.current),
        }

    # -- read side ---------------------------------------------------------
    def burning(self) -> List[str]:
        with self._lock:
            return [n for n, st in self._states.items()
                    if st.state == "breach"]

    def fast_burning(self) -> List[str]:
        """Specs whose FAST window alone is burning ≥ its threshold —
        the minutes-scale early warning the autoscaler keys scale-out
        on. Deliberately looser than :meth:`burning` (which also
        requires the slow window): capacity added only after the slow
        window confirms the breach is capacity added too late."""
        with self._lock:
            return [n for n, st in self._states.items()
                    if st.burn_fast is not None
                    and st.burn_fast >= st.spec.burn_fast]

    def status(self) -> Dict[str, Any]:
        """The ``/slo.json`` payload (and the ``slo`` block of
        ``/status.json``)."""
        with self._lock:
            specs = [self._info(st) for st in self._states.values()]
            burning = [s["name"] for s in specs
                       if s["state"] == "breach"]
            ticks = self._ticks
            running = self._thread is not None
        return {
            "enabled": True,
            "running": running,
            "ticks": ticks,
            "burning": burning,
            "specs": specs,
        }

    # -- metrics -----------------------------------------------------------
    def register_metrics(self, registry) -> None:
        """Mount the ``pio_slo_*`` series (docs/observability.md)."""
        budget_fam = registry.gauge(
            "pio_slo_budget_remaining",
            "Fraction of the error budget left over the spec's "
            "compliance window (1 = untouched, 0 = exhausted; -1 "
            "while there is no data to account against)")
        burn_fam = registry.gauge(
            "pio_slo_burn_rate",
            "Error-budget burn rate (1.0 = burning exactly the "
            "budget) per spec and window (fast | slow); 0 while "
            "unknown")
        breach_fam = registry.gauge(
            "pio_slo_breach",
            "1 while the spec's fast AND slow windows both burn past "
            "their alert thresholds")
        self._viol_counter = registry.counter(
            "pio_slo_violations_total",
            "ok->breach transitions per SLO spec (each one has "
            "force-retained flight-recorder traces riding along)")

        def _bind(name: str) -> None:
            def read(field: str, default: float = 0.0):
                with self._lock:
                    st = self._states.get(name)
                    if st is None:
                        return default
                    v = getattr(st, field)
                    return default if v is None else float(v)

            budget_fam.labels(slo=name).set_fn(
                lambda: read("budget_remaining", -1.0))
            burn_fam.labels(slo=name, window="fast").set_fn(
                lambda: read("burn_fast"))
            burn_fam.labels(slo=name, window="slow").set_fn(
                lambda: read("burn_slow"))
            breach_fam.labels(slo=name).set_fn(
                lambda: 1.0 if self._state_name(name) == "breach"
                else 0.0)
            # a zero sample per spec so the series exists (and the
            # label set is visible) before the first violation
            self._viol_counter.labels(slo=name).inc(0.0)

        for name in self._states:
            _bind(name)

    def _state_name(self, name: str) -> str:
        with self._lock:
            st = self._states.get(name)
            return st.state if st is not None else "unknown"

    # -- ticker ------------------------------------------------------------
    def start(self, interval_sec: float = 1.0) -> None:
        """Start the background evaluator (idempotent)."""
        if interval_sec <= 0:
            raise ValueError("interval_sec must be positive")
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            thread = threading.Thread(
                target=self._run, args=(float(interval_sec),),
                daemon=True, name="slo-engine")
            self._thread = thread
        thread.start()

    def _run(self, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                self.observe()
            except Exception:  # noqa: BLE001 — the evaluator must
                log.exception("SLO tick failed")  # outlive a bad tick

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is None:
            return
        self._stop.set()
        thread.join(timeout=timeout)
