"""AOT compile artifacts — capture at build time, load at deploy time.

The cold-start gap (NORTHSTAR_r05: ~29 s deploy warm) is almost entirely
XLA compilation of the serving entry points.  JAX's AOT API makes those
executables portable: ``fn.lower(...).compile()`` yields a loaded
executable whose bytes ``jax.experimental.serialize_executable``
round-trips, and the deserialized executable is called with the dynamic
arguments only (statics are baked in) and answers bitwise-identically.

This module is the seam between the jit serving paths and that artifact
mechanism.  Serving entry points route their launches through
:func:`dispatch`, which has three behaviours selected by process-global
state:

* **normal** (neither store active): call the jit function unchanged —
  zero overhead beyond one global read.
* **capture** (``capture_into`` — during ``ptpu build``): lower+compile
  the entry, serialize it into the capture store keyed by the entry
  signature, and answer from the freshly compiled executable.  The
  build-time warm ladder (``warm_serving``) drives exactly the shapes
  deploy will see, so the artifact dir covers the serving envelope.
* **serve** (``activate`` — during ``QueryServer._warm_serving``): look
  the signature up in the store; a hit answers from the deserialized
  executable (milliseconds), a miss falls through to the jit path and
  compiles — the stale-key / corrupt-artifact fallback.  Every failure
  mode degrades to "compile like before", never to an error.

Artifact stores are versioned directories::

    <root>/<key-digest>/manifest.json     # store key + entry table
    <root>/<key-digest>/<entry-key>.exec  # pickled {blob, in_tree, out_tree}

The store key (jax version, backend, device count, mesh shape, rank,
quant mode, top-k mode, max batch — see :func:`store_key`) must match
EXACTLY between build and deploy; any drift resolves the digest to a
different directory and deploy falls back to compiling (counted in
``stats()["stale"]``).  Entry files carry a sha256 in the manifest and a
corrupt or truncated file is skipped, never trusted.

Artifacts embed pickled PyTreeDefs: treat an artifact dir with the same
trust as the model store it sits beside (docs/cold-start.md).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

__all__ = [
    "ArtifactStore", "activate", "capture_into", "deactivate",
    "dispatch", "entry_key", "key_digest", "reset_stats", "stats",
    "store_key",
]

_FORMAT = 1
_MANIFEST = "manifest.json"
_EXT = ".exec"

_lock = threading.Lock()
_capture_store: Optional["ArtifactStore"] = None
_serve_store: Optional["ArtifactStore"] = None


def _zero_stats() -> Dict[str, Any]:
    return {
        "loaded_entries": 0,    # artifact files deserialized
        "loaded_calls": 0,      # dispatches answered from an artifact
        "compiled_calls": 0,    # dispatches that fell through while serving
        "captured_entries": 0,  # entries written by capture
        "capture_errors": 0,    # entries that would not serialize
        "corrupt_entries": 0,   # sha/unpickle failures (skipped)
        "stale": 0,             # store-open key mismatches
        "load_seconds": 0.0,    # cumulative deserialize time
    }


_stats = _zero_stats()


def stats() -> Dict[str, Any]:
    """Snapshot of the process-wide AOT counters (see `_zero_stats`)."""
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        _stats.update(_zero_stats())


def _bump(name: str, by: float = 1) -> None:
    with _lock:
        _stats[name] += by


# ---------------------------------------------------------------------------
# keys

def store_key(**fields: Any) -> Dict[str, Any]:
    """The store-level cache key: artifact format + toolchain identity +
    caller-supplied serving-shape fields (mesh shape, rank, quant mode,
    top-k mode, max batch...).  Build and deploy MUST derive the key from
    the same inputs — :func:`key_digest` of the key names the artifact
    subdirectory, so any mismatch is an automatic fallback-to-compile."""
    import jax

    key: Dict[str, Any] = {
        "format": _FORMAT,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
    }
    for name, value in fields.items():
        key[name] = list(value) if isinstance(value, tuple) else value
    return key


def key_digest(key: Dict[str, Any]) -> str:
    blob = json.dumps(key, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _leaf_sig(leaf: Any) -> Tuple:
    """Identity of one dynamic argument leaf: dtype + shape + placement.
    Placement matters — a serialized executable records its device
    assignment, so per-device replicated-lane entries must not collide."""
    if leaf is None:
        return ("none",)
    dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
    shape = tuple(getattr(leaf, "shape", ()))
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        placement: Any = "host"
    else:
        try:
            placement = tuple(sorted(d.id for d in sharding.device_set))
        except Exception:  # noqa: BLE001 — exotic shardings: opaque repr
            placement = repr(sharding)
    return (dtype, shape, placement)


def entry_key(name: str, dyn_args: Sequence[Any],
              statics: Optional[Dict[str, Any]] = None,
              key_extra: Iterable[Any] = ()) -> str:
    """Per-entry key: entry name + dynamic-arg signature (treedef, and
    per-leaf dtype/shape/placement) + static kwargs + caller extras
    (e.g. the sharded ranker's mesh/k/quant cache key)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tuple(dyn_args))
    sig = (name, str(treedef), tuple(_leaf_sig(l) for l in leaves),
           tuple(sorted((statics or {}).items())), tuple(key_extra))
    digest = hashlib.sha256(repr(sig).encode()).hexdigest()[:20]
    return f"{name}-{digest}"


# ---------------------------------------------------------------------------
# store

class ArtifactStore:
    """One versioned artifact directory (``<root>/<key-digest>``) holding
    serialized serving executables, plus the in-memory cache of loaded /
    freshly captured ones.  Thread-safe; all IO failures are contained
    (a bad entry is skipped and the caller compiles)."""

    def __init__(self, root: str, key: Dict[str, Any]):
        self.root = os.path.abspath(root)
        self.key = dict(key)
        self.path = os.path.join(self.root, key_digest(self.key))
        self.entries: Dict[str, Dict[str, Any]] = {}
        self._loaded: Dict[str, Any] = {}
        self._failed: set[str] = set()
        self._lock = threading.Lock()

    # -- build side ---------------------------------------------------

    def capture(self, ekey: str, fn: Any, dyn_args: Sequence[Any],
                statics: Optional[Dict[str, Any]] = None) -> Any:
        """Lower+compile ``fn`` for this signature, persist the
        serialized executable, and return the compiled (loaded)
        executable so the build-time warm ladder still executes it."""
        from jax.experimental import serialize_executable as se

        with self._lock:
            cached = self._loaded.get(ekey)
        if cached is not None:
            return cached
        compiled = fn.lower(*dyn_args, **(statics or {})).compile()
        blob, in_tree, out_tree = se.serialize(compiled)
        payload = pickle.dumps(
            {"blob": blob, "in_tree": in_tree, "out_tree": out_tree},
            protocol=pickle.HIGHEST_PROTOCOL)
        os.makedirs(self.path, exist_ok=True)
        fname = ekey + _EXT
        fpath = os.path.join(self.path, fname)
        tmp = fpath + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, fpath)
        with self._lock:
            self.entries[ekey] = {
                "file": fname,
                "sha256": hashlib.sha256(payload).hexdigest(),
                "bytes": len(payload),
            }
            self._loaded[ekey] = compiled
        _bump("captured_entries")
        return compiled

    def flush(self) -> str:
        """Atomically (re)write the manifest; returns the store path."""
        os.makedirs(self.path, exist_ok=True)
        with self._lock:
            doc = {"key": self.key, "entries": dict(self.entries)}
        tmp = os.path.join(self.path, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(self.path, _MANIFEST))
        return self.path

    # -- deploy side --------------------------------------------------

    @classmethod
    def open(cls, root: str, key: Dict[str, Any]
             ) -> Optional["ArtifactStore"]:
        """Open the store for ``key`` under ``root``.  Returns ``None``
        (and counts ``stale``) when the directory or manifest is missing
        or the manifest's key disagrees — the caller compiles."""
        store = cls(root, key)
        manifest = os.path.join(store.path, _MANIFEST)
        try:
            with open(manifest) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            _bump("stale")
            return None
        if doc.get("key") != store.key:
            _bump("stale")
            return None
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            _bump("stale")
            return None
        store.entries = entries
        return store

    def load(self, ekey: str) -> Optional[Any]:
        """Deserialize (once) and return the executable for ``ekey``, or
        ``None`` on miss / checksum mismatch / unpickle failure."""
        from jax.experimental import serialize_executable as se

        with self._lock:
            if ekey in self._loaded:
                return self._loaded[ekey]
            if ekey in self._failed:
                return None
            meta = self.entries.get(ekey)
        if meta is None:
            return None
        t0 = time.perf_counter()
        try:
            with open(os.path.join(self.path, meta["file"]), "rb") as f:
                payload = f.read()
            if hashlib.sha256(payload).hexdigest() != meta.get("sha256"):
                raise ValueError("artifact checksum mismatch")
            doc = pickle.loads(payload)
            executable = se.deserialize_and_load(
                doc["blob"], doc["in_tree"], doc["out_tree"])
        except Exception:  # noqa: BLE001 — any bad artifact ⇒ compile
            _bump("corrupt_entries")
            with self._lock:
                self._failed.add(ekey)
            return None
        with self._lock:
            self._loaded[ekey] = executable
        _bump("loaded_entries")
        _bump("load_seconds", time.perf_counter() - t0)
        return executable

    def __len__(self) -> int:
        return len(self.entries)


# ---------------------------------------------------------------------------
# process-global modes

@contextmanager
def capture_into(store: ArtifactStore):
    """Route every :func:`dispatch` in this process through AOT capture
    into ``store`` for the duration (the ``ptpu build`` driver)."""
    global _capture_store
    with _lock:
        prev, _capture_store = _capture_store, store
    try:
        yield store
    finally:
        with _lock:
            _capture_store = prev
        store.flush()


def activate(store: Optional[ArtifactStore]) -> None:
    """Serve dispatches from ``store`` (misses compile as before).
    Stays active for the server's lifetime so post-warm shape misses
    still probe the artifact table first."""
    global _serve_store
    with _lock:
        _serve_store = store


def deactivate() -> None:
    activate(None)


def serving_store() -> Optional[ArtifactStore]:
    return _serve_store


def dispatch(name: str, fn: Any, dyn_args: Sequence[Any],
             statics: Optional[Dict[str, Any]] = None,
             key_extra: Iterable[Any] = ()) -> Any:
    """Launch a serving entry point through the AOT seam.

    ``fn`` is the jit-wrapped callable; ``dyn_args`` its dynamic
    arguments (passed positionally), ``statics`` its static kwargs, and
    ``key_extra`` any additional identity the signature cannot see
    (e.g. the mesh/k tuple keying a compile-once product function).
    Normal mode is a tail call into ``fn`` — the seam costs one global
    read on the hot path."""
    serve = _serve_store
    capture = _capture_store
    if serve is None and capture is None:
        return fn(*dyn_args, **(statics or {}))
    ekey = entry_key(name, dyn_args, statics, key_extra)
    if serve is not None:
        executable = serve.load(ekey)
        if executable is not None:
            _bump("loaded_calls")
            return executable(*dyn_args)
        _bump("compiled_calls")
    if capture is not None:
        try:
            compiled = capture.capture(ekey, fn, dyn_args, statics)
        except Exception:  # noqa: BLE001 — unserializable ⇒ jit as usual
            _bump("capture_errors")
        else:
            return compiled(*dyn_args)
    return fn(*dyn_args, **(statics or {}))
