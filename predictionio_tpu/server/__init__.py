"""HTTP servers: event ingestion, engine serving, admin, dashboard."""

from .http import AppServer, HTTPApp, HTTPError, Request, Response  # noqa: F401
