"""Admin REST API.

Capability parity with ``tools/admin/AdminAPI.scala:62-121`` +
``tools/admin/CommandClient.scala``: ``GET /`` liveness,
``GET /cmd/app`` list, ``POST /cmd/app`` create (app + generated access
key + event-store init), ``DELETE /cmd/app/{name}`` full delete,
``DELETE /cmd/app/{name}/data`` event wipe. Responses carry the
reference's ``{status, message}`` GeneralResponse shape.
"""

from __future__ import annotations

from typing import Optional

from ..data.storage.base import AccessKey, App
from ..data.storage.registry import Storage, get_storage
from ..obs import MetricsRegistry
from .http import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    json_response,
    make_key_auth,
    mount_metrics,
)


def build_app(storage: Optional[Storage] = None,
              accesskey: Optional[str] = None) -> HTTPApp:
    app = HTTPApp("adminserver")

    # telemetry (ISSUE 2): the shared /metrics + /status.json mount
    registry = MetricsRegistry()
    mount_metrics(app, registry, server_name="adminserver",
                  status=lambda: {"status": "alive"})
    app.metrics_registry = registry  # type: ignore[attr-defined]

    def st() -> Storage:
        return storage if storage is not None else get_storage()

    _auth = make_key_auth(accesskey)

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        return json_response({"status": "alive"})

    @app.route("GET", "/cmd/app")
    def app_list(req: Request) -> Response:
        _auth(req)
        s = st()
        apps = []
        for a in s.apps().get_all():
            keys = s.access_keys().get_by_app_id(a.id)
            apps.append({"name": a.name, "id": a.id,
                         "accessKey": keys[0].key if keys else ""})
        return json_response({"status": 1, "message": "Successful retrieved"
                              " app list.", "apps": apps})

    @app.route("POST", "/cmd/app")
    def app_new(req: Request) -> Response:
        _auth(req)
        body = req.json() or {}
        name = body.get("name")
        if not name:
            return json_response({"status": 0,
                                  "message": "name is required."}, 400)
        s = st()
        if s.apps().get_by_name(name) is not None:
            return json_response(
                {"status": 0,
                 "message": f"App {name} already exists. Aborting."})
        app_id = s.apps().insert(App(id=int(body.get("id") or 0), name=name,
                                     description=body.get("description")))
        if app_id is None:
            return json_response({"status": 0,
                                  "message": "Unable to create new app."})
        s.events().init(app_id)
        key = s.access_keys().insert(AccessKey(key="", app_id=app_id,
                                               events=()))
        return json_response({"status": 1,
                              "message": "App created successfully.",
                              "id": app_id, "name": name, "key": key})

    @app.route("DELETE", r"/cmd/app/(?P<name>[^/]+)/data")
    def app_data_delete(req: Request) -> Response:
        _auth(req)
        s = st()
        a = s.apps().get_by_name(req.path_params["name"])
        if a is None:
            return json_response(
                {"status": 0,
                 "message": f"App {req.path_params['name']} does not "
                            f"exist."}, 404)
        s.events().remove(a.id)
        s.events().init(a.id)
        return json_response({"status": 1,
                              "message": f"Removed Event Store for this app "
                                         f"ID: {a.id}"})

    @app.route("DELETE", r"/cmd/app/(?P<name>[^/]+)")
    def app_delete(req: Request) -> Response:
        _auth(req)
        s = st()
        a = s.apps().get_by_name(req.path_params["name"])
        if a is None:
            return json_response(
                {"status": 0,
                 "message": f"App {req.path_params['name']} does not "
                            f"exist."}, 404)
        for c in s.channels().get_by_app_id(a.id):
            s.events().remove(a.id, c.id)
            s.channels().delete(c.id)
        s.events().remove(a.id)
        for k in s.access_keys().get_by_app_id(a.id):
            s.access_keys().delete(k.key)
        s.apps().delete(a.id)
        return json_response({"status": 1,
                              "message": f"App successfully deleted"})

    return app


def create_admin_server(storage: Optional[Storage] = None,
                        host: str = "127.0.0.1",
                        port: int = 7071,
                        accesskey: Optional[str] = None,
                        ssl_context=None) -> AppServer:
    return AppServer(build_app(storage, accesskey=accesskey), host=host,
                     port=port, ssl_context=ssl_context)
