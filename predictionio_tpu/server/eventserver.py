"""Event Server: REST ingestion over the event store.

Capability parity with the reference Event Server
(``data/api/EventServer.scala:61-560``): access-key auth via query param
or Basic header (:92-130), channel resolution, allowed-events
enforcement (:249,353), single/batch/filtered-query event routes with the
reference's status-code semantics (batch cap 50 with per-event status
array, :340-419), ``/stats.json`` behind ``--stats`` (:421-441), webhook
routes ``/webhooks/<name>.json|form`` (:442-523), and plugin routes.
"""

from __future__ import annotations

import base64
import logging
from dataclasses import dataclass
from typing import List, Optional

from ..cache.bus import InvalidationBus, default_bus
from ..data.event import Event, EventValidationError, parse_iso
from ..data.storage.base import EventFilter, ANY
from ..data.storage.registry import Storage, get_storage
from ..data.webhooks import (
    ConnectorException,
    form_connectors,
    json_connectors,
    to_event,
)
from ..obs import MetricsRegistry
from .http import (
    AppServer,
    HTTPApp,
    HTTPError,
    Request,
    Response,
    json_response,
    mount_metrics,
)
from .plugins import EventServerPlugins
from .stats import StatsCollector

log = logging.getLogger(__name__)

MAX_EVENTS_PER_BATCH = 50  # EventServer.scala:66


@dataclass
class AuthData:
    app_id: int
    channel_id: Optional[int]
    events: List[str]  # allowed event names; empty = all allowed


def authenticate(storage: Storage, req: Request) -> AuthData:
    """Resolve accessKey (query param, else Basic auth username) → app
    (+channel), mirroring ``EventServer.scala:92-130``."""
    key = req.query.get("accessKey")
    if key is None:
        auth = req.headers.get("Authorization", "")
        if auth.startswith("Basic "):
            try:
                decoded = base64.b64decode(auth[len("Basic "):]).decode("utf-8")
            except Exception:
                raise HTTPError(401, "Invalid accessKey.")
            key = decoded.strip().split(":")[0]
        else:
            raise HTTPError(401, "Missing accessKey.")
    record = storage.access_keys().get(key)
    if record is None:
        raise HTTPError(401, "Invalid accessKey.")
    channel_id: Optional[int] = None
    channel_name = req.query.get("channel")
    if channel_name is not None:
        channels = {c.name: c.id for c in
                    storage.channels().get_by_app_id(record.app_id)}
        if channel_name not in channels:
            raise HTTPError(401, f"Invalid channel '{channel_name}'.")
        channel_id = channels[channel_name]
    return AuthData(app_id=record.app_id, channel_id=channel_id,
                    events=list(record.events))


def _allowed(auth: AuthData, event_name: str) -> bool:
    return not auth.events or event_name in auth.events


def build_app(storage: Optional[Storage] = None, *, stats: bool = False,
              plugins: Optional[EventServerPlugins] = None,
              bus: Optional[InvalidationBus] = None) -> HTTPApp:
    st = storage if storage is not None else get_storage()
    collector = StatsCollector() if stats else None
    plug = plugins or EventServerPlugins()
    app = HTTPApp("eventserver")
    # serving-cache invalidation (ISSUE 4): every accepted ingest is
    # published so a cached result contradicted by this event dies NOW
    # (same-process engine servers) instead of at the TTL bound
    inval_bus = bus if bus is not None else default_bus()

    # telemetry (ISSUE 2): event-ingest counters + the shared runtime
    # series; /metrics and an enriched /status.json via mount_metrics
    registry = MetricsRegistry()
    registry.gauge("pio_stats_enabled",
                   "1 when the --stats per-app collector is on"
                   ).set(1.0 if stats else 0.0)
    ingested = registry.counter(
        "pio_events_ingested_total",
        "Events accepted into the store, by ingest route")
    invalidations_pub = registry.counter(
        "pio_cache_bus_published_total",
        "Ingested events published to the serving-cache invalidation "
        "bus (deliveries = publishes × live subscribers)")

    def _publish(app_id: int, event: Event) -> None:
        """Best-effort bus publish: ingest NEVER fails because a cache
        subscriber did."""
        try:
            inval_bus.publish(app_id, event.entity_type,
                              event.entity_id, event.event)
            invalidations_pub.inc()
        except Exception as e:  # noqa: BLE001
            log.error("invalidation publish failed: %s", e)

    def _publish_batch(app_id: int, events: List[Event]) -> None:
        """Coalesced publish for an accepted batch (ISSUE 10
        satellite): one subscriber snapshot + one stats update for the
        whole batch instead of a full publish (two lock passes + a
        dead-ref sweep) per event. Tag semantics are exactly those of
        N single publishes — every subscriber still sees every item."""
        if not events:
            return
        try:
            inval_bus.publish_many(
                app_id, [(e.entity_type, e.entity_id, e.event)
                         for e in events])
            invalidations_pub.inc(len(events))
        except Exception as e:  # noqa: BLE001
            log.error("invalidation publish failed: %s", e)
    mount_metrics(app, registry, server_name="eventserver",
                  status=lambda: {"status": "alive",
                                  "statsEnabled": bool(collector)})
    app.metrics_registry = registry  # type: ignore[attr-defined]

    def _auth(req: Request) -> AuthData:
        return authenticate(st, req)

    def _stamp_trace(req: Request, event: Event) -> Event:
        """Stamp the ingest request's W3C trace context into the
        accepted event (``pio_traceparent`` builtin property,
        ISSUE 12): the streaming trainer adopts it at fold-in so the
        event's trace, the fold-in pass, and the hot-swap that made it
        servable are ONE trace — ``/trace.json?id=`` then shows
        ingest → canary → swap end to end.

        Only when the CALLER sent a ``traceparent`` (W3C semantics: a
        request joins a trace, a server never imposes one) — an
        untraced client's events read back byte-identical to what it
        posted."""
        if req.trace is None or req.trace.parent_span_id is None \
                or "pio_traceparent" in event.properties:
            return event  # untraced caller / a relaying stamp wins
        from ..data.datamap import DataMap

        return event.copy(properties=DataMap(
            {**event.properties, "pio_traceparent":
             req.trace.traceparent()}))

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        return json_response({"status": "alive"})

    @app.route("GET", "/plugins.json")
    def plugins_json(req: Request) -> Response:
        return json_response({"plugins": plug.describe()})

    @app.route("GET", r"/plugins/(?P<ptype>[^/]+)/(?P<pname>[^/]+)"
                      r"(?P<rest>(/[^/]+)*)")
    def plugin_rest(req: Request) -> Response:
        """Per-plugin REST surface (``EventServer.scala:174-205``):
        accessKey-authenticated; the plugin's ``handle_rest`` receives the
        caller's (appId, channelId) plus the remaining path segments."""
        from .plugins import resolve_plugin

        auth = _auth(req)
        plugin, args = resolve_plugin(
            {"inputblockers": plug.input_blockers,
             "inputsniffers": plug.input_sniffers},
            req.path_params["ptype"], req.path_params["pname"],
            req.path_params["rest"])
        return json_response(
            plugin.handle_rest(auth.app_id, auth.channel_id, args))

    @app.route("POST", "/events.json")
    def post_event(req: Request) -> Response:
        auth = _auth(req)
        try:
            event = Event.from_json(req.json())
        except (EventValidationError, TypeError, KeyError, ValueError) as e:
            raise HTTPError(400, str(e))
        if not _allowed(auth, event.event):
            return json_response(
                {"message": f"{event.event} events are not allowed"}, 403)
        event = _stamp_trace(req, event)
        plug.process_input(auth.app_id, auth.channel_id, event)
        event_id = st.events().insert(event, auth.app_id, auth.channel_id)
        ingested.labels(route="events").inc()
        _publish(auth.app_id, event)
        if collector:
            collector.bookkeeping(auth.app_id, 201, event)
        return json_response({"eventId": event_id}, 201)

    @app.route("GET", "/events.json")
    def get_events(req: Request) -> Response:
        auth = _auth(req)
        q = req.query
        reversed_ = q.get("reversed", "false").lower() == "true"
        if reversed_ and not (q.get("entityType") and q.get("entityId")):
            raise HTTPError(400, "the parameter reversed can only be used "
                                 "with both entityType and entityId specified.")
        try:
            filt = EventFilter(
                start_time=parse_iso(q["startTime"]) if "startTime" in q else None,
                until_time=parse_iso(q["untilTime"]) if "untilTime" in q else None,
                entity_type=q.get("entityType"),
                entity_id=q.get("entityId"),
                event_names=[q["event"]] if "event" in q else None,
                target_entity_type=q.get("targetEntityType", ANY),
                target_entity_id=q.get("targetEntityId", ANY),
                limit=int(q.get("limit", 20)),
                reversed=reversed_)
        except (EventValidationError, ValueError) as e:
            raise HTTPError(400, str(e))
        events = list(st.events().find(auth.app_id, auth.channel_id, filt))
        if not events:
            return json_response({"message": "Not Found"}, 404)
        return json_response([e.to_json() for e in events])

    @app.route("POST", "/batch/events.json")
    def post_batch(req: Request) -> Response:
        auth = _auth(req)
        payload = req.json()
        if not isinstance(payload, list):
            raise HTTPError(400, "batch request body must be a JSON array")
        if len(payload) > MAX_EVENTS_PER_BATCH:
            raise HTTPError(400, "Batch request must have less than or equal "
                                 f"to {MAX_EVENTS_PER_BATCH} events")
        results: list = []
        valid: list = []  # (position in results, event)
        for obj in payload:
            try:
                event = _stamp_trace(req, Event.from_json(obj))
            except (EventValidationError, TypeError, KeyError, ValueError) as e:
                results.append({"status": 400, "message": str(e)})
                continue
            if not _allowed(auth, event.event):
                results.append({
                    "status": 403,
                    "message": f"{event.event} events are not allowed"})
                continue
            try:
                plug.process_input(auth.app_id, auth.channel_id, event)
            except Exception as e:  # noqa: BLE001 — per-event isolation
                results.append({"status": 500, "message": str(e)})
                continue
            results.append(None)  # filled below
            valid.append((len(results) - 1, event))

        if valid:
            # bulk insert (one storage transaction instead of one commit
            # per event — ~5× HTTP throughput on SQLite); fall back to
            # per-event inserts so one poison event can't fail the batch
            # (the reference's per-event futureInsert isolation,
            # EventServer.scala:372-401). ONLY the insert_batch call is
            # guarded: a failure after a successful bulk insert must not
            # re-insert (and thus duplicate) the whole batch.
            try:
                ids = st.events().insert_batch(
                    [e for _, e in valid], auth.app_id, auth.channel_id)
            except Exception:  # noqa: BLE001 — isolate per event
                ids = None
            accepted: list = []  # published ONCE, after the loop
            if ids is not None:
                for (pos, event), eid in zip(valid, ids):
                    results[pos] = {"status": 201, "eventId": eid}
                    ingested.labels(route="batch").inc()
                    accepted.append(event)
                    if collector:
                        collector.bookkeeping(auth.app_id, 201, event)
            else:
                for pos, event in valid:
                    try:
                        eid = st.events().insert(event, auth.app_id,
                                                 auth.channel_id)
                        results[pos] = {"status": 201, "eventId": eid}
                        ingested.labels(route="batch").inc()
                        accepted.append(event)
                        if collector:
                            collector.bookkeeping(auth.app_id, 201, event)
                    except Exception as e:  # noqa: BLE001
                        results[pos] = {"status": 500, "message": str(e)}
            _publish_batch(auth.app_id, accepted)
        return json_response(results)

    @app.route("POST", "/columnar/events.npz")
    def post_columnar(req: Request) -> Response:
        """Zero-copy block ingest: the body is one npz-encoded
        ``ColumnarBatch`` (the same wire format the storage server's
        bulk read serves). No per-event JSON parse, no per-event
        ``Event`` objects: the backend's ``insert_columnar`` lane
        writes the block in a single transaction and the invalidation
        bus gets ONE coalesced publish of the block's unique
        ``(entityType, entityId, event)`` triples. Per-event niceties
        (input plugins, trace stamping, stats bookkeeping, per-event
        ids in the response) deliberately don't apply — this is the
        firehose lane; use ``/batch/events.json`` when you need them."""
        import numpy as np

        from ..data.storage.wire import batch_from_npz

        auth = _auth(req)
        try:
            batch = batch_from_npz(req.body)
        except Exception as e:
            raise HTTPError(400, f"bad columnar block: {e}")
        if auth.events:
            names = [batch.dicts.event_names.values[int(c)]
                     for c in np.unique(batch.event)]
            bad = [nm for nm in names if not _allowed(auth, nm)]
            if bad:
                return json_response(
                    {"message": f"{bad[0]} events are not allowed"}, 403)
        n = st.events().insert_columnar(batch, auth.app_id,
                                        auth.channel_id)
        ingested.labels(route="columnar").inc(n)
        if n:
            try:
                d = batch.dicts
                uniq = np.unique(np.stack(
                    [batch.entity_type, batch.entity_id, batch.event],
                    axis=1), axis=0)
                inval_bus.publish_many(auth.app_id, [
                    (d.entity_types.values[int(a)],
                     d.entity_ids.values[int(b)],
                     d.event_names.values[int(c)])
                    for a, b, c in uniq])
                invalidations_pub.inc(n)
            except Exception as e:  # noqa: BLE001
                log.error("invalidation publish failed: %s", e)
        if collector:
            collector.bookkeeping_bulk(auth.app_id, 201, batch)
        return json_response({"accepted": int(n)}, 201)

    @app.route("GET", "/stats.json")
    def get_stats(req: Request) -> Response:
        auth = _auth(req)
        if collector is None:
            # runtime hint (ISSUE 2 satellite): the toggle is
            # boot-time-only, so the 404 explains exactly how to turn
            # it on; /status.json and /metrics carry the same state
            return json_response(
                {"message": "To see stats, launch Event Server with --stats "
                            "argument.",
                 "statsEnabled": False,
                 "hint": "Restart with `ptpu eventserver --stats` — the "
                         "collector only exists when enabled at boot. "
                         "Aggregate counters are always available at "
                         "/metrics and /status.json."}, 404)
        return json_response(collector.get(auth.app_id))

    @app.route("GET", r"/events/(?P<event_id>[^/]+)\.json")
    def get_event(req: Request) -> Response:
        auth = _auth(req)
        event = st.events().get(req.path_params["event_id"], auth.app_id,
                                auth.channel_id)
        if event is None:
            return json_response({"message": "Not Found"}, 404)
        return json_response(event.to_json())

    @app.route("DELETE", r"/events/(?P<event_id>[^/]+)\.json")
    def delete_event(req: Request) -> Response:
        auth = _auth(req)
        found = st.events().delete(req.path_params["event_id"], auth.app_id,
                                   auth.channel_id)
        if found:
            return json_response({"message": "Found"})
        return json_response({"message": "Not Found"}, 404)

    def _webhook_post(req: Request, name: str, is_form: bool) -> Response:
        auth = _auth(req)
        registry = form_connectors if is_form else json_connectors
        connector = registry.get(name)
        if connector is None:
            return json_response(
                {"message": f"webhooks connection for {name} is not "
                            "supported."}, 404)
        try:
            data = req.form() if is_form else req.json()
            event = _stamp_trace(req, to_event(connector, data))
        except (ConnectorException, EventValidationError, ValueError) as e:
            raise HTTPError(400, str(e))
        event_id = st.events().insert(event, auth.app_id, auth.channel_id)
        ingested.labels(route="webhook").inc()
        _publish(auth.app_id, event)
        if collector:
            collector.bookkeeping(auth.app_id, 201, event)
        return json_response({"eventId": event_id}, 201)

    def _webhook_get(req: Request, name: str, is_form: bool) -> Response:
        _auth(req)
        registry = form_connectors if is_form else json_connectors
        if name in registry:
            return json_response({"message": "Ok"})
        return json_response(
            {"message": f"webhooks connection for {name} is not supported."},
            404)

    @app.route("POST", r"/webhooks/(?P<name>[^/]+)\.json")
    def webhook_post_json(req: Request) -> Response:
        return _webhook_post(req, req.path_params["name"], is_form=False)

    @app.route("GET", r"/webhooks/(?P<name>[^/]+)\.json")
    def webhook_get_json(req: Request) -> Response:
        return _webhook_get(req, req.path_params["name"], is_form=False)

    @app.route("POST", r"/webhooks/(?P<name>[^/]+)\.form")
    def webhook_post_form(req: Request) -> Response:
        return _webhook_post(req, req.path_params["name"], is_form=True)

    @app.route("GET", r"/webhooks/(?P<name>[^/]+)\.form")
    def webhook_get_form(req: Request) -> Response:
        return _webhook_get(req, req.path_params["name"], is_form=True)

    return app


def create_event_server(storage: Optional[Storage] = None,
                        host: str = "0.0.0.0", port: int = 7070,
                        stats: bool = False) -> AppServer:
    """Bind the Event Server (``EventServer.createEventServer``,
    ``EventServer.scala:528-548``; default port 7070 per ``Run.main``)."""
    return AppServer(build_app(storage, stats=stats), host, port)
