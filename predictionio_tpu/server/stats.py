"""Event Server request bookkeeping.

Capability parity with the reference's ``Stats``/``StatsActor``
(``data/api/Stats.scala:41-80``, ``data/api/StatsActor.scala:30-76``):
per-appId counts keyed by (entityType, targetEntityType, event) and by
status code, kept for the current hour with the previous hour retained
after cutoff. No actor needed — a lock suffices.
"""

from __future__ import annotations

import threading
from datetime import datetime, timedelta, timezone
from typing import Dict, Optional, Tuple

from ..data.event import Event, isoformat_millis

EteKey = Tuple[str, Optional[str], str]  # (entityType, targetEntityType, event)


class Stats:
    """One accumulation window (the reference's ``Stats`` class)."""

    def __init__(self, start_time: datetime):
        self.start_time = start_time
        self.end_time: Optional[datetime] = None
        self.status_code_count: Dict[Tuple[int, int], int] = {}
        self.ete_count: Dict[Tuple[int, EteKey], int] = {}

    def update(self, app_id: int, status: int, event: Event) -> None:
        sk = (app_id, status)
        self.status_code_count[sk] = self.status_code_count.get(sk, 0) + 1
        ek = (app_id, (event.entity_type, event.target_entity_type, event.event))
        self.ete_count[ek] = self.ete_count.get(ek, 0) + 1

    def cutoff(self, end_time: datetime) -> None:
        self.end_time = end_time

    def snapshot(self, app_id: int) -> dict:
        return {
            "startTime": isoformat_millis(self.start_time),
            "endTime": (isoformat_millis(self.end_time)
                        if self.end_time else None),
            "basic": [
                {"key": {"entityType": k[0], "targetEntityType": k[1],
                         "event": k[2]},
                 "value": v}
                for (aid, k), v in sorted(self.ete_count.items())
                if aid == app_id],
            "statusCode": [
                {"key": code, "value": v}
                for (aid, code), v in sorted(self.status_code_count.items())
                if aid == app_id],
        }


def _hour_floor(t: datetime) -> datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class StatsCollector:
    """Thread-safe hourly-rolling pair of windows (``StatsActor`` role)."""

    def __init__(self):
        self._lock = threading.Lock()
        now = datetime.now(timezone.utc)
        self._current = Stats(_hour_floor(now))
        self._previous: Optional[Stats] = None

    def _roll(self, now: datetime) -> None:
        hour = _hour_floor(now)
        if hour > self._current.start_time:
            self._current.cutoff(hour)
            self._previous = self._current
            self._current = Stats(hour)

    def bookkeeping(self, app_id: int, status: int, event: Event) -> None:
        now = datetime.now(timezone.utc)
        with self._lock:
            self._roll(now)
            self._current.update(app_id, status, event)

    def get(self, app_id: int) -> dict:
        with self._lock:
            self._roll(datetime.now(timezone.utc))
            result = self._current.snapshot(app_id)
            if self._previous is not None:
                result["prev"] = self._previous.snapshot(app_id)
            return result
