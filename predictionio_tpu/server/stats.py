"""Server request/runtime bookkeeping.

Capability parity with the reference's ``Stats``/``StatsActor``
(``data/api/Stats.scala:41-80``, ``data/api/StatsActor.scala:30-76``):
per-appId counts keyed by (entityType, targetEntityType, event) and by
status code, kept for the current hour with the previous hour retained
after cutoff. No actor needed — a lock suffices.

Also home of :class:`RecompileSentinel` — the runtime complement of the
``ptpu check`` recompile-hazard lint: it counts XLA backend compiles
after the serving warmup finished, so a recompile storm on the query
path (novel shapes, unhashable statics regressions) is visible in the
engine server's ``/status.json`` instead of only as tail latency.
"""

from __future__ import annotations

import threading
from datetime import datetime, timezone
from typing import Dict, Optional, Tuple

from ..concurrency import new_lock
from ..data.event import Event, isoformat_millis

EteKey = Tuple[str, Optional[str], str]  # (entityType, targetEntityType, event)


class Stats:
    """One accumulation window (the reference's ``Stats`` class)."""

    def __init__(self, start_time: datetime):
        self.start_time = start_time
        self.end_time: Optional[datetime] = None
        self.status_code_count: Dict[Tuple[int, int], int] = {}
        self.ete_count: Dict[Tuple[int, EteKey], int] = {}

    def update(self, app_id: int, status: int, event: Event) -> None:
        sk = (app_id, status)
        self.status_code_count[sk] = self.status_code_count.get(sk, 0) + 1
        ek = (app_id, (event.entity_type, event.target_entity_type, event.event))
        self.ete_count[ek] = self.ete_count.get(ek, 0) + 1

    def cutoff(self, end_time: datetime) -> None:
        self.end_time = end_time

    def snapshot(self, app_id: int) -> dict:
        return {
            "startTime": isoformat_millis(self.start_time),
            "endTime": (isoformat_millis(self.end_time)
                        if self.end_time else None),
            "basic": [
                {"key": {"entityType": k[0], "targetEntityType": k[1],
                         "event": k[2]},
                 "value": v}
                for (aid, k), v in sorted(self.ete_count.items())
                if aid == app_id],
            "statusCode": [
                {"key": code, "value": v}
                for (aid, code), v in sorted(self.status_code_count.items())
                if aid == app_id],
        }


def _hour_floor(t: datetime) -> datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class StatsCollector:
    """Thread-safe hourly-rolling pair of windows (``StatsActor`` role)."""

    def __init__(self):
        self._lock = new_lock("StatsCollector._lock")
        now = datetime.now(timezone.utc)
        self._current = Stats(_hour_floor(now))
        self._previous: Optional[Stats] = None

    def _roll(self, now: datetime) -> None:
        hour = _hour_floor(now)
        if hour > self._current.start_time:
            self._current.cutoff(hour)
            self._previous = self._current
            self._current = Stats(hour)

    def bookkeeping(self, app_id: int, status: int, event: Event) -> None:
        now = datetime.now(timezone.utc)
        with self._lock:
            self._roll(now)
            self._current.update(app_id, status, event)

    def get(self, app_id: int) -> dict:
        with self._lock:
            self._roll(datetime.now(timezone.utc))
            result = self._current.snapshot(app_id)
            if self._previous is not None:
                result["prev"] = self._previous.snapshot(app_id)
            return result


class RecompileSentinel:
    """Post-warmup compilation-cache-miss counter.

    ``jax.monitoring`` fires one duration event per XLA backend compile
    (``/jax/core/compile/backend_compile_duration``); a process-wide
    listener tallies them. :meth:`arm` snapshots the tally when serving
    warmup completes — after that, every additional compile is traffic
    paying a compile it should not, and :meth:`snapshot` reports the
    delta. The listener registers once per process and is never removed
    (jax offers no unregister); instances only read the shared counter,
    so sentinels are cheap and re-armable (deploy → reload → re-warm).
    """

    _lock = threading.Lock()
    _total = 0
    _installed = False
    _available = False

    @classmethod
    def _listener(cls, name: str, *args, **kwargs) -> None:
        if name == "/jax/core/compile/backend_compile_duration":
            with cls._lock:
                cls._total += 1

    @classmethod
    def _install(cls) -> None:
        with cls._lock:
            if cls._installed:
                return
            cls._installed = True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                cls._listener)
            cls._available = True
        except Exception:  # noqa: BLE001 — jax absent/changed: degrade
            cls._available = False

    def __init__(self):
        self._install()
        self._baseline: Optional[int] = None

    @classmethod
    def total_compiles(cls) -> int:
        with cls._lock:
            return cls._total

    @property
    def armed(self) -> bool:
        return self._baseline is not None

    def arm(self) -> None:
        """Start (or restart) counting — call when warmup completes."""
        self._baseline = self.total_compiles()

    @property
    def since_armed(self) -> int:
        if self._baseline is None:
            return 0
        return self.total_compiles() - self._baseline

    def snapshot(self) -> dict:
        return {
            "available": self._available,
            "armed": self.armed,
            "compilesSinceWarm": self.since_armed,
            "compilesTotal": self.total_compiles(),
        }
