"""Server request/runtime bookkeeping.

Capability parity with the reference's ``Stats``/``StatsActor``
(``data/api/Stats.scala:41-80``, ``data/api/StatsActor.scala:30-76``):
per-appId counts keyed by (entityType, targetEntityType, event) and by
status code, kept for the current hour with the previous hour retained
after cutoff. No actor needed — a lock suffices.

Also home of :class:`RecompileSentinel` — the runtime complement of the
``ptpu check`` recompile-hazard lint: it counts XLA backend compiles
after the serving warmup finished, so a recompile storm on the query
path (novel shapes, unhashable statics regressions) is visible in the
engine server's ``/status.json`` instead of only as tail latency.
"""

from __future__ import annotations

import threading
from datetime import datetime, timezone
from typing import Dict, Optional, Tuple

from ..concurrency import new_lock
from ..data.event import Event, isoformat_millis

EteKey = Tuple[str, Optional[str], str]  # (entityType, targetEntityType, event)


class Stats:
    """One accumulation window (the reference's ``Stats`` class)."""

    def __init__(self, start_time: datetime):
        self.start_time = start_time
        self.end_time: Optional[datetime] = None
        self.status_code_count: Dict[Tuple[int, int], int] = {}
        self.ete_count: Dict[Tuple[int, EteKey], int] = {}

    def update(self, app_id: int, status: int, event: Event) -> None:
        sk = (app_id, status)
        self.status_code_count[sk] = self.status_code_count.get(sk, 0) + 1
        ek = (app_id, (event.entity_type, event.target_entity_type, event.event))
        self.ete_count[ek] = self.ete_count.get(ek, 0) + 1

    def cutoff(self, end_time: datetime) -> None:
        self.end_time = end_time

    def snapshot(self, app_id: int) -> dict:
        return {
            "startTime": isoformat_millis(self.start_time),
            "endTime": (isoformat_millis(self.end_time)
                        if self.end_time else None),
            "basic": [
                {"key": {"entityType": k[0], "targetEntityType": k[1],
                         "event": k[2]},
                 "value": v}
                for (aid, k), v in sorted(self.ete_count.items())
                if aid == app_id],
            "statusCode": [
                {"key": code, "value": v}
                for (aid, code), v in sorted(self.status_code_count.items())
                if aid == app_id],
        }


def _hour_floor(t: datetime) -> datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class StatsCollector:
    """Thread-safe hourly-rolling pair of windows (``StatsActor`` role)."""

    def __init__(self):
        self._lock = new_lock("StatsCollector._lock")
        now = datetime.now(timezone.utc)
        self._current = Stats(_hour_floor(now))
        self._previous: Optional[Stats] = None

    def _roll(self, now: datetime) -> None:
        hour = _hour_floor(now)
        if hour > self._current.start_time:
            self._current.cutoff(hour)
            self._previous = self._current
            self._current = Stats(hour)

    def bookkeeping(self, app_id: int, status: int, event: Event) -> None:
        now = datetime.now(timezone.utc)
        with self._lock:
            self._roll(now)
            self._current.update(app_id, status, event)

    def bookkeeping_bulk(self, app_id: int, status: int, batch) -> None:
        """Columnar-block bookkeeping: one ``np.unique`` over the coded
        columns replaces ``n`` per-event dict updates — the window's
        counts come out exactly as if every event had been booked
        individually."""
        import numpy as np

        n = batch.n
        if not n:
            return
        keys, counts = np.unique(np.stack(
            [batch.entity_type, batch.target_type, batch.event], axis=1),
            axis=0, return_counts=True)
        d = batch.dicts
        now = datetime.now(timezone.utc)
        with self._lock:
            self._roll(now)
            cur = self._current
            sk = (app_id, status)
            cur.status_code_count[sk] = \
                cur.status_code_count.get(sk, 0) + int(n)
            # ptpu: allow[host-sync-in-hot-path] — host numpy already;
            # `keys` holds the block's DISTINCT triples (bounded by the
            # app's event vocabulary), not its n rows
            for (et, tt, ev), c in zip(keys.tolist(), counts.tolist()):
                ek = (app_id, (
                    d.entity_types.values[et],
                    d.target_types.values[tt] if tt >= 0 else None,
                    d.event_names.values[ev]))
                cur.ete_count[ek] = cur.ete_count.get(ek, 0) + int(c)

    def get(self, app_id: int) -> dict:
        with self._lock:
            self._roll(datetime.now(timezone.utc))
            result = self._current.snapshot(app_id)
            if self._previous is not None:
                result["prev"] = self._previous.snapshot(app_id)
            return result


class RecompileSentinel:
    """Post-warmup compilation-cache-miss counter.

    ``jax.monitoring`` fires one duration event per XLA backend compile
    (``/jax/core/compile/backend_compile_duration``); a process-wide
    listener tallies them. :meth:`arm` snapshots the tally when serving
    warmup completes — after that, every additional compile is traffic
    paying a compile it should not, and :meth:`snapshot` reports the
    delta. The listener registers once per process and is never removed
    (jax offers no unregister); instances only read the shared counter,
    so sentinels are cheap and re-armable (deploy → reload → re-warm).
    """

    _lock = threading.Lock()
    _total = 0
    _installed = False
    _available = False
    #: per-event compile/trace-time table (ISSUE 12): every
    #: ``jax.monitoring`` duration event keyed by its (bounded) event
    #: name — count/total/max/last seconds. ``compile_table()`` serves
    #: it on ``/profile.json`` so a ``pio_compiles_since_warm`` blip
    #: can be itemized (which stage paid, how long) without a profiler
    #: attach.
    _durations: Dict[str, Dict[str, float]] = {}
    MAX_TABLE_EVENTS = 64

    @classmethod
    def _listener(cls, name: str, *args, **kwargs) -> None:
        seconds = 0.0
        if args:
            try:
                seconds = float(args[0])
            except (TypeError, ValueError):
                seconds = 0.0
        with cls._lock:
            if name == "/jax/core/compile/backend_compile_duration":
                cls._total += 1
            row = cls._durations.get(name)
            if row is None:
                if len(cls._durations) >= cls.MAX_TABLE_EVENTS:
                    return  # bounded: never grow without limit
                row = cls._durations[name] = {
                    "count": 0, "total_sec": 0.0, "max_sec": 0.0,
                    "last_sec": 0.0}
            row["count"] += 1
            row["total_sec"] += seconds
            row["last_sec"] = seconds
            if seconds > row["max_sec"]:
                row["max_sec"] = seconds

    @classmethod
    def _install(cls) -> None:
        with cls._lock:
            if cls._installed:
                return
            cls._installed = True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                cls._listener)
            cls._available = True
        except Exception:  # noqa: BLE001 — jax absent/changed: degrade
            cls._available = False

    def __init__(self):
        self._install()
        self._baseline: Optional[int] = None

    @classmethod
    def total_compiles(cls) -> int:
        with cls._lock:
            return cls._total

    @property
    def armed(self) -> bool:
        return self._baseline is not None

    def arm(self) -> None:
        """Start (or restart) counting — call when warmup completes."""
        self._baseline = self.total_compiles()

    @property
    def since_armed(self) -> int:
        if self._baseline is None:
            return 0
        return self.total_compiles() - self._baseline

    @classmethod
    def compile_table(cls) -> dict:
        """Per-event duration rows (rounded, JSON-ready), most total
        time first — the itemization behind ``pio_compiles_since_warm``
        and the ``/profile.json`` compile-time table."""
        with cls._lock:
            rows = {k: dict(v) for k, v in cls._durations.items()}
        return {
            name: {"count": int(r["count"]),
                   "totalSec": round(r["total_sec"], 4),
                   "maxSec": round(r["max_sec"], 4),
                   "lastSec": round(r["last_sec"], 4)}
            for name, r in sorted(rows.items(),
                                  key=lambda kv: -kv[1]["total_sec"])}

    def snapshot(self) -> dict:
        return {
            "available": self._available,
            "armed": self.armed,
            "compilesSinceWarm": self.since_armed,
            "compilesTotal": self.total_compiles(),
        }
