"""Dashboard: web UI over evaluation history.

Capability parity with ``tools/dashboard/Dashboard.scala:47-160``:
``GET /`` renders an HTML index of completed evaluation instances
(newest first) with links to per-instance
``/engine_instances/{id}/evaluator_results.{txt,html,json}``; the JSON
variant is also exposed CORS-enabled as ``local_evaluator_results.json``.
"""

from __future__ import annotations

import html as _html
from typing import Optional

from ..data.event import utcnow
from ..data.storage.registry import Storage, get_storage
from .http import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    json_response,
    make_key_auth,
)


def build_app(storage: Optional[Storage] = None,
              accesskey: Optional[str] = None) -> HTTPApp:
    app = HTTPApp("dashboard")
    start_time = utcnow()

    def st() -> Storage:
        return storage if storage is not None else get_storage()

    _auth = make_key_auth(accesskey)
    #: propagated to generated links so navigation stays authenticated
    key_qs = f"?accessKey={accesskey}" if accesskey else ""

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        _auth(req)
        rows = []
        for i in st().evaluation_instances().get_completed():
            esc = _html.escape
            rows.append(
                f"<tr><td>{esc(i.id)}</td>"
                f"<td>{esc(str(i.start_time))}</td>"
                f"<td>{esc(str(i.end_time))}</td>"
                f"<td>{esc(i.evaluation_class)}</td>"
                f"<td>{esc(i.evaluator_results)}</td>"
                f"<td><a href='/engine_instances/{esc(i.id)}/"
                f"evaluator_results.html{key_qs}'>HTML</a> "
                f"<a href='/engine_instances/{esc(i.id)}/"
                f"evaluator_results.json{key_qs}'>JSON</a> "
                f"<a href='/engine_instances/{esc(i.id)}/"
                f"evaluator_results.txt{key_qs}'>TXT</a></td></tr>")
        body = (
            "<html><head><title>PredictionIO-TPU Dashboard</title></head>"
            f"<body><h1>Evaluation history</h1>"
            f"<p>Dashboard up since {start_time}</p>"
            "<table border='1'><tr><th>ID</th><th>Start</th><th>End</th>"
            "<th>Evaluation</th><th>Result</th><th>Details</th></tr>"
            + "".join(rows) + "</table></body></html>")
        return Response(status=200, body=body,
                        content_type="text/html; charset=utf-8")

    def _instance(req: Request):
        return st().evaluation_instances().get(req.path_params["iid"])

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"evaluator_results\.txt")
    def results_txt(req: Request) -> Response:
        _auth(req)
        i = _instance(req)
        if i is None:
            return json_response({"message": "Not Found"}, 404)
        return Response(status=200, body=i.evaluator_results,
                        content_type="text/plain; charset=utf-8")

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"evaluator_results\.html")
    def results_html(req: Request) -> Response:
        _auth(req)
        i = _instance(req)
        if i is None:
            return json_response({"message": "Not Found"}, 404)
        return Response(status=200, body=i.evaluator_results_html,
                        content_type="text/html; charset=utf-8")

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"evaluator_results\.json")
    def results_json(req: Request) -> Response:
        _auth(req)
        i = _instance(req)
        if i is None:
            return json_response({"message": "Not Found"}, 404)
        return Response(status=200, body=i.evaluator_results_json,
                        content_type="application/json")

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"local_evaluator_results\.json")
    def results_json_cors(req: Request) -> Response:
        resp = results_json(req)
        resp.headers["Access-Control-Allow-Origin"] = "*"
        return resp

    return app


def create_dashboard(storage: Optional[Storage] = None,
                     host: str = "127.0.0.1", port: int = 9000,
                     accesskey: Optional[str] = None,
                     ssl_context=None) -> AppServer:
    return AppServer(build_app(storage, accesskey=accesskey), host=host,
                     port=port, ssl_context=ssl_context)
