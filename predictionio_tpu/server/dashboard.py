"""Dashboard: web UI over evaluation history.

Capability parity with ``tools/dashboard/Dashboard.scala:47-160``:
``GET /`` renders an HTML index of completed evaluation instances
(newest first) with links to per-instance
``/engine_instances/{id}/evaluator_results.{txt,html,json}``; the JSON
variant is also exposed CORS-enabled as ``local_evaluator_results.json``.
"""

from __future__ import annotations

import html as _html
from typing import Optional

from ..data.event import utcnow
from ..data.storage.registry import Storage, get_storage
from ..obs import MetricsRegistry
from .http import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    SessionAuth,
    mount_metrics,
)


def build_app(storage: Optional[Storage] = None,
              accesskey: Optional[str] = None,
              secure: bool = False) -> HTTPApp:
    app = HTTPApp("dashboard")
    start_time = utcnow()

    # telemetry (ISSUE 2): the dashboard scrapes like every other
    # server; its index page surfaces the percentile table
    registry = MetricsRegistry()
    mount_metrics(app, registry, server_name="dashboard",
                  status=lambda: {"status": "alive"})
    app.metrics_registry = registry  # type: ignore[attr-defined]

    def st() -> Storage:
        return storage if storage is not None else get_storage()

    # cookie session after the first authenticated request: generated
    # links never carry the accessKey (it would land in browser history,
    # proxy logs, and Referer headers)
    _session = SessionAuth(accesskey, secure=secure)

    def _auth(req: Request) -> dict:
        """Authorize; returns response headers (Set-Cookie on first
        key-authenticated request) to attach to every outcome, 404s
        included."""
        set_cookie = _session(req)
        return {"Set-Cookie": set_cookie} if set_cookie else {}

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        headers = _auth(req)
        rows = []
        for i in st().evaluation_instances().get_completed():
            esc = _html.escape
            rows.append(
                f"<tr><td>{esc(i.id)}</td>"
                f"<td>{esc(str(i.start_time))}</td>"
                f"<td>{esc(str(i.end_time))}</td>"
                f"<td>{esc(i.evaluation_class)}</td>"
                f"<td>{esc(i.evaluator_results)}</td>"
                f"<td><a href='/engine_instances/{esc(i.id)}/"
                f"evaluator_results.html'>HTML</a> "
                f"<a href='/engine_instances/{esc(i.id)}/"
                f"evaluator_results.json'>JSON</a> "
                f"<a href='/engine_instances/{esc(i.id)}/"
                f"evaluator_results.txt'>TXT</a></td></tr>")
        # request-latency percentile table from this server's own
        # registry (ISSUE 2: tails on the dashboard, not just uptime)
        lat_rows = []
        hist = registry.snapshot().get(
            "pio_http_request_duration_seconds") or {}
        if isinstance(hist, dict) and "count" in hist:
            hist = {"(all)": hist}
        for route, s in sorted(hist.items()):
            if not isinstance(s, dict) or not s.get("count"):
                continue
            lat_rows.append(
                f"<tr><td>{_html.escape(str(route))}</td>"
                f"<td>{s['count']}</td>"
                f"<td>{s['p50'] * 1000:.3f}</td>"
                f"<td>{s['p90'] * 1000:.3f}</td>"
                f"<td>{s['p99'] * 1000:.3f}</td></tr>")
        lat_table = (
            "<h2>Request latency percentiles</h2>"
            "<table border='1'><tr><th>route</th><th>count</th>"
            "<th>p50 (ms)</th><th>p90 (ms)</th><th>p99 (ms)</th></tr>"
            + "".join(lat_rows) + "</table>"
            "<p><a href='/metrics'>Prometheus metrics</a></p>"
            if lat_rows else "")
        body = (
            "<html><head><title>PredictionIO-TPU Dashboard</title></head>"
            f"<body><h1>Evaluation history</h1>"
            f"<p>Dashboard up since {start_time}</p>"
            "<table border='1'><tr><th>ID</th><th>Start</th><th>End</th>"
            "<th>Evaluation</th><th>Result</th><th>Details</th></tr>"
            + "".join(rows) + "</table>" + lat_table + "</body></html>")
        return Response(status=200, body=body,
                        content_type="text/html; charset=utf-8",
                        headers=headers)

    def _instance(req: Request):
        return st().evaluation_instances().get(req.path_params["iid"])

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"evaluator_results\.txt")
    def results_txt(req: Request) -> Response:
        headers = _auth(req)
        i = _instance(req)
        if i is None:
            return Response(status=404, body={"message": "Not Found"},
                            headers=headers)
        return Response(status=200, body=i.evaluator_results,
                        content_type="text/plain; charset=utf-8",
                        headers=headers)

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"evaluator_results\.html")
    def results_html(req: Request) -> Response:
        headers = _auth(req)
        i = _instance(req)
        if i is None:
            return Response(status=404, body={"message": "Not Found"},
                            headers=headers)
        return Response(status=200, body=i.evaluator_results_html,
                        content_type="text/html; charset=utf-8",
                        headers=headers)

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"evaluator_results\.json")
    def results_json(req: Request) -> Response:
        headers = _auth(req)
        i = _instance(req)
        if i is None:
            return Response(status=404, body={"message": "Not Found"},
                            headers=headers)
        return Response(status=200, body=i.evaluator_results_json,
                        content_type="application/json", headers=headers)

    @app.route("GET", r"/engine_instances/(?P<iid>[^/]+)/"
                      r"local_evaluator_results\.json")
    def results_json_cors(req: Request) -> Response:
        resp = results_json(req)
        resp.headers["Access-Control-Allow-Origin"] = "*"
        return resp

    return app


def create_dashboard(storage: Optional[Storage] = None,
                     host: str = "127.0.0.1", port: int = 9000,
                     accesskey: Optional[str] = None,
                     ssl_context=None) -> AppServer:
    return AppServer(build_app(storage, accesskey=accesskey,
                               secure=ssl_context is not None),
                     host=host, port=port, ssl_context=ssl_context)
