"""Minimal threaded HTTP app framework shared by the framework's servers.

The reference runs three akka-http servers (Event Server
``data/api/EventServer.scala``, engine server ``workflow/CreateServer.scala``,
admin/dashboard ``tools/``). Here one stdlib-based micro-framework backs all
of them: regex-routed handlers over ``ThreadingHTTPServer`` — no actor
system, no external dependencies, good enough for host-side control planes
(the TPU data plane never goes through HTTP).
"""

from __future__ import annotations

import json
import logging
import random
import re
import secrets
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..concurrency import new_lock
from ..data.storage.base import StorageError
from ..faults import FaultError

__all__ = ["Request", "Response", "HTTPApp", "AppServer", "json_response",
           "mount_metrics", "mount_trace_routes"]

#: Retry-After seconds on a 503 caused by an unavailable backing store
#: (docs/reliability.md): short enough that a recovered store is back
#: in rotation fast, long enough that a retrying client is not the one
#: that keeps it down
RETRY_AFTER_SECONDS = 1

#: Structured JSON access log — one line per request with the request id
#: and any per-phase timings the handler attached (``Request.obs``).
#: Quiet unless the operator enables INFO on this logger.
access_log = logging.getLogger("predictionio_tpu.access")


@dataclass
class Request:
    method: str
    path: str
    query: Dict[str, str]
    headers: Dict[str, str]
    body: bytes
    #: Named groups from the route pattern match.
    path_params: Dict[str, str] = field(default_factory=dict)
    #: Per-request id: echoed from an ``X-Request-ID`` header or minted
    #: here, attached to the access-log line and the response so any
    #: slow query can be decomposed post-hoc.
    request_id: str = ""
    #: Handler-attached observability payload (per-phase timings etc.);
    #: merged into this request's access-log line. Keys starting with
    #: ``_`` are carriers for in-process objects (the live trace) and
    #: never serialize into the log line.
    obs: Dict[str, Any] = field(default_factory=dict)
    #: The live :class:`~predictionio_tpu.obs.trace.Trace` when the app
    #: has a tracer mounted (every request does, cheaply; retention is
    #: the sampled part — docs/tracing.md). Also threaded through
    #: ``obs["_trace"]`` so batcher/pipeline code that only sees the
    #: obs dict can attach stage spans.
    trace: Any = None

    def header(self, name: str, default: Optional[str] = None
               ) -> Optional[str]:
        """Case-insensitive header lookup (clients send
        ``traceparent``, ``Traceparent``, ``TraceParent``…)."""
        v = self.headers.get(name)
        if v is not None:
            return v
        lower = name.lower()
        for k, val in self.headers.items():
            if k.lower() == lower:
                return val
        return default

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body.decode("utf-8"))

    def form(self) -> Dict[str, str]:
        """Parse an ``application/x-www-form-urlencoded`` body."""
        parsed = parse_qs(self.body.decode("utf-8"), keep_blank_values=True)
        return {k: v[0] for k, v in parsed.items()}


@dataclass
class Response:
    status: int = 200
    body: Any = None
    content_type: str = "application/json"
    headers: Dict[str, str] = field(default_factory=dict)

    def encoded(self) -> bytes:
        if self.body is None:
            return b""
        if isinstance(self.body, bytes):
            return self.body
        if isinstance(self.body, str):
            return self.body.encode("utf-8")
        return json.dumps(self.body).encode("utf-8")


def json_response(body: Any, status: int = 200) -> Response:
    return Response(status=status, body=body)


def make_key_auth(accesskey: Optional[str]) -> Callable[["Request"], None]:
    """Shared ``?accessKey=`` guard (the reference's KeyAuthentication,
    ``common/.../KeyAuthentication.scala:33-58``): no-op when no key is
    configured; constant-time comparison otherwise."""
    import hmac

    def _auth(req: "Request") -> None:
        if accesskey and not hmac.compare_digest(
                req.query.get("accessKey") or "", accesskey):
            raise HTTPError(401, "Invalid accessKey.")

    return _auth


class SessionAuth:
    """Cookie-session guard for browser-facing servers (dashboard).

    Accepts the accessKey once — via ``?accessKey=`` or an
    ``Authorization: Bearer`` header — then mints an HttpOnly session
    cookie, so generated links never embed the secret (which would leak
    into browser history, proxy logs, and Referer headers). The reference
    dashboard had no auth at all; this extends its KeyAuthentication
    pattern (``common/.../KeyAuthentication.scala:33-58``) to browsers.

    Calling the instance authorizes a request and returns a ``Set-Cookie``
    header value when a new session was minted (else ``None``); raises
    :class:`HTTPError` 401 on failure.
    """

    MAX_SESSIONS = 4096

    def __init__(self, accesskey: Optional[str],
                 cookie_name: str = "pio_dashboard_session",
                 secure: bool = False):
        import hmac as _hmac
        self._hmac = _hmac
        self.accesskey = accesskey
        self.cookie_name = cookie_name
        self.secure = secure
        #: insertion-ordered so overflow evicts the oldest session only —
        #: a cookie-less poller (curl health check) must not wholesale
        #: log out live browser sessions; values are monotonic expiry times
        self._tokens: "Dict[str, float]" = {}
        self._lock = new_lock("SessionKeyAuth._lock")

    #: sessions expire after 24h; a captured cookie does not authenticate
    #: for the life of the server process
    TTL_SECONDS = 24 * 3600.0

    def _cookie_token(self, req: "Request") -> Optional[str]:
        header = req.headers.get("Cookie") or ""
        for part in header.split(";"):
            name, _, value = part.strip().partition("=")
            if name == self.cookie_name and value:
                return value
        return None

    def __call__(self, req: "Request") -> Optional[str]:
        if not self.accesskey:
            return None
        import time as _time
        now = _time.monotonic()
        tok = self._cookie_token(req)
        if tok is not None:
            with self._lock:
                for t, expiry in self._tokens.items():
                    if self._hmac.compare_digest(tok, t):
                        if now <= expiry:
                            return None
                        break  # expired: fall through to key auth
        supplied = req.query.get("accessKey") or ""
        if not supplied:
            auth = req.headers.get("Authorization") or ""
            if auth.startswith("Bearer "):
                supplied = auth[len("Bearer "):]
        if supplied and self._hmac.compare_digest(supplied, self.accesskey):
            import secrets
            tok = secrets.token_urlsafe(32)
            with self._lock:
                expired = [t for t, exp in self._tokens.items()
                           if now > exp]
                for t in expired:
                    del self._tokens[t]
                while len(self._tokens) >= self.MAX_SESSIONS:
                    self._tokens.pop(next(iter(self._tokens)))
                self._tokens[tok] = now + self.TTL_SECONDS
            attrs = "; HttpOnly; SameSite=Strict; Path=/"
            if self.secure:
                attrs += "; Secure"
            return f"{self.cookie_name}={tok}{attrs}"
        raise HTTPError(401, "Invalid accessKey.")


def ssl_context_from(cert_path: Optional[str] = None,
                     key_path: Optional[str] = None):
    """Build a server SSLContext from PEM files; falls back to the
    ``PIO_SSL_CERT``/``PIO_SSL_KEY`` env vars; None when unconfigured
    (the reference's keystore-driven SSLConfiguration, PEM-based)."""
    import os
    import ssl

    cert = cert_path or os.environ.get("PIO_SSL_CERT")
    key = key_path or os.environ.get("PIO_SSL_KEY")
    if not cert:
        if key:
            raise ValueError("SSL key configured without a certificate; "
                             "set both or neither")
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key or None)
    return ctx


Handler = Callable[[Request], Response]


class HTTPApp:
    """Routes ``(method, path-regex) → handler``; first match wins.

    When a :class:`~predictionio_tpu.obs.MetricsRegistry` is mounted
    (:func:`mount_metrics`), every request is timed into a per-route
    latency histogram, counted by status, stamped with a request id, and
    logged as one structured JSON access-log line.
    """

    def __init__(self, name: str = "app"):
        self.name = name
        self._routes: List[Tuple[str, re.Pattern, str, Handler]] = []
        self.metrics = None  # set by mount_metrics
        self._http_hist = None
        self._http_count = None
        self.tracer = None  # set by mount_metrics (obs.trace.Tracer)
        #: probabilistic sampling of the structured access log
        #: (ISSUE 12 satellite): at high qps the per-request
        #: ``json.dumps`` is real money — sample the successes, but
        #: errors and 503s ALWAYS log (they are why the log exists)
        self.access_log_sample = 1.0

    def route(self, method: str, pattern: str) -> Callable[[Handler], Handler]:
        compiled = re.compile(f"^{pattern}$")

        def deco(fn: Handler) -> Handler:
            self._routes.append((method.upper(), compiled, pattern, fn))
            return fn
        return deco

    def enable_metrics(self, registry) -> None:
        """Record per-route request latency/status into ``registry``."""
        self.metrics = registry
        self._http_hist = registry.histogram(
            "pio_http_request_duration_seconds",
            "HTTP request wall time by route")
        self._http_count = registry.counter(
            "pio_http_requests_total",
            "HTTP requests by route, method, and status code")

    def _dispatch(self, req: Request) -> Tuple[Response, str]:
        """Route + run the handler; returns (response, route pattern —
        the bounded-cardinality label, never the raw path)."""
        path_matched = False
        for method, pattern, raw, fn in self._routes:
            m = pattern.match(req.path)
            if m:
                path_matched = True
                if method == req.method:
                    req.path_params = m.groupdict()
                    try:
                        return fn(req), raw
                    except HTTPError as e:
                        return (json_response({"message": e.message},
                                              e.status), raw)
                    except (StorageError, FaultError) as e:
                        # an unavailable backing store is a RETRYABLE
                        # dependency outage, not a server bug: 503 with
                        # Retry-After (and a clean message — never a
                        # traceback body) instead of a bare 500, so
                        # well-behaved clients back off and retry
                        # (ISSUE 11 satellite)
                        resp = json_response(
                            {"message": "backing store unavailable: "
                                        f"{e}"}, 503)
                        resp.headers["Retry-After"] = str(
                            RETRY_AFTER_SECONDS)
                        return resp, raw
                    except Exception as e:  # noqa: BLE001 — server boundary
                        return json_response({"message": str(e)}, 500), raw
        if path_matched:
            return json_response({"message": "Method Not Allowed"},
                                 405), "(method-not-allowed)"
        return json_response({"message": "Not Found"}, 404), "(unmatched)"

    def handle(self, req: Request) -> Response:
        req.request_id = (req.headers.get("X-Request-ID")
                          or secrets.token_hex(8))
        tracer = self.tracer
        if tracer is not None:
            # W3C context propagation (ISSUE 12): continue the caller's
            # trace when a valid ``traceparent`` rides in, else mint a
            # fresh one — tied to X-Request-ID either way
            req.trace = tracer.begin(
                f"{req.method} {req.path}",
                traceparent=req.header("traceparent"),
                request_id=req.request_id, server=self.name)
            req.obs["_trace"] = req.trace
        t0 = time.monotonic()
        resp, route = self._dispatch(req)
        dt = time.monotonic() - t0
        resp.headers.setdefault("X-Request-ID", req.request_id)
        if self.metrics is not None:
            hist = self._http_hist.labels(route=route)
            hist.observe(dt)
            self._http_count.labels(route=route, method=req.method,
                                    status=str(resp.status)).inc()
            if req.trace is not None:
                req.trace.exemplar(hist, dt)
        if req.trace is not None:
            req.trace.set_attr("route", route)
            resp.headers.setdefault("traceparent",
                                    req.trace.traceparent())
            retained, reason = tracer.finish(req.trace,
                                             status=resp.status,
                                             duration=dt)
            if retained:
                resp.headers.setdefault("X-Trace-Retained", reason)
        if access_log.isEnabledFor(logging.INFO) \
                and self._log_this(resp.status):
            line = {"server": self.name, "requestId": req.request_id,
                    "method": req.method, "path": req.path,
                    "status": resp.status,
                    "durationMs": round(dt * 1000, 3)}
            if req.trace is not None:
                line["traceId"] = req.trace.trace_id
            line.update((k, v) for k, v in req.obs.items()
                        if not k.startswith("_"))
            access_log.info(json.dumps(line))
        return resp

    def _log_this(self, status: int) -> bool:
        """Access-log admission: errors/503s always; successes at the
        configured sample rate (``ServerConfig.access_log_sample``)."""
        if status >= 400:
            return True
        sample = self.access_log_sample
        if sample >= 1.0:
            return True
        if sample <= 0.0:
            return False
        return random.random() < sample


class HTTPError(Exception):
    """Raise inside a handler to produce a JSON error response."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


#: content type of the OpenMetrics exposition (the format that can
#: carry exemplars); negotiated via the Accept header on /metrics
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def mount_metrics(app: HTTPApp, registry, server_name: Optional[str] = None,
                  status: Optional[Callable[[], Dict[str, Any]]] = None,
                  runtime: bool = True, tracer=None) -> None:
    """The shared telemetry mount every server goes through:

    - instruments the app's request path (latency histogram, status
      counters, request ids, access log) via :meth:`HTTPApp.enable_metrics`
    - registers the standard runtime series (build info, XLA compiles,
      transfer-guard violations, per-device HBM) and the global
      ``timed(name)`` span registry
    - adds ``GET /metrics`` — Prometheus text format 0.0.4, or
      OpenMetrics 1.0 (with bucket exemplars) when the scraper sends
      ``Accept: application/openmetrics-text``
    - when ``status`` is given, adds ``GET /status.json`` returning its
      dict enriched with the registry snapshot (servers with a bespoke
      status route — the engine server — pass ``status=None`` and
      enrich their own)
    - mounts a request :class:`~predictionio_tpu.obs.trace.Tracer` +
      ``GET /trace.json`` (the flight-recorder read side,
      docs/tracing.md). ``tracer=None`` builds a default one;
      ``tracer=False`` disables tracing for this app.
    """
    from ..obs import Tracer, mount_span_metrics, register_runtime_metrics

    if runtime:
        register_runtime_metrics(registry, server_name or app.name)
        mount_span_metrics(registry)
    app.enable_metrics(registry)
    if tracer is None:
        tracer = Tracer()
    if tracer is not False:
        app.tracer = tracer
        tracer.register_metrics(registry)
        mount_trace_routes(app, tracer)

    # scrape self-cost guard (ISSUE 17 satellite): rendering the
    # exposition is work the server pays PER SCRAPER — an aggregator
    # polling N replicas every 250ms must be able to see (and a
    # regression test bound) what that costs. Sub-ms bounds: a healthy
    # render of a few hundred series is tens of microseconds.
    render_hist = registry.histogram(
        "pio_metrics_render_seconds",
        "Wall time to render one /metrics(.json) exposition, by format",
        bounds=[0.0001 * (2.0 ** i) for i in range(16)])

    @app.route("GET", "/metrics")
    def metrics(req: Request) -> Response:
        # content negotiation (ISSUE 12 satellite): OpenMetrics is
        # required for exemplar rendering; everything else gets the
        # 0.0.4 text format it always got
        accept = req.header("Accept") or ""
        openmetrics = "application/openmetrics-text" in accept
        t0 = time.perf_counter()
        body = registry.render(openmetrics=openmetrics)
        render_hist.labels(
            format="openmetrics" if openmetrics else "text"
        ).observe(time.perf_counter() - t0)
        if openmetrics:
            return Response(body=body,
                            content_type=OPENMETRICS_CONTENT_TYPE)
        return Response(
            body=body,
            content_type="text/plain; version=0.0.4; charset=utf-8")

    @app.route("GET", "/metrics.json")
    def metrics_json(req: Request) -> Response:
        # the fleet-scrape lane (ISSUE 17): full-fidelity JSON with
        # raw cumulative histogram buckets, so the aggregator merges
        # pooled populations instead of averaging percentiles
        t0 = time.perf_counter()
        resp = json_response(registry.export())
        render_hist.labels(format="json").observe(
            time.perf_counter() - t0)
        return resp

    if status is not None:
        @app.route("GET", "/status.json")
        def status_json(req: Request) -> Response:
            return json_response(dict(status(),
                                      metrics=registry.snapshot()))


def mount_trace_routes(app: HTTPApp, tracer) -> None:
    """``GET /trace.json`` — the flight recorder's read side:

    - ``?id=<trace id>`` → that retained trace as Chrome/Perfetto
      trace-event JSON (load it at ui.perfetto.dev)
    - ``?slowest=N`` → summaries of the N slowest retained traces
    - no params → recorder status (counts by reason, ring occupancy,
      live slow threshold, recent retentions)
    """

    @app.route("GET", "/trace.json")
    def trace_json(req: Request) -> Response:
        trace_id = req.query.get("id")
        if trace_id:
            trace = tracer.recorder.get(trace_id)
            if trace is None:
                raise HTTPError(
                    404, f"trace {trace_id!r} is not retained (it was "
                         f"fast and healthy, or has aged out of the "
                         f"ring)")
            return json_response(trace.to_trace_events())
        if "slowest" in req.query:
            try:
                n = int(req.query["slowest"])
            except ValueError:
                raise HTTPError(400, "slowest must be an integer")
            return json_response({
                "traces": [t.summary()
                           for t in tracer.recorder.slowest(n)]})
        return json_response(tracer.status())


class _Handler(BaseHTTPRequestHandler):
    app: HTTPApp  # bound by AppServer
    protocol_version = "HTTP/1.1"
    # response header + body go out in separate writes; without
    # TCP_NODELAY, Nagle + the peer's delayed ACK stalls every
    # keep-alive response ~40ms (measured: host-path p50 10ms → 44ms
    # the moment clients reused connections)
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _dispatch(self) -> None:
        parsed = urlparse(self.path)
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        req = Request(method=self.command, path=parsed.path, query=query,
                      headers={k: v for k, v in self.headers.items()},
                      body=body)
        resp = self.app.handle(req)
        payload = resp.encoded()
        self.send_response(resp.status)
        self.send_header("Content-Type", resp.content_type)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in resp.headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    do_GET = do_POST = do_DELETE = do_PUT = _dispatch


class _AppHTTPServer(ThreadingHTTPServer):
    # listen backlog: the stdlib default (5) resets connections the
    # moment a burst of concurrent clients lands — the serving
    # micro-batcher exists precisely to absorb such bursts
    request_queue_size = 256


class AppServer:
    """Owns a ``ThreadingHTTPServer`` for one :class:`HTTPApp`; start in a
    daemon thread (tests, embedded) or serve on the main thread (CLI)."""

    def __init__(self, app: HTTPApp, host: str = "0.0.0.0", port: int = 0,
                 ssl_context=None):
        handler = type("BoundHandler", (_Handler,), {"app": app})
        self.httpd = _AppHTTPServer((host, port), handler)
        if ssl_context is not None:
            # HTTPS (the reference's JKS SSLConfiguration,
            # common/.../SSLConfiguration.scala:26-58, PEM-based here)
            self.httpd.socket = ssl_context.wrap_socket(
                self.httpd.socket, server_side=True)
        self.app = app
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start_background(self) -> "AppServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name=f"{self.app.name}-http",
            daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
