"""Plugin hooks for the event and engine servers.

Capability parity with the reference plugin system
(``workflow/EngineServerPlugin.scala:24-41``,
``data/api/EventServerPlugin.scala:21-34``, loaded via ``ServiceLoader``):
input/output *blockers* run synchronously (raising aborts the request),
input/output *sniffers* observe asynchronously. Discovery here is an
explicit ``register`` call (or ``predictionio_tpu.plugins`` entry points)
instead of classpath scanning.
"""

from __future__ import annotations

import abc
import logging
import queue
import threading
from typing import Any, Dict, List, Optional

from ..data.event import Event

log = logging.getLogger(__name__)


class EventServerPlugin(abc.ABC):
    """Event-side hook (``data/api/EventServerPlugin.scala:21-34``)."""

    plugin_name: str = ""
    plugin_description: str = ""

    @abc.abstractmethod
    def process(self, app_id: int, channel_id: Optional[int],
                event: Event) -> None:
        ...

    def handle_rest(self, app_id: int, channel_id: Optional[int],
                    args: List[str]) -> Any:
        return {}


class EngineServerPlugin(abc.ABC):
    """Engine-side hook (``workflow/EngineServerPlugin.scala:24-41``):
    ``process`` sees (query, prediction) and may transform the prediction
    (blockers) or merely observe (sniffers)."""

    plugin_name: str = ""
    plugin_description: str = ""

    @abc.abstractmethod
    def process(self, query: Any, prediction: Any) -> Any:
        ...

    def handle_rest(self, args: List[str]) -> Any:
        return {}


class _SnifferPump:
    """Async fan-out to sniffers (the reference's plugin actors).

    Sniffers observe; they must never apply backpressure to the ingest
    or serve path — so the queue is bounded and overload DROPS the
    oldest-unserved observation (counted) instead of growing without
    limit or blocking the caller. ``close()`` drains to a sentinel and
    joins the pump thread, so a server stop→start cycle leaks nothing."""

    _STOP = object()

    def __init__(self, maxsize: int = 1024):
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.dropped = 0

    def _ensure(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="plugin-sniffers")
                self._thread.start()

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is self._STOP:
                return
            try:
                fn()
            except Exception:
                log.exception("sniffer plugin failed")

    def submit(self, fn) -> None:
        self._ensure()
        try:
            self._q.put_nowait(fn)
        except queue.Full:
            # observers lose a sample under overload; the hot path
            # never blocks on them
            self.dropped += 1

    def close(self, timeout: float = 5.0) -> None:
        """Stop the pump thread after the queued work drains."""
        with self._lock:
            t = self._thread
            self._thread = None
        if t is None or not t.is_alive():
            return
        self._q.put(self._STOP)
        t.join(timeout=timeout)


class EventServerPlugins:
    def __init__(self):
        self.input_blockers: Dict[str, EventServerPlugin] = {}
        self.input_sniffers: Dict[str, EventServerPlugin] = {}
        self._pump = _SnifferPump()

    def register(self, plugin: EventServerPlugin, *, blocker: bool) -> None:
        target = self.input_blockers if blocker else self.input_sniffers
        target[plugin.plugin_name or type(plugin).__name__] = plugin

    def process_input(self, app_id: int, channel_id: Optional[int],
                      event: Event) -> None:
        for p in self.input_blockers.values():
            p.process(app_id, channel_id, event)
        for p in self.input_sniffers.values():
            self._pump.submit(
                lambda p=p: p.process(app_id, channel_id, event))

    def describe(self) -> dict:
        def one(plugins: Dict[str, EventServerPlugin]) -> dict:
            return {name: {"name": p.plugin_name,
                           "description": p.plugin_description,
                           "class": type(p).__qualname__}
                    for name, p in plugins.items()}
        return {"inputblockers": one(self.input_blockers),
                "inputsniffers": one(self.input_sniffers)}

    def close(self) -> None:
        self._pump.close()


class EngineServerPlugins:
    def __init__(self):
        self.output_blockers: Dict[str, EngineServerPlugin] = {}
        self.output_sniffers: Dict[str, EngineServerPlugin] = {}
        self._pump = _SnifferPump()

    def register(self, plugin: EngineServerPlugin, *, blocker: bool) -> None:
        target = self.output_blockers if blocker else self.output_sniffers
        target[plugin.plugin_name or type(plugin).__name__] = plugin

    def process_output(self, query: Any, prediction: Any) -> Any:
        for p in self.output_blockers.values():
            prediction = p.process(query, prediction)
        for p in self.output_sniffers.values():
            self._pump.submit(lambda p=p: p.process(query, prediction))
        return prediction

    def describe(self) -> dict:
        def one(plugins: Dict[str, EngineServerPlugin]) -> dict:
            return {name: {"name": p.plugin_name,
                           "description": p.plugin_description,
                           "class": type(p).__qualname__}
                    for name, p in plugins.items()}
        return {"outputblockers": one(self.output_blockers),
                "outputsniffers": one(self.output_sniffers)}

    def close(self) -> None:
        self._pump.close()


def resolve_plugin(registry_map, ptype: str, pname: str, rest: str):
    """Shared ``/plugins/<type>/<name>/<args…>`` dispatch for the engine
    and event servers: returns (plugin, args) or raises the appropriate
    404 ``HTTPError``."""
    from .http import HTTPError

    plugins = registry_map.get(ptype)
    if plugins is None:
        raise HTTPError(404, f"unknown plugin type {ptype!r}")
    plugin = plugins.get(pname)
    if plugin is None:
        raise HTTPError(404, f"plugin {pname!r} not registered")
    return plugin, [seg for seg in rest.split("/") if seg]
