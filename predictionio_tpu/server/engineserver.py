"""Engine server: deployed-model query serving.

Capability parity with the reference engine server
(``workflow/CreateServer.scala:109-705``): ``POST /queries.json`` runs
supplement → per-algorithm predict → serve (:484-633, serving called with
the *original* query by design :506-513), the feedback loop posts
``predict`` events with a generated ``prId`` back to the event store
(:527-589), ``/reload`` rebinds to the latest COMPLETED engine instance
(``MasterActor`` :342-371), ``/stop`` shuts down, ``GET /`` renders a
status page with per-request bookkeeping (:415-417,597-604), and output
plugins transform/observe every prediction (:591-595).

The TPU-minded difference: models stay resident in HBM and ``predict`` is
expected to be a thin host wrapper over jitted device code, so the serving
hot path never recompiles.
"""

from __future__ import annotations

import html
import json
import logging
import secrets
import threading
import time
from dataclasses import dataclass
from typing import Any, List, Optional

from ..concurrency import (
    instrument_locks,
    locks_instrumented,
    new_lock,
    new_rlock,
    register_lock_metrics,
)
from ..controller.context import Context
from ..controller.engine import Engine
from ..controller.params import EngineParams
from ..data.event import Event, utcnow
from ..data.storage.base import STATUS_COMPLETED, EngineInstance
from ..faults import declare, fire
from ..faults import registry as fault_registry
from ..utils.retrying import RetryPolicy, backoff_delays
from ..obs import (
    DEFAULT_LATENCY_BOUNDS,
    POW2_COUNT_BOUNDS,
    MetricsRegistry,
    OverlapTracker,
    hbm_stats,
)
from ..obs import numerics as numerics_sentinel
from ..obs.trace import (
    activate_traces,
    add_stage_spans,
    mark_active_traces,
)
from ..rollout.registry import ReleaseRegistry
from ..rollout.splitter import ARM_CANDIDATE, ARM_STABLE
from ..utils.jsonutil import from_jsonable, to_jsonable
from .http import (
    AppServer,
    HTTPApp,
    HTTPError,
    Request,
    Response,
    json_response,
    make_key_auth,
    mount_metrics,
)
from .plugins import EngineServerPlugins

log = logging.getLogger(__name__)

F_LANE = declare("serving.lane",
                 "one micro-batch dispatch on a replicated serving "
                 "lane (lane= labels the device ordinal) — injecting "
                 "here simulates a dead device/lane")
F_LANE_RESTART = declare("serving.lane_restart",
                         "a lane-restart probe (lane=): injecting here "
                         "keeps a dead lane down")
F_DISPATCH = declare("serving.dispatch",
                     "one batched device dispatch (any serving mode)")


def pick_live_lane(lane: int, n_lanes: int, dead) -> int:
    """Route traffic for ``lane`` to a surviving lane: identity while
    healthy; a dead lane's batches redistribute deterministically
    across the survivors (round-robin by ordinal). With every lane
    dead there is nothing better than the original."""
    if n_lanes <= 0 or lane not in dead:
        return lane
    alive = [i for i in range(n_lanes) if i not in dead]
    if not alive:
        return lane
    return alive[lane % len(alive)]


def _gen_pr_id() -> str:
    """64-char alphanumeric prediction id (``CreateServer.scala:535``)."""
    return secrets.token_hex(32)


@dataclass
class ServerConfig:
    """Knobs of the reference's ``ServerConfig``
    (``CreateServer.scala:78-96``)."""

    feedback: bool = False
    #: App receiving feedback events (required when ``feedback``).
    feedback_app_name: Optional[str] = None
    accesskey: Optional[str] = None  # require ?accessKey= on control routes
    #: Coalesce concurrent queries into one ``batch_predict`` device
    #: dispatch (SURVEY hard part 3 — the reference served strictly
    #: per-request, ``CreateServer.scala:507-510`` "TODO: Parallelize").
    batching: bool = False
    batch_window_ms: float = 2.0   # max wait for a batch to fill
    #: measured sweet spot at 256-way burst on a tunneled v5e (the
    #: bench battery's winning config; `ptpu deploy --max-batch`
    #: shares this default)
    max_batch: int = 128
    #: Concurrent batch dispatches in flight. Through a remote-device
    #: tunnel the dispatch round trip (~80-170ms) dwarfs device compute;
    #: one drainer leaves the link idle while a batch is in flight
    #: (measured: 1 drainer = 258 qps, per-query with 64 HTTP threads =
    #: 335 qps because the tunnel pipelines independent RPCs). Several
    #: drainers pipeline batches the same way. Serial mode: the drainer
    #: thread count; staged mode: the single-binding dispatch-thread
    #: count (enqueue concurrency — in-flight batches are bounded by
    #: ``pipeline_depth``, not this).
    batch_pipeline: int = 4
    #: Serving batch-path architecture (ISSUE 9,
    #: docs/serving-pipeline.md). "staged": the continuous-batching
    #: pipeline — assemble (host pool parses/validates/supplements the
    #: next batch while the device is busy), dispatch (one thread per
    #: lane ENQUEUES executables via JAX async dispatch, never blocking
    #: on results), readback (host pool blocks on device arrays, runs
    #: serve/to_jsonable/feedback and wakes callers), with bounded
    #: hand-off queues between stages. "serial": the pre-ISSUE-9
    #: drainer threads, each doing everything for its own batch — kept
    #: for A/B benches and as the conservative fallback.
    serving_pipeline: str = "staged"
    #: Per-query deadline (ms) covering queue wait through readback: a
    #: submit unanswered by then returns 503 and its queue entry is
    #: shed (``pio_query_deadline_exceeded_total`` counts them), so a
    #: wedged dispatch degrades into fast 503s instead of hanging every
    #: HTTP worker forever. 0 disables (the pre-ISSUE-9 behavior).
    queue_deadline_ms: float = 30_000.0
    #: staged pipeline: host threads forming/parsing/supplementing
    #: batches (the assemble stage). One is plenty for fast
    #: supplements (forming a batch costs ~0.3ms); raise it for
    #: templates whose supplement does event-store reads — more
    #: workers split the arrival stream into SMALLER batches, which
    #: costs device efficiency (measured: 2 workers dropped mean
    #: occupancy 16 → 9 at 24-thread burst).
    assemble_workers: int = 1
    #: staged pipeline: host threads blocking on device results and
    #: serializing/feedback (the readback stage). Sized to the
    #: in-flight depth: each worker parks on one batch's readback
    #: while the device runs later batches.
    readback_workers: int = 4
    #: staged pipeline: bounded in-flight (dispatched-but-unresolved)
    #: batches per lane — the knob that trades batch size against
    #: latency hiding. 0 = auto: 1 where the "device" shares the host
    #: cores (CPU — nothing to hide; maximum occupancy wins, measured
    #: 1.6× the serial drainer), 4 on real accelerators (the readback
    #: round trip through a device tunnel is 80-170ms and must be
    #: pipelined, exactly like the serial drainer's 4 concurrent
    #: dispatches — but with fatter batches and host work off the
    #: critical path). While the pipeline is full, arrivals pool in
    #: the submit queue (where the deadline sheds them) and the next
    #: pickup coalesces the backlog into one fat batch.
    pipeline_depth: int = 0
    #: POST query errors to this URL (``remoteLog``,
    #: ``CreateServer.scala:435-446``); never fails the query.
    log_url: Optional[str] = None
    log_prefix: str = ""
    #: Compile the serving device kernels for every batch size the
    #: micro-batcher can produce (the pow2 ladder) BEFORE traffic hits
    #: them. Each novel shape is a fresh XLA compile — measured 6-20s
    #: through a device tunnel, which is exactly the round-4 microbatch
    #: p90/p99 pathology. Runs in a background thread; ``/status.json``
    #: exposes ``servingWarm``.
    warm_start: bool = True
    #: ``jax.transfer_guard`` level wrapped around the post-warmup query
    #: path — the runtime complement of ``ptpu check``'s
    #: host-sync-in-hot-path lint. "log" surfaces every implicit
    #: device↔host transfer a query triggers; "disallow" turns them into
    #: errors (canary deployments); "allow"/"off"/None disables. Applied
    #: only once warmup is done: warmup itself legitimately transfers.
    transfer_guard: Optional[str] = "log"
    #: Serving cache hierarchy (ISSUE 4): an exact-key query-result
    #: cache consulted BEFORE the micro-batcher (hot queries skip
    #: supplement/dispatch entirely, singleflight dedups concurrent
    #: identical misses), a feature cache for serving-time event-store
    #: reads, and a device-resident hot-entity tier — all invalidated
    #: by the event server's ingest bus and flushed on every rebind.
    #: Off by default: turning result caching on is a staleness
    #: decision the operator must make (see docs/serving-cache.md).
    serving_cache: bool = False
    cache_entries: int = 8192          # query-tier LRU capacity
    #: query-result staleness BOUND: the bus usually invalidates far
    #: sooner; this TTL is the ceiling when ingest happens in another
    #: process (no in-process bus delivery)
    cache_ttl_sec: float = 30.0
    feature_cache_entries: int = 8192
    feature_ttl_sec: float = 5.0       # event-store read staleness bound
    #: hottest entities whose factor rows stay pinned on device
    #: (0 disables the tier)
    hot_entities: int = 512
    hot_refresh_every: int = 256       # re-rank/re-pin cadence (serves)
    #: Instrument every lock in the serving stack with the
    #: concurrency package's DebugLock: live lock-order-inversion and
    #: re-entry detection, pio_lock_* wait/hold/contention series, and
    #: a deadlock watchdog that dumps all thread stacks to the access
    #: log when a wait exceeds PTPU_LOCK_WATCHDOG_SEC. Off by default:
    #: disabled means plain threading locks — zero overhead. The
    #: PTPU_DEBUG_LOCKS=1 env var enables it without a config change
    #: (the staging runbook path, docs/operations.md).
    debug_locks: bool = False
    #: Runtime NaN/Inf sentinels on the numeric serving stack
    #: (docs/observability.md): streaming fold-in solves run
    #: checkify-wrapped (device-side nonfinite detection before a
    #: hot-swap can poison the serving table) and serving top-k
    #: scores get a host NaN probe, feeding the
    #: pio_numerics_checks_total / pio_numerics_nonfinite_total
    #: counters and the ``nonfinite`` flag of /status.json's degraded
    #: block. Off by default: the instrumented sites are one bool
    #: check — zero overhead (the fault-registry pattern). The
    #: PTPU_DEBUG_NUMERICS=1 env var enables it without a config
    #: change.
    debug_numerics: bool = False
    #: Row-quantized serving factor tables (ISSUE 13,
    #: docs/kernels.md): "int8" stores per-row-scaled int8 factors
    #: (~4x more users per HBM, ~4x less bandwidth per scored batch),
    #: "bf16" halves both — dequantized on the fly with f32
    #: accumulation (Tensor-Casting precision co-design). Guarded by a
    #: deploy-time NDCG@10 parity probe that auto-falls-back to f32
    #: when the model's rank/scale cannot take the quantization, so
    #: the knob can never silently degrade ranking. "off" serves f32.
    serving_quant: str = "off"
    #: Batched-lane top-k realization: "fused" = the Pallas
    #: gather→score→top-k kernel (ops/fused_topk.py — the [B, I]
    #: score matrix never lands in HBM), "einsum" = the XLA matmul +
    #: top_k baseline, "auto" = the persistent autotune table
    #: (gram_autotune.best_topk_mode), support-gated so "fused" never
    #: resolves where the kernel cannot lower. An explicit "fused" on
    #: a CPU host runs the interpret-mode kernel (a debugging/A-B
    #: configuration, mirroring gram_mode="fused").
    serving_topk: str = "auto"
    #: Mesh-wide serving (ISSUE 6, docs/sharded-serving.md):
    #: "single" — today's one-device path; "replicated" — a full model
    #: copy per device, the micro-batcher fans micro-batches out
    #: round-robin across per-device lanes (~N× qps on N chips, no
    #: cross-device sync on the serve path); "sharded" — factor tables
    #: row-sharded over the (batch, model) mesh via NamedSharding
    #: (models bigger than one HBM; GSPMD resolves the gathers);
    #: "auto" — sharded when the model's resident bytes exceed the
    #: per-device HBM headroom, else replicated on >1 device.
    serving_mode: str = "single"
    #: Streaming incremental training (ISSUE 10, docs/streaming.md):
    #: start a :class:`~predictionio_tpu.streaming.StreamTrainer` with
    #: the deploy — it tails ``stream_app_name``'s event log behind a
    #: durable cursor, folds fresh events into the bound ALS model via
    #: per-entity least-squares solves, canaries each delta, and
    #: hot-swaps the updated rows into this serving binding. Off by
    #: default; ``ptpu stream start`` attaches one to a live server.
    streaming: bool = False
    #: App whose event log the trainer tails (required when
    #: ``streaming``; falls back to ``feedback_app_name``).
    stream_app_name: Optional[str] = None
    #: Poll fallback between fold-in passes; in-process ingest wakes
    #: the trainer immediately through the invalidation bus.
    stream_interval_ms: float = 500.0
    stream_max_events: int = 2048      # events per fold-in micro-batch
    #: durable cursor identity (two trainers sharing a consumer name
    #: fight over one cursor)
    stream_consumer: str = "stream-trainer"
    stream_drift_threshold: float = 1.0  # DriftMonitor retrain trigger
    #: touched-entity probes per fold-in canary check (0 disables)
    stream_canary_probes: int = 8
    #: Fault injection (ISSUE 11, docs/reliability.md): a
    #: ``PTPU_FAULTS``-grammar spec string armed into the process-wide
    #: fault registry at server construction, so failure drills script
    #: real storage/lane/dispatch faults against a deployed server
    #: (``ptpu deploy --faults``). None = nothing armed (the env var
    #: still works).
    faults: Optional[str] = None
    #: End-to-end request tracing (ISSUE 12, docs/tracing.md): every
    #: request is traced into the tail-sampled flight recorder — only
    #: slow (adaptive p99) / errored / deadline-503'd / fault-injected
    #: traces are retained, served as Perfetto JSON on
    #: ``GET /trace.json``. On by default: the per-request cost is a
    #: handful of allocations (measured ≤5% on the host fast path);
    #: off for A/B benches of that overhead.
    tracing: bool = True
    #: retained traces the flight-recorder ring holds (oldest evicted)
    trace_ring: int = 512
    #: fixed slow-retention threshold in ms; 0 = adaptive (the live
    #: p99 of traced request durations)
    trace_slow_ms: float = 0.0
    #: probabilistic sampling of the structured JSON access log: 1.0
    #: logs every request (the historical behavior), 0.01 logs ~1% —
    #: errors and 503s ALWAYS log regardless. High-qps serving should
    #: not pay a json.dumps per healthy request (ISSUE 12 satellite).
    access_log_sample: float = 1.0
    #: artifact directory for on-demand ``POST /profile`` device
    #: captures (None: $PTPU_PROFILE_DIR, else <tmp>/ptpu-profiles)
    profile_dir: Optional[str] = None
    #: Hot-key telemetry (ISSUE 17, docs/fleet.md): capacity of the
    #: Space-Saving heavy-hitter sketch fed by the query path's entity
    #: ids — every key hotter than 1/k of traffic is guaranteed
    #: monitored. Exported as ``pio_hot_keys{rank,key}`` and the
    #: ``hotKeys`` block of /status.json (which the fleet aggregator
    #: merges); the signal entity-affinity routing will consume.
    #: 0 disables the sketch entirely.
    hot_keys_k: int = 128
    #: SLO engine (ISSUE 15, docs/slo.md): declarative service
    #: objectives evaluated continuously against this server's live
    #: metric registry via multi-window error-budget burn rates
    #: (pio_slo_* series, /slo.json, an slo block on /status.json).
    #: None = the built-in default specs (availability + latency on
    #: /queries.json, freshness while streaming); a path loads a
    #: committed spec file (slo/specs/*.json). Breach transitions
    #: force-retain flight-recorder traces for the duration of the
    #: burn, so every violation arrives with exemplar evidence.
    slo_specs: Optional[str] = None
    #: evaluation tick; 0 disables the SLO engine entirely
    slo_interval_ms: float = 1000.0
    #: consecutive failed dispatches on one replicated lane before the
    #: lane is declared dead and its traffic redistributed across the
    #: surviving lanes (degraded mode — pio_serving_degraded)
    lane_fail_threshold: int = 3
    #: lane-restart probe schedule: bounded exponential backoff from
    #: this base, capped at 32x — a dead lane is probed (restart =
    #: fault-point probe + per-device model re-replication) until it
    #: comes back or the attempt budget is spent
    lane_restart_backoff_ms: float = 100.0
    lane_restart_max_attempts: int = 8
    #: Warm-from-artifact deploy (ISSUE 19, docs/cold-start.md): root
    #: of the AOT artifact store ``ptpu build --aot`` wrote. When set,
    #: ``_warm_serving`` becomes artifact-load-then-verify — serving
    #: executables deserialize in milliseconds instead of compiling —
    #: with automatic fallback to compiling on any key mismatch,
    #: missing build, or corrupt entry. None keeps the compile warm.
    artifact_dir: Optional[str] = None


@dataclass
class CandidateBinding:
    """A candidate release bound ALONGSIDE the stable one: its own
    algorithms/models/serving so the two arms never share mutable
    state. ``raw_models`` keep the as-loaded blobs — promotion rebinds
    through the normal ``_bind`` path so the stable batch budget (and
    its device placement) is re-derived, not inherited from the
    candidate's batch-1 serving."""

    engine_params: EngineParams
    algorithms: List[Any]
    models: List[Any]
    raw_models: List[Any]
    serving: Any
    instance: EngineInstance
    warm_done: threading.Event


class QueryServer:
    """One deployed engine: algorithms + live models + serving logic."""

    def __init__(self, ctx: Context, engine: Engine,
                 engine_params: EngineParams, models: List[Any],
                 instance: EngineInstance,
                 config: Optional[ServerConfig] = None,
                 plugins: Optional[EngineServerPlugins] = None):
        self.ctx = ctx
        self.engine = engine
        self.config = config or ServerConfig()
        if self.config.feedback:
            # fail fast at deploy rather than logging per query
            app_name = self.config.feedback_app_name
            if not app_name:
                raise ValueError(
                    "feedback=True requires feedback_app_name")
            if ctx.storage.apps().get_by_name(app_name) is None:
                raise ValueError(
                    f"feedback app {app_name!r} does not exist")
        self.plugins = plugins or EngineServerPlugins()
        if self.config.faults:
            # failure drills (ISSUE 11): arm the requested injections
            # BEFORE anything that might be their target exists
            from ..faults import inject_spec

            inject_spec(self.config.faults)
        if self.config.debug_locks and not locks_instrumented():
            # flip the factories BEFORE any serving-stack lock exists
            # so the cache/rollout/batcher locks built below are all
            # DebugLocks feeding one process order graph
            instrument_locks(True)
        if self.config.debug_numerics or numerics_sentinel.debug_env():
            # arm the NaN/Inf sentinels BEFORE the bind so warmup
            # fold-ins and probe serves are covered too
            numerics_sentinel.enable()
        self._lock = new_rlock("QueryServer._lock")
        # serving cache hierarchy (ISSUE 4): built BEFORE the first
        # _bind so the bind can wire the feature tier into algorithms
        self.cache = self._make_cache()
        self._bind(engine_params, models, instance)
        # bookkeeping (CreateServer.scala:415-417)
        self.start_time = utcnow()
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0
        # telemetry (ISSUE 2): the engine server's metric registry —
        # per-phase query-path histograms plus the batcher's occupancy
        # and queue-depth series. QueryServer owns it so direct query()
        # callers (tests, batch jobs) record the same series HTTP
        # traffic does; build_app mounts it on /metrics.
        self.metrics = MetricsRegistry()
        self._phase_hist = self.metrics.histogram(
            "pio_query_phase_seconds",
            "Per-phase query-path wall time (queue_wait, assemble, "
            "supplement, dispatch, serve, readback, feedback)",
            bounds=DEFAULT_LATENCY_BOUNDS)
        self._latency_hist = self.metrics.histogram(
            "pio_query_latency_seconds",
            "End-to-end serving wall time per query",
            bounds=DEFAULT_LATENCY_BOUNDS)
        self._batch_occupancy = self.metrics.histogram(
            "pio_batch_occupancy",
            "Queries coalesced per micro-batch dispatch",
            bounds=POW2_COUNT_BOUNDS)
        self._queue_depth = self.metrics.histogram(
            "pio_queue_depth",
            "Batcher queue depth observed at each batch pickup",
            bounds=POW2_COUNT_BOUNDS)
        self._query_errors = self.metrics.counter(
            "pio_query_errors_total", "Failed queries by status class")
        # staged serving pipeline series (ISSUE 9,
        # docs/serving-pipeline.md): per-stage wall time, inter-stage
        # queue depths, deadline sheds, and the overlap accounting that
        # PROVES the device computes while host stages run
        self._pipeline_stage_hist = self.metrics.histogram(
            "pio_pipeline_stage_seconds",
            "Per-batch wall time of each staged-pipeline stage "
            "(assemble = parse+supplement, dispatch = device enqueue, "
            "readback = device wait + serve + serialize + feedback)",
            bounds=DEFAULT_LATENCY_BOUNDS)
        self._pipeline_qdepth = self.metrics.histogram(
            "pio_pipeline_queue_depth",
            "Queue depth observed at each pipeline stage pickup "
            "(queue=submit|dispatch|readback)",
            bounds=POW2_COUNT_BOUNDS)
        self._deadline_exceeded = self.metrics.counter(
            "pio_query_deadline_exceeded_total",
            "Queries shed with 503 after exceeding "
            "ServerConfig.queue_deadline_ms — load shedding under a "
            "wedged or saturated dispatch, never silent hangs")
        self._pipeline_overlapped = self.metrics.counter(
            "pio_pipeline_overlapped_dispatches_total",
            "Batch launches that found an earlier batch still in "
            "flight on the device — direct evidence of stage overlap")
        self.overlap = OverlapTracker()
        self.metrics.gauge(
            "pio_pipeline_device_idle_fraction",
            "Fraction of wall time (since first batch) with NO batch "
            "in flight on the device; the staged pipeline under load "
            "should drive this toward 0",
            fn=self.overlap.device_idle_fraction)
        self.metrics.gauge(
            "pio_pipeline_overlap_fraction",
            "Fraction of wall time where the device was busy WHILE an "
            "assemble/readback host stage ran — the overlap the staged "
            "pipeline exists to create (a serial drainer reads ~0)",
            fn=self.overlap.overlap_fraction)
        # mesh-wide serving series (ISSUE 6): per-device lane depth /
        # latency / dispatch counts while replicated fan-out is active,
        # plus the resolved mode as a render-time gauge
        self._lane_latency = self.metrics.histogram(
            "pio_lane_batch_seconds",
            "Per-lane micro-batch wall time (replicated fan-out; lane "
            "label = device ordinal)",
            bounds=DEFAULT_LATENCY_BOUNDS)
        self._lane_depth = self.metrics.histogram(
            "pio_lane_queue_depth",
            "Batcher queue depth observed at each lane's batch pickup",
            bounds=POW2_COUNT_BOUNDS)
        self._lane_dispatches = self.metrics.counter(
            "pio_lane_dispatches_total",
            "Micro-batches dispatched per serving lane")
        self.metrics.gauge(
            "pio_serving_lanes",
            "Per-device serving lanes active (0 = single/sharded "
            "binding)",
            fn=lambda: float(len(self.lane_models)))
        # graceful degradation (ISSUE 11, docs/reliability.md): lane
        # supervision state + the telemetry that makes a dead lane an
        # alert instead of a mystery latency cliff. _lane_health guards
        # the dead-set and failure streaks; the binding lock is NOT
        # reused here because lane death is detected on the dispatch
        # hot path.
        self._lane_health = new_lock("QueryServer._lane_health")
        self._dead_lanes: dict = {}        # lane → {"since", "reason"}
        self._lane_streaks: dict = {}      # lane → consecutive failures
        self._lane_restarts = self.metrics.counter(
            "pio_lane_restarts_total",
            "Successful restarts of a dead serving lane, by lane")
        self._lane_failures = self.metrics.counter(
            "pio_lane_failures_total",
            "Failed micro-batch dispatches per serving lane (the "
            "streak that crosses lane_fail_threshold kills the lane)")
        self.metrics.gauge(
            "pio_serving_degraded",
            "1 while one or more replicated serving lanes are dead "
            "and their traffic is redistributed across survivors",
            fn=lambda: 1.0 if self._dead_lanes else 0.0)
        # end-to-end tracing (ISSUE 12, docs/tracing.md): the server
        # owns the tracer (like the registry) so direct query() callers
        # trace the same way HTTP traffic does; build_app mounts it on
        # the request path + /trace.json. The profiler backs
        # POST /profile (bounded-window jax.profiler captures).
        from ..obs.trace import DeviceProfiler, Tracer
        self.tracer = (Tracer(ring=self.config.trace_ring,
                              slow_ms=self.config.trace_slow_ms)
                       if self.config.tracing else None)
        self.profiler = DeviceProfiler(self.config.profile_dir)
        # hot-key telemetry (ISSUE 17): a Space-Saving sketch over the
        # query path's entity ids — exported per replica as
        # pio_hot_keys{rank,key} and merged fleet-wide by the
        # aggregator. O(k) per record, k bounded by config.
        from ..obs.hotkeys import SpaceSaving, mount_hot_key_metrics
        self.hotkeys: Optional[SpaceSaving] = None
        if self.config.hot_keys_k > 0:
            self.hotkeys = SpaceSaving(capacity=self.config.hot_keys_k)
            mount_hot_key_metrics(self.metrics, self.hotkeys)
        # fault-injection observability: injections delivered anywhere
        # in this process, attributed by point and mode — and flagged
        # onto whatever traces the injected thread was working on, so
        # a fault-injected request is retained by the flight recorder
        self._fault_injections = self.metrics.counter(
            "pio_fault_injections_total",
            "Fault-registry injections delivered, by point and mode "
            "(drills only; 0 in production)")

        def _on_fault(point: str, mode: str) -> None:
            self._fault_injections.labels(point=point, mode=mode).inc()
            mark_active_traces("fault", faultPoint=point,
                               faultMode=mode)

        fault_registry().add_listener(_on_fault)
        self.metrics.gauge(
            "pio_fault_enabled",
            "1 while any fault-injection spec is armed in this process",
            fn=lambda: 1.0 if fault_registry().enabled() else 0.0)
        # numeric-sentinel observability (debug_numerics /
        # PTPU_DEBUG_NUMERICS=1): checks delivered anywhere in this
        # process, attributed by entry point; any nonfinite sample
        # also raises the `nonfinite` flag in /status.json's degraded
        # block
        self._numerics_checks = self.metrics.counter(
            "pio_numerics_checks_total",
            "Numeric-sentinel NaN/Inf checks delivered, by entry "
            "point (debug_numerics only; absent in production)")
        self._numerics_nonfinite = self.metrics.counter(
            "pio_numerics_nonfinite_total",
            "Numeric-sentinel checks that observed NaN/Inf, by entry "
            "point — nonzero flags nonfinite in /status.json")

        def _on_numerics(entry: str, bad: bool) -> None:
            self._numerics_checks.labels(entry=entry).inc()
            if bad:
                self._numerics_nonfinite.labels(entry=entry).inc()

        if numerics_sentinel.active():
            self._numerics_listener = _on_numerics
            numerics_sentinel.add_listener(_on_numerics)
        else:
            self._numerics_listener = None
        # progressive delivery (ISSUE 3): per-release-arm series the
        # rollout health gate windows over, the release registry this
        # server's deploy/reload/promote/rollback actions are recorded
        # in, and the (at most one) live candidate binding + controller
        self._release_queries = self.metrics.counter(
            "pio_release_queries_total",
            "Queries served per release arm while a rollout is live")
        self._release_errors = self.metrics.counter(
            "pio_release_query_errors_total",
            "Server-side (5xx) query failures per release arm while a "
            "rollout is live")
        self._release_latency = self.metrics.histogram(
            "pio_release_latency_seconds",
            "End-to-end serving wall time per release arm while a "
            "rollout is live",
            bounds=DEFAULT_LATENCY_BOUNDS)
        self._shadow_mirrors = self.metrics.counter(
            "pio_release_shadow_mirrors_total",
            "Queries mirrored to a shadow candidate")
        self.releases = ReleaseRegistry(
            ctx.storage, instance.engine_id, instance.engine_version,
            instance.engine_variant)
        self.rollout = None  # the live RolloutController, if any
        self._candidate: Optional[CandidateBinding] = None
        self._algo_pool = None    # parallel per-algorithm dispatch
        self._mirror_pool = None  # shadow mirrors (separate pool: a
        # mirror runs query_candidate, which dispatches into the algo
        # pool — sharing one pool could deadlock at saturation
        # recompile sentinel: armed when warmup finishes, so every
        # compile after that is a query paying a trace it shouldn't
        # (the runtime half of ptpu check's recompile-hazard lint)
        from .stats import RecompileSentinel
        self.recompile_sentinel = RecompileSentinel()
        self.warm_done = threading.Event()
        # lifecycle advertisement (ISSUE 18): the router's lifecycle
        # manager flips this via POST /drain; the fleet aggregator
        # reads the resulting /status.json "lifecycle" field so a
        # draining replica leaves rollups + the headroom denominator
        # without an up-flap when its scrapes finally stop
        self.drain_started = threading.Event()
        self.metrics.gauge(
            "pio_compiles_since_warm",
            "XLA compiles after serving warmup finished — every one is "
            "traffic paying a trace it should not",
            fn=lambda: self.recompile_sentinel.since_armed)
        self.metrics.gauge(
            "pio_serving_warm",
            "1 once the serving shapes are pre-compiled",
            fn=lambda: 1.0 if self.warm_done.is_set() else 0.0)
        # warm-time telemetry (ISSUE 19): where warm time actually went
        # — artifact-store open + executable deserialize ("load"), the
        # lane-0 warm ladder net of loads ("compile"), lanes 1..N-1
        # ("replicate"), and the post-warm verify pass ("probe")
        self._warmup_seconds = self.metrics.histogram(
            "pio_warmup_seconds",
            "Serving warm-up wall time by phase "
            "(phase=load|compile|replicate|probe); an artifact warm "
            "puts its mass in load, a cold warm in compile",
            bounds=[0.01, 0.05, 0.25, 1.0, 2.0, 5.0, 15.0, 30.0, 60.0])
        #: warm provenance for /status.json: set by _warm_serving once
        #: per generation ({"artifact": bool, "seconds": {...}, ...})
        self._warm_report: dict = {}
        # the initial _bind ran before this registry existed; record
        # the resolved gram + serving-kernel modes now (rebinds
        # re-record inside _bind)
        self._record_gram_mode()
        self._record_serving_kernel()
        self._record_sharding_findings()
        if self.cache is not None:
            self.cache.register_metrics(self.metrics)
        if locks_instrumented():
            register_lock_metrics(self.metrics)
        # the batcher lives on the server (not build_app) so the cached
        # serve() path and direct embedders share one batcher.
        # Replicated mode implies it: the dispatch threads ARE the
        # per-device lanes (fan-out), so a replicated binding without
        # --batching still gets its N lanes. serving_pipeline picks the
        # architecture: the staged continuous-batching pipeline
        # (ISSUE 9) or the pre-ISSUE-9 serial drainers.
        if self.config.serving_pipeline not in ("staged", "serial"):
            raise ValueError(
                f"serving_pipeline must be 'staged' or 'serial', got "
                f"{self.config.serving_pipeline!r}")
        lanes = len(self.lane_models) or 1
        if self.config.batching or lanes > 1:
            if self.config.serving_pipeline == "staged":
                self.batcher = StagedPipeline(
                    self, self.config.batch_window_ms,
                    self.config.max_batch, lanes=lanes,
                    assemble_workers=self.config.assemble_workers,
                    readback_workers=self.config.readback_workers,
                    depth=self.config.pipeline_depth,
                    deadline_ms=self.config.queue_deadline_ms,
                    dispatch_workers=self.config.batch_pipeline)
            else:
                self.batcher = MicroBatcher(
                    self, self.config.batch_window_ms,
                    self.config.max_batch,
                    pipeline=max(self.config.batch_pipeline, lanes),
                    lanes=lanes,
                    deadline_ms=self.config.queue_deadline_ms)
        else:
            self.batcher = None
        self._warm_gen = 0  # stale warm threads must not set the event
        if self.config.warm_start:
            threading.Thread(target=self._warm_serving, args=(0,),
                             daemon=True, name="serving-warmup").start()
        else:
            self.warm_done.set()
            self.recompile_sentinel.arm()
        # streaming incremental training (ISSUE 10): the deploy-time
        # trainer. Fail fast on a bad config — a deploy that silently
        # drops its freshness contract is worse than one that errors.
        self.stream = None
        if self.config.streaming:
            self.start_stream()
        # SLO engine (ISSUE 15, docs/slo.md): every objective is
        # accounted against the registry built above, on a background
        # tick. The server owns it (like the tracer/registry) so
        # direct query() embedders burn the same budgets HTTP traffic
        # does; build_app serves /slo.json off it. Breach transitions
        # flip the tracer into force-retention — the flight recorder
        # carries the evidence for every violation counted.
        self.slo = None
        if self.config.slo_interval_ms > 0:
            from ..slo import SLOEngine, default_specs, load_specs

            if self.config.slo_specs:
                # fail fast at deploy: a server that silently dropped
                # its objectives is worse than one that errors
                slo_specs, _ = load_specs(self.config.slo_specs)
            else:
                slo_specs = default_specs(
                    streaming=self.config.streaming)
            self.slo = SLOEngine(self.metrics, slo_specs,
                                 on_transition=self._on_slo_transition)
            self.slo.register_metrics(self.metrics)
            self.slo.start(self.config.slo_interval_ms / 1000.0)

    def _on_slo_transition(self, spec, breached: bool, info) -> None:
        """ok↔breach edge hook: while ANY spec burns, the tail sampler
        retains every trace (reason ``slo``) — an SLO violation must
        never arrive without flight-recorder exemplars riding along."""
        tracer = self.tracer
        if tracer is None or self.slo is None:
            return
        tracer.force_retention("slo" if self.slo.burning() else None)

    def slo_status(self) -> dict:
        """The ``slo`` block of ``/status.json`` (and ``/slo.json``)."""
        if self.slo is None:
            return {"enabled": False,
                    "hint": "deploy with --slo-specs FILE (or leave "
                            "slo_interval_ms at its default) to "
                            "evaluate service objectives"}
        return self.slo.status()

    def stop_slo(self) -> None:
        if self.slo is not None:
            self.slo.stop()

    def close(self, timeout: float = 5.0) -> None:
        """Release every background worker this server owns — rollout
        gate, stream trainer, SLO evaluator, batcher drainers /
        pipeline stages, sniffer pump — so a deploy→shutdown cycle
        leaks no threads (``ptpu audit-lifecycle`` gates this).
        Idempotent. Direct ``query()`` calls still work after close;
        batched submits do not — close after the listener is down."""
        if self.rollout is not None:
            self.rollout.stop()
        self.stop_stream()
        self.stop_slo()
        if self.batcher is not None:
            self.batcher.close(timeout=timeout)
        self.plugins.close()

    # -- lifecycle advertisement (ISSUE 18) ----------------------------------
    @property
    def lifecycle(self) -> str:
        """``warming`` | ``ready`` | ``draining`` — the state this
        replica advertises on ``/status.json``. Draining means "finish
        what's in flight, send me nothing new": the router has already
        pulled this replica from its ring; the aggregator keeps it out
        of rollups and treats its eventual silence as an expected
        departure."""
        if self.drain_started.is_set():
            return "draining"
        return "ready" if self.warm_done.is_set() else "warming"

    def enter_drain(self) -> None:
        """Irreversible: announce drain (``POST /drain``). The server
        keeps answering queries — in-flight and in-deadline work must
        complete — but every surface now reports lifecycle=draining."""
        self.drain_started.set()

    def artifact_key(self) -> dict:
        """The AOT artifact store key for THIS binding — every field
        that changes which executables serve: toolchain identity (jax
        version/backend/device count, added by ``aot.store_key``), the
        resolved serving placement (mode, mesh shape, lane count), the
        bound tables' rank + ACTUAL quantization (the parity probe may
        have fallen back to f32 — the requested knob is not the truth),
        and the batching envelope. ``ptpu build`` and deploy both
        derive the key through here, so any drift resolves to a
        different artifact directory and deploy falls back to
        compiling (docs/cold-start.md)."""
        from .. import aot

        with self._lock:
            models = list(self.models)
            lanes = len(self.lane_models)
        ranks, quants = [], []
        for m in models:
            itf = getattr(m, "item_factors", None)
            if itf is None:
                continue
            data = getattr(itf, "data", itf)
            shape = getattr(data, "shape", None)
            if shape is not None and len(shape) == 2:
                ranks.append(int(shape[-1]))
            quants.append(str(getattr(itf, "quant", "off")))
        mesh = getattr(self, "serving_mesh", None)
        return aot.store_key(
            serving_mode=str(getattr(self, "serving_mode_resolved",
                                     self.config.serving_mode)),
            mesh_shape=(tuple(int(s) for s in mesh.devices.shape)
                        if mesh is not None else None),
            lanes=lanes,
            rank=tuple(ranks),
            quant=tuple(quants),
            topk=str(self.config.serving_topk),
            max_batch=int(self.config.max_batch),
            batching=bool(self.config.batching or lanes),
        )

    def _warm_serving(self, gen: int) -> None:
        """Warm the serving path's device shapes (single query + the
        batcher's pow2 ladder) so first traffic never pays a compile.
        Algorithms opt in by implementing
        ``warm_serving(model, max_batch)``; failures only log — a cold
        cache is slow, not broken. ``gen`` guards against a stale
        deploy-time thread flipping ``warm_done`` while a post-reload
        re-warm (newer generation) is still compiling new shapes.

        With ``config.artifact_dir`` set this is artifact-load-then-
        verify (ISSUE 19): the AOT store built by ``ptpu build`` is
        opened and activated, the same ladder then ANSWERS from
        deserialized executables (milliseconds) instead of compiling,
        and executing every entry on real zeros is the verification.
        Any mismatch — stale key, missing build, corrupt entry — falls
        back to compiling that entry exactly as before."""
        from .. import aot

        with self._lock:
            # snapshot: a concurrent reload/promote must not swap the
            # lists out from under the zip mid-warm
            algorithms, models = self.algorithms, self.models
            lane_models = list(self.lane_models)
        max_b = self.config.max_batch \
            if (self.config.batching or lane_models) else 1
        aot.reset_stats()
        t0 = time.perf_counter()
        store = None
        if self.config.artifact_dir:
            try:
                store = aot.ArtifactStore.open(self.config.artifact_dir,
                                               self.artifact_key())
            except Exception as e:  # noqa: BLE001 — artifacts optional
                log.warning("artifact store open failed: %s — "
                            "compiling", e)
            aot.activate(store)
            if store is not None:
                log.info("serving artifacts: %d entries under %s",
                         len(store), store.path)
            else:
                log.warning(
                    "no matching serving artifacts under %s (stale key "
                    "or missing build) — falling back to compile",
                    self.config.artifact_dir)
        t_open = time.perf_counter() - t0

        def _walk(models_i) -> None:
            for algo, model in zip(algorithms, models_i):
                warm = getattr(algo, "warm_serving", None)
                if warm is None:
                    continue
                try:
                    warm(model, max_b)
                except Exception as e:  # noqa: BLE001 — warm the rest
                    log.warning("serving warmup failed for %s: %s",
                                type(algo).__name__, e)

        # every lane warms its own copy: executables compile (or load)
        # PER DEVICE, so warming lane 0 alone leaves lanes 1..N-1
        # paying cold compiles on first fan-out. Lane 0 accounts to
        # the "compile" phase, the rest to "replicate"; artifact
        # deserialize time is subtracted into "load" where it belongs.
        all_lanes = lane_models or [models]
        t1 = time.perf_counter()
        _walk(all_lanes[0])
        first_walk = time.perf_counter() - t1
        first_load = aot.stats()["load_seconds"]
        t2 = time.perf_counter()
        for models_i in all_lanes[1:]:
            _walk(models_i)
        repl_walk = time.perf_counter() - t2
        # probe: re-run the lane-0 ladder against the now-warm caches —
        # every shape must answer without a compile; this is the
        # "verify" half of artifact-load-then-verify. Compile warms
        # skip it: the compile itself proved every shape, and algo
        # ``warm_serving`` hooks keep their one-run-per-warm contract
        # (the reload-race tests count on it)
        t3 = time.perf_counter()
        if store is not None:
            _walk(all_lanes[0])
        t_probe = time.perf_counter() - t3
        s = aot.stats()
        phases = {
            "load": t_open + s["load_seconds"],
            "compile": max(first_walk - first_load, 0.0),
            "replicate": max(repl_walk
                             - (s["load_seconds"] - first_load), 0.0),
            "probe": t_probe,
        }
        for phase, sec in phases.items():
            self._warmup_seconds.labels(phase=phase).observe(sec)
        report = {
            # an ARTIFACT warm: a store was bound and every ladder
            # entry answered from it (zero compile fallbacks)
            "artifact": bool(store is not None and s["loaded_entries"]
                             and not s["compiled_calls"]),
            "store": store.path if store is not None else None,
            "storeEntries": len(store) if store is not None else 0,
            "loadedEntries": int(s["loaded_entries"]),
            "compiledFallbacks": int(s["compiled_calls"]),
            "corruptEntries": int(s["corrupt_entries"]),
            "staleStores": int(s["stale"]),
            "seconds": {k: round(v, 4) for k, v in phases.items()},
            "totalSeconds": round(sum(phases.values()), 4),
        }
        if report["artifact"]:
            log.info("serving warm from artifact in %.2fs (%d entries)",
                     report["totalSeconds"], report["loadedEntries"])
        # check+set under the lock: unsynchronized, a stale thread could
        # pass the gen check, lose the CPU to reload()'s clear+increment,
        # then set() — reporting warm while the re-warm still compiles
        with self._lock:
            if gen == self._warm_gen:
                self._warm_report = report
                self.warm_done.set()
                self.recompile_sentinel.arm()

    def _bind(self, engine_params: EngineParams, models: List[Any],
              instance: EngineInstance) -> None:
        with self._lock:
            if self.cache is not None:
                # FULL flush on every rebind (deploy/reload/promote):
                # a new model must never serve results — or pinned
                # factor rows — computed by the old one (ISSUE 4)
                self.cache.flush_all()
            self.engine_params = engine_params
            self.instance = instance
            # stream lineage (ISSUE 10): a rebind installs a fresh
            # full-retrain base — the incremental generation restarts
            # from it (the StreamTrainer notices the new instance id
            # and re-folds pending events against the new base)
            self._stream_generation = 0
            self._stream_rows = 0
            self._stream_last_apply: Optional[float] = None
            self._stream_base_bound_at = time.time()
            self.algorithms = self.engine.make_algorithms(engine_params)
            for algo in self.algorithms:
                algo.bind_serving(self.ctx)
                self._bind_feature_cache(algo)
            # serving fast path knobs (ISSUE 13): pin the batched-lane
            # top-k realization for this deploy (validates the value —
            # a bad config fails the deploy, not the first query) and
            # row-quantize the serving tables BEFORE device placement,
            # so the host→HBM transfer already moves the small tables.
            # The quantize hook runs its NDCG parity probe and returns
            # the f32 model unchanged where quantization loses ranking
            # (auto-off).
            from ..models.als import set_serving_topk_mode

            if self.config.serving_quant not in ("off", "bf16", "int8"):
                raise ValueError(
                    f"serving_quant must be 'off', 'bf16' or 'int8', "
                    f"got {self.config.serving_quant!r}")
            set_serving_topk_mode(self.config.serving_topk)
            if self.config.serving_quant != "off":
                quantized = []
                for a, m in zip(self.algorithms, models):
                    q = getattr(a, "quantize_serving_model", None)
                    if q is None:
                        quantized.append(m)
                        continue
                    # bind-time only (deploy/reload/promote, never a
                    # query): the quantize hook is a pure table
                    # rewrite with the same atomic-swap contract as
                    # the prepare_serving_model calls below; it
                    # cannot re-enter the binding lock.
                    # ptpu: allow[callback-under-lock]
                    quantized.append(q(m, self.config.serving_quant))
                models = quantized
            # fix device placement ONCE at bind (deploy/reload), not
            # per query — a re-materialized model holds numpy factors
            bind_batch = self.config.max_batch if self.config.batching \
                else 1
            self.models = [a.prepare_serving_model(m, bind_batch)
                           for a, m in zip(self.algorithms, models)]
            self.serving = self.engine.make_serving(engine_params)
            # ptpu: allow[blocking-under-lock] — bind-time only
            # (deploy/reload/promote, never a query): the gram-mode
            # resolution may one-shot-probe the fused kernel's
            # lowering, and the result must be recorded inside the
            # same swap that installs the binding it describes
            self._record_gram_mode()
            # ptpu: allow[blocking-under-lock] — same bind-time-only
            # contract for the serving-kernel resolution probe
            self._record_serving_kernel()
            # mesh-wide placement (ISSUE 6): resolve the serving mode
            # against the live devices and the model's resident bytes,
            # then either fan the binding out as per-device lane copies
            # (replicated) or re-place it row-sharded over the serving
            # mesh (sharded). Inside the same lock as the binding swap:
            # a promote/reload swaps mode, mesh, lanes and models as
            # one unit — queries never see a half-placed binding.
            # ptpu: allow[blocking-under-lock] — that atomic-swap
            # contract is exactly why the device placement happens
            # with the lock held (bind-time, never per query)
            self._place_binding()

    # ptpu: guarded-by[_lock] — only ever called from _bind under the
    # binding lock (the gauge family itself is thread-safe)
    def _record_gram_mode(self) -> None:
        """Refresh the ``pio_gram_mode`` info gauge (ISSUE 7) from the
        bound algorithms' ALS params: the weighted-gram realization
        they resolve to on THIS backend (autotune table + Pallas
        lowering support, ``models/als.resolved_gram_mode``) reads 1;
        a label a rebind left behind drops to 0 — a retrain/deploy
        that silently fell off the fused kernel is visible on
        /metrics, not just in bench lines. The very first _bind runs
        before __init__ creates the registry — __init__ re-records
        right after; rebinds find it in place."""
        if getattr(self, "metrics", None) is None:
            return  # constructor's initial _bind; __init__ re-records
        try:
            from ..models.als import resolved_gram_mode

            mode = None
            for algo in self.algorithms:
                p = getattr(algo, "params", None)
                if p is not None and hasattr(p, "gram_mode"):
                    mode = resolved_gram_mode(p)
                    break
            if mode is None:
                return
            fam = self.metrics.gauge(
                "pio_gram_mode",
                "Resolved ALS gram realization of the bound engine "
                "params (info gauge: 1 at the active mode label)")
            self._gram_mode_gauge = fam
            for _, child in fam.children():
                child.set(0.0)
            fam.labels(mode=mode).set(1.0)
        except Exception:  # noqa: BLE001 — telemetry must not block a
            pass           # deploy/reload/promote

    # ptpu: guarded-by[_lock] — only ever called from _bind under the
    # binding lock (the gauge family itself is thread-safe)
    def _record_serving_kernel(self) -> None:
        """Refresh the ``pio_serving_kernel`` info gauge (ISSUE 13):
        the batched-lane top-k realization × serving-quant dtype the
        bound models resolve to on THIS backend (autotune table +
        Pallas lowering support, ``models/als.resolved_topk_mode``)
        reads 1; stale labels from a prior bind drop to 0 — a deploy
        that quietly fell off the fused kernel or auto-disabled
        quantization is visible on /metrics, not just in bench
        lines. Sits next to ``pio_gram_mode``."""
        if getattr(self, "metrics", None) is None:
            return  # constructor's initial _bind; __init__ re-records
        try:
            from ..models.als import resolved_topk_mode, serving_quant_of

            mode = quant = None
            for algo, model in zip(self.algorithms, self.models):
                p = getattr(algo, "params", None)
                if p is not None and hasattr(p, "rank"):
                    quant = serving_quant_of(model)
                    mode = resolved_topk_mode(int(p.rank), quant)
                    break
            if mode is None:
                return
            fam = self.metrics.gauge(
                "pio_serving_kernel",
                "Resolved serving top-k realization x quant dtype of "
                "the bound engine (info gauge: 1 at the active "
                "labels)")
            self._serving_kernel_gauge = fam
            for _, child in fam.children():
                child.set(0.0)
            fam.labels(mode=mode, quant=quant).set(1.0)
            self._serving_kernel = {"mode": mode, "quant": quant}
        except Exception:  # noqa: BLE001 — telemetry must not block a
            pass           # deploy/reload/promote

    def _record_sharding_findings(self) -> None:
        """Record the ``pio_sharding_findings`` info gauge (ISSUE 14):
        per-rule count of ``# ptpu: allow[...]`` pragmas naming a
        sharding-family rule baked into THIS deployed build — the
        accepted-and-justified sharding debt the static pass would
        otherwise flag. A deploy that ships new suppressed sharding
        findings moves this gauge, so the debt is visible on /metrics
        next to ``pio_gram_mode``/``pio_serving_kernel``, not only in
        code review. Source-text census (no jax, no AST), run once at
        server construction — the installed sources don't change under
        a live process."""
        if getattr(self, "metrics", None) is None:
            return
        try:
            from ..analysis.sharding import count_sharding_pragmas

            counts = count_sharding_pragmas()
            fam = self.metrics.gauge(
                "pio_sharding_findings",
                "Pragma-suppressed sharding findings baked into the "
                "deployed build (info gauge: count per rule)")
            for rule, n in sorted(counts.items()):
                fam.labels(rule=rule).set(float(n))
            self._sharding_findings = dict(counts)
        except Exception:  # noqa: BLE001 — telemetry must not block
            pass           # server construction

    def sharding_findings_status(self) -> dict:
        """The suppressed-sharding-debt block for /status.json."""
        counts = getattr(self, "_sharding_findings", None) or {}
        return {"suppressed": sum(counts.values()),
                "byRule": dict(sorted(counts.items()))}

    def serving_kernel_status(self) -> dict:
        """The resolved serving-kernel block for /status.json: top-k
        realization, quant dtype, and the configured knobs (resolved
        may differ — auto-off parity fallback, unsupported kernel)."""
        out = {"configuredQuant": self.config.serving_quant,
               "configuredTopk": self.config.serving_topk}
        out.update(getattr(self, "_serving_kernel", None)
                   or {"mode": None, "quant": None})
        return out

    @staticmethod
    def _models_nbytes(models: List[Any]) -> Optional[int]:
        """Resident bytes of the bound models' array leaves — the
        numerator of the auto-mode HBM sizing math. None when nothing
        reports nbytes (sizing unknown ≠ sizing zero)."""
        try:
            import jax

            total = 0
            seen = False
            for m in models:
                for leaf in jax.tree_util.tree_leaves(m):
                    nb = getattr(leaf, "nbytes", None)
                    if nb is not None:
                        total += int(nb)
                        seen = True
            return total if seen else None
        except Exception:  # noqa: BLE001 — sizing is advisory
            return None

    # ptpu: guarded-by[_lock] — only ever called from _bind, which
    # holds the (reentrant) binding lock around the whole placement
    def _place_binding(self) -> None:
        """Resolve ``ServerConfig.serving_mode`` and place the stable
        binding accordingly. Called under ``self._lock`` from
        :meth:`_bind`. Sets ``serving_mode_resolved``, ``serving_mesh``
        (sharded), and ``lane_devices``/``lane_models`` (replicated:
        one full model list per device, each committed to its own
        chip)."""
        self.serving_mesh = None
        self.lane_devices: List[Any] = []
        self.lane_models: List[List[Any]] = []
        # a rebind replicates every lane fresh: prior lane deaths are
        # about models/devices that no longer serve (the constructor's
        # first _bind runs before the health state exists)
        if getattr(self, "_lane_health", None) is not None:
            with self._lane_health:
                self._dead_lanes.clear()
                self._lane_streaks.clear()
        mode = self.config.serving_mode
        if mode == "single":
            self.serving_mode_resolved = "single"
            return
        import jax

        from ..parallel.mesh import (
            make_serving_mesh,
            resolve_serving_mode,
        )

        devices = jax.devices()
        resolved = resolve_serving_mode(
            mode, self._models_nbytes(self.models), len(devices))
        if resolved != "sharded" and len(devices) <= 1:
            resolved = "single"
        self.serving_mode_resolved = resolved
        if resolved == "replicated":
            self.lane_devices = list(devices)
            for dev in devices:
                lane = []
                for a, m in zip(self.algorithms, self.models):
                    rep = getattr(a, "replicate_serving_model", None)
                    lane.append(rep(m, dev) if rep is not None else m)
                self.lane_models.append(lane)
        elif resolved == "sharded":
            mesh = make_serving_mesh(devices=devices)
            self.serving_mesh = mesh
            self.models = self._shard_models(self.algorithms,
                                             self.models, mesh)

    @staticmethod
    def _shard_models(algorithms: List[Any], models: List[Any],
                      mesh) -> List[Any]:
        """Row-shard every model whose algorithm supports it; models
        without the hook keep their single-device placement (they
        still serve — just not mesh-wide)."""
        out = []
        for a, m in zip(algorithms, models):
            hook = getattr(a, "shard_serving_model", None)
            out.append(hook(m, mesh) if hook is not None else m)
        return out

    def _bind_feature_cache(self, algo: Any) -> None:
        """Hand the feature tier to algorithms that cache serving-time
        event-store reads (e.g. the e-commerce template's seen/
        unavailable/weighted/recent lookups)."""
        if self.cache is None:
            return
        bind = getattr(algo, "bind_feature_cache", None)
        if bind is not None:
            bind(self.cache.features)

    def _make_cache(self):
        cfg = self.config
        if not cfg.serving_cache:
            return None
        from ..cache import ServingCache

        return ServingCache(
            query_entries=cfg.cache_entries,
            query_ttl_sec=cfg.cache_ttl_sec,
            feature_entries=cfg.feature_cache_entries,
            feature_ttl_sec=cfg.feature_ttl_sec,
            hot_capacity=cfg.hot_entities,
            hot_refresh_every=cfg.hot_refresh_every,
            pin_fn=self._pin_hot)

    def _pin_hot(self, entity_keys: List[str]):
        """Hot-tier pin callback: delegate to the (single) algorithm's
        ``pin_hot_entities`` against the CURRENT stable binding. Under
        replicated fan-out the pin lands on EVERY lane device
        (per-device pinned shards), so hot serves stay lane-local."""
        with self._lock:
            algorithms, models = self.algorithms, self.models
            devices = list(self.lane_devices)
        if len(algorithms) != 1:
            return {}, 0  # multi-algo serving blends predictions;
        pin = getattr(algorithms[0], "pin_hot_entities", None)  # a
        if pin is None:                  # single-algo pin would skew
            return {}, 0
        if devices:
            try:
                return pin(models[0], entity_keys, devices=devices)
            except TypeError:
                pass  # algorithm predates per-lane pinning
        return pin(models[0], entity_keys)

    def _transfer_guard(self):
        """Post-warmup queries run under ``jax.transfer_guard`` so any
        implicit device↔host transfer on the hot path is logged (or
        rejected, per config) instead of silently stalling dispatch.
        Warmup-phase traffic and guard levels of "allow"/"off" get a
        no-op context; so does a jax too old to have the API."""
        from contextlib import nullcontext

        level = self.config.transfer_guard
        if not level or level in ("off", "allow") \
                or not self.warm_done.is_set():
            return nullcontext()
        try:
            import jax

            return jax.transfer_guard(level)
        except Exception:  # noqa: BLE001 — observability, never a dep
            return nullcontext()

    def _ensure_algo_pool(self):
        with self._lock:
            if self._algo_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._algo_pool = ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="algo-dispatch")
            return self._algo_pool

    def _predict_all(self, algorithms: List[Any], models: List[Any],
                     supplemented: Any) -> List[Any]:
        """Per-algorithm predictions, dispatched CONCURRENTLY when the
        engine has more than one algorithm (the reference served them
        serially — ``CreateServer.scala:507-510`` "TODO: Parallelize";
        predictions are independent by the DASE contract, serving sees
        them in params order). The single-algorithm common case stays
        pool-free."""
        if len(algorithms) == 1:
            return [algorithms[0].predict(models[0], supplemented)]
        pool = self._ensure_algo_pool()
        futures = [pool.submit(a.predict, m, supplemented)
                   for a, m in zip(algorithms, models)]
        return [f.result() for f in futures]

    def _dispatch_predictions(self, algorithms: List[Any],
                              models: List[Any],
                              supplemented: Any) -> List[Any]:
        """Per-query dispatch with the hot-entity fast path (ISSUE 4):
        a known-hot user's prediction runs off the pinned device-
        resident row table (``predict_pinned``), skipping the full
        factor-table gather; anything unusual falls back to the normal
        path — the tier is an accelerator, never a correctness
        dependency."""
        cache = self.cache
        if (cache is not None and cache.hot is not None
                and len(algorithms) == 1):
            entity = getattr(supplemented, "user", None)
            handle = (cache.hot.lookup(str(entity))
                      if entity is not None else None)
            pinned = getattr(algorithms[0], "predict_pinned", None)
            if handle is not None and pinned is not None:
                try:
                    return [pinned(models[0], supplemented, handle)]
                except Exception as e:  # noqa: BLE001 — e.g. a pin
                    log.warning(        # raced a rebind; serve normally
                        "pinned hot-path serve failed, falling "
                        "back: %s", e)
        return self._predict_all(algorithms, models, supplemented)

    def _record_phases(self, phases: dict) -> None:
        for phase, sec in phases.items():
            self._phase_hist.labels(phase=phase).observe(sec)

    def _observe_release(self, arm: str, seconds: float,
                         error: bool) -> None:
        """Per-arm health series, recorded only while a rollout is
        live (the controller windows these; client 4xx never counts
        against an arm's health)."""
        rollout = self.rollout
        if rollout is None or not rollout.active:
            return
        self._release_queries.labels(arm=arm).inc()
        if error:
            self._release_errors.labels(arm=arm).inc()
        self._release_latency.labels(arm=arm).observe(seconds)

    def release_arm_snapshot(self, arm: str):
        """Cumulative ``(queries, errors, latency buckets)`` for one
        release arm — the rollout controller diffs successive snapshots
        into sliding windows."""
        return (self._release_queries.labels(arm=arm).value,
                self._release_errors.labels(arm=arm).value,
                self._release_latency.labels(arm=arm).bucket_counts())

    def release_arms(self) -> dict:
        """Live per-arm stats for ``/release.json`` and the bench."""
        out = {}
        for arm in (ARM_STABLE, ARM_CANDIDATE):
            queries, errors, _ = self.release_arm_snapshot(arm)
            out[arm] = {
                "queries": int(queries), "errors": int(errors),
                "latency": self._release_latency.labels(
                    arm=arm).snapshot()}
        return out

    def mesh_status(self) -> dict:
        """Mesh-wide serving state for ``/status.json`` and the status
        page (ISSUE 6): resolved mode, mesh shape, and — under
        replicated fan-out — per-lane device / dispatch-count / batch
        latency / queue-depth rows (the per-device occupancy view; the
        per-device HBM gauges live in the sibling ``hbm`` block)."""
        with self._lock:
            mode = self.serving_mode_resolved
            lane_devices = list(self.lane_devices)
            mesh = self.serving_mesh
        out: dict = {"mode": mode}
        if mesh is not None:
            out["meshShape"] = {str(ax): int(sz) for ax, sz
                                in zip(mesh.axis_names,
                                       mesh.devices.shape)}
            out["devices"] = int(mesh.devices.size)
        if lane_devices:
            out["devices"] = len(lane_devices)
            lanes = []
            for i, dev in enumerate(lane_devices):
                lat = self._lane_latency.labels(lane=str(i)).snapshot()
                depth = self._lane_depth.labels(lane=str(i)).snapshot()
                lanes.append({
                    "lane": i,
                    "device": str(dev),
                    "deviceId": int(getattr(dev, "id", i)),
                    "dispatches": int(self._lane_dispatches.labels(
                        lane=str(i)).value),
                    "batchP50Ms": (round(lat["p50"] * 1000, 3)
                                   if lat.get("count") else None),
                    "batchP99Ms": (round(lat["p99"] * 1000, 3)
                                   if lat.get("count") else None),
                    "queueDepthP50": (depth["p50"]
                                      if depth.get("count") else None),
                })
            out["lanes"] = lanes
        return out

    # -- lane supervision / graceful degradation (ISSUE 11) -----------------
    def live_lane(self, lane: int) -> int:
        """Where a batch assigned to ``lane`` should actually run:
        identity while the lane is healthy, a surviving lane while it
        is dead (docs/reliability.md)."""
        with self._lock:
            n = len(self.lane_models)
        with self._lane_health:
            return pick_live_lane(lane, n, self._dead_lanes)

    def lane_attempt_order(self, lane: int) -> List[int]:
        """Dispatch-failover order for a batch assigned to ``lane``:
        its live mapping first, then every other lane (healthy ones
        before dead ones as a last resort) — each tried at most once,
        so one batch can never loop."""
        with self._lock:
            n = len(self.lane_models)
        if n <= 0:
            return [lane]
        with self._lane_health:
            dead = set(self._dead_lanes)
        first = pick_live_lane(lane % n, n, dead)
        rest = [i for i in range(n) if i != first]
        rest.sort(key=lambda i: (i in dead, i))
        return [first] + rest

    def _lane_ok(self, lane: int) -> None:
        with self._lane_health:
            self._lane_streaks.pop(lane, None)

    def _lane_error(self, lane: int, exc: Exception) -> None:
        """A dispatch on ``lane`` failed: count the streak and declare
        the lane dead at ``lane_fail_threshold`` consecutive failures
        (then start its restarter)."""
        self._lane_failures.labels(lane=str(lane)).inc()
        threshold = max(self.config.lane_fail_threshold, 1)
        with self._lane_health:
            if lane in self._dead_lanes:
                return
            streak = self._lane_streaks.get(lane, 0) + 1
            self._lane_streaks[lane] = streak
            if streak < threshold:
                return
            self._dead_lanes[lane] = {
                "since": time.time(),
                "reason": f"{type(exc).__name__}: {exc}"[:300],
                "failures": streak,
            }
        log.error("serving lane %d declared dead after %d consecutive "
                  "dispatch failures (%s); redistributing its traffic "
                  "and starting the restarter", lane, streak, exc)
        threading.Thread(target=self._lane_restarter, args=(lane,),
                         daemon=True,
                         name=f"lane-restarter-{lane}").start()

    def _lane_restarter(self, lane: int) -> None:
        """Probe a dead lane back to life: bounded-exponential-backoff
        attempts, each probing the lane's fault point (a still-armed
        injection keeps it down) and re-replicating the serving models
        onto the lane's device. Success rejoins the lane and counts
        ``pio_lane_restarts_total``; an exhausted budget leaves it dead
        (degraded mode persists — the operator sees it on
        /status.json)."""
        cfg = self.config
        policy = RetryPolicy(
            max_attempts=max(cfg.lane_restart_max_attempts, 1),
            base_ms=max(cfg.lane_restart_backoff_ms, 1.0),
            cap_ms=max(cfg.lane_restart_backoff_ms, 1.0) * 32)
        delays = list(backoff_delays(policy)) + [0.0]
        for delay in delays:
            time.sleep(delay)
            with self._lock:
                if lane >= len(self.lane_devices):
                    return  # a rebind changed the lane layout
                dev = self.lane_devices[lane]
                algorithms = self.algorithms
                models = self.models
                instance_id = self.instance.id
            try:
                # the probe: if the injected (or real) fault is still
                # there, this raises and we back off
                fire(F_LANE_RESTART, lane=str(lane))
                fire(F_LANE, lane=str(lane))
                fresh = []
                for a, m in zip(algorithms, models):
                    rep = getattr(a, "replicate_serving_model", None)
                    fresh.append(rep(m, dev) if rep is not None else m)
            except Exception as e:  # noqa: BLE001 — still down
                log.warning("lane %d restart probe failed: %s", lane, e)
                continue
            with self._lock:
                if self.instance.id != instance_id \
                        or lane >= len(self.lane_models):
                    return  # binding swapped mid-restart: the rebind
                    # already rebuilt every lane and reset health
                self.lane_models[lane] = fresh
            with self._lane_health:
                self._dead_lanes.pop(lane, None)
                self._lane_streaks.pop(lane, None)
            self._lane_restarts.labels(lane=str(lane)).inc()
            log.info("serving lane %d restarted and rejoined", lane)
            return
        log.error("serving lane %d restart budget exhausted (%d "
                  "attempts); staying degraded", lane,
                  policy.max_attempts)

    def degraded_status(self) -> dict:
        """The degraded block of ``/status.json``: dead lanes, restart
        and failure totals, and whether fault injection is armed."""
        with self._lane_health:
            dead = [{"lane": int(k), "since": v["since"],
                     "reason": v["reason"]}
                    for k, v in sorted(self._dead_lanes.items())]

        def _total(fam) -> int:
            return int(sum(child.value for _, child in fam.children()))

        nonfinite = numerics_sentinel.active() \
            and numerics_sentinel.nonfinite_seen()
        return {
            "active": bool(dead) or nonfinite,
            "deadLanes": dead,
            "laneRestarts": _total(self._lane_restarts),
            "laneFailures": _total(self._lane_failures),
            "faultInjection": fault_registry().enabled(),
            "nonfinite": nonfinite,
        }

    def spans_summary(self) -> dict:
        """Percentile rows for the status page: each query phase plus
        end-to-end latency, from the live bounded histograms."""
        out: dict = {}

        def row(hist) -> Optional[dict]:
            s = hist.snapshot()
            if not s.get("count"):
                return None
            return {"count": s["count"], "p50": s["p50"],
                    "p90": s["p90"], "p99": s["p99"],
                    "max_sec": s["max"]}

        for items, child in self._phase_hist.children():
            r = row(child)
            if r is not None:
                out["phase:" + dict(items).get("phase", "?")] = r
        for items, child in self._latency_hist.children():
            r = row(child)
            if r is not None:
                out["query (end-to-end)"] = r
        return out

    # -- cached serving entrypoints (ISSUE 4) --------------------------------
    @staticmethod
    def _entity_of(query_json: Any) -> Optional[str]:
        """The query's primary entity (the cache-tag / hot-tier key).
        Every bundled template keys queries by ``user``; entity-less
        queries cache fine but can't be invalidated per-entity (the
        TTL bound covers them)."""
        if isinstance(query_json, dict):
            entity = query_json.get("user")
            if entity is not None:
                return str(entity)
        return None

    def _record_cache_hit(self, arm: str, t0: float,
                          obs: Optional[dict]) -> None:
        dt = time.monotonic() - t0
        self._latency_hist.observe(dt)
        self._observe_release(arm, dt, error=False)
        if obs is not None:
            obs["cache"] = "hit"
            tr = self._trace_of(obs)
            if tr is not None:
                # a hit never touches the device: one span tells the
                # whole story, and the tier rides as an attribute
                tr.set_attr("arm", arm)
                tr.set_attr("cacheTier", "query")
                tr.add_span("cache_hit", t0, t0 + dt, tier="query")
                tr.exemplar(self._latency_hist.labels(), dt)
        with self._lock:
            self.last_serving_sec = dt
            self.avg_serving_sec = (
                (self.avg_serving_sec * self.request_count + dt)
                / (self.request_count + 1))
            self.request_count += 1

    def _compute_stable(self, query_json: Any,
                        obs: Optional[dict]) -> Any:
        """The uncached stable pipeline: micro-batcher when configured,
        else the per-query path. Returns the jsonable result or an
        ``HTTPError`` instance (the batcher's slot contract); the
        per-query path raises instead — callers handle both."""
        if self.batcher is not None:
            return self.batcher.submit(query_json, obs=obs)
        return self.query(query_json, obs=obs)

    def serve(self, query_json: Any, obs: Optional[dict] = None) -> Any:
        """The stable-arm serving entry ``/queries.json`` uses: query
        cache → singleflight → batcher/per-query compute → cache fill.
        A cache hit skips supplement and device dispatch entirely;
        concurrent identical misses compute ONCE. Returns the result
        or an ``HTTPError`` instance; may also raise ``HTTPError``."""
        if self.hotkeys is not None:
            # recorded BEFORE the cache: a hot key that is hot because
            # it keeps hitting the cache is still a hot key (the
            # router signal counts demand, not device work)
            self.hotkeys.record(self._entity_of(query_json))
        cache = self.cache
        if cache is None:
            return self._compute_stable(query_json, obs)
        from ..cache import canonical_key, entity_tag

        t0 = time.monotonic()
        with self._lock:
            instance_id = self.instance.id
        key = (instance_id, canonical_key(query_json))
        entity = self._entity_of(query_json)
        if entity is not None and cache.hot is not None:
            cache.hot.record(entity)
        found, value = cache.query.lookup(key)
        if found:
            self._record_cache_hit(ARM_STABLE, t0, obs)
            return value
        tag = entity_tag("user", entity) if entity is not None else None

        def compute() -> Any:
            # epoch BEFORE the pipeline runs: an ingest that lands
            # mid-compute moves it, and the fill is dropped instead of
            # caching a result the invalidation already condemned
            token = cache.epoch_token(tag)
            result = self._compute_stable(query_json, obs)
            if not isinstance(result, HTTPError):
                cache.put_query_fresh(
                    key, result, (tag,) if tag else (), token)
            return result

        result, leader = cache.flight.do(key, compute)
        if obs is not None and not leader:
            obs["cache"] = "coalesced"
        return result

    def serve_candidate(self, query_json: Any,
                        obs: Optional[dict] = None) -> Any:
        """The candidate-arm serving entry: same cache discipline as
        :meth:`serve` under the CANDIDATE instance's namespace — the
        two arms can never serve each other's cached results. Raises
        like :meth:`query_candidate`."""
        if self.hotkeys is not None:
            self.hotkeys.record(self._entity_of(query_json))
        cache = self.cache
        with self._lock:
            cand = self._candidate
        if cache is None or cand is None:
            return self.query_candidate(query_json, obs=obs)
        from ..cache import canonical_key, entity_tag

        t0 = time.monotonic()
        key = (cand.instance.id, canonical_key(query_json))
        found, value = cache.query.lookup(key)
        if found:
            self._record_cache_hit(ARM_CANDIDATE, t0, obs)
            return value
        entity = self._entity_of(query_json)
        tag = entity_tag("user", entity) if entity is not None else None

        def compute() -> Any:
            token = cache.epoch_token(tag)
            result = self.query_candidate(query_json, obs=obs)
            cache.put_query_fresh(key, result, (tag,) if tag else (),
                                  token)
            return result

        result, leader = cache.flight.do(key, compute)
        if obs is not None and not leader:
            obs["cache"] = "coalesced"
        return result

    # -- batched hot path ---------------------------------------------------
    def query_batch(self, query_jsons: List[Any],
                    obs_list: Optional[List[dict]] = None,
                    lane: Optional[int] = None) -> List[Any]:
        """Serve many queries with ONE ``batch_predict`` device dispatch
        per algorithm. Per-query errors come back as ``HTTPError``s in the
        result slots so one bad query never fails its batch-mates.
        ``obs_list`` (one dict per query, from the batcher) receives each
        query's access-log payload: the shared batch phase timings plus
        its own readback/feedback time.

        ``lane`` (replicated fan-out, ISSUE 6) selects that lane's
        per-device model copies — the dispatch compiles and runs on the
        lane's own chip, no cross-device sync. With no lanes bound the
        argument is ignored (a stale drainer after a mode-changing
        reload falls back to the stable binding, never a torn one)."""
        from ..workflow.batch_predict import predict_serve_batch

        t0 = time.monotonic()
        phases: dict = {}
        with self._lock:
            algorithms, serving = self.algorithms, self.serving
            if lane is not None and self.lane_models:
                lane = lane % len(self.lane_models)
                models = self.lane_models[lane]
            else:
                lane = None
                models = self.models
            instance_id = self.instance.id
        traces = [self._trace_of(o) for o in (obs_list or [])]
        traces += [None] * (len(query_jsons) - len(traces))
        query_cls = algorithms[0].query_class
        parsed: List[Any] = []
        out: List[Any] = [None] * len(query_jsons)
        ok_rows: List[int] = []
        for i, qj in enumerate(query_jsons):
            try:
                parsed.append(from_jsonable(query_cls, qj))
                ok_rows.append(i)
            except (TypeError, ValueError) as e:
                out[i] = HTTPError(400, str(e))
        phases["assemble"] = time.monotonic() - t0
        per_query_ms: List[dict] = [{} for _ in query_jsons]
        if ok_rows:
            if lane is not None:
                fire(F_LANE, lane=str(lane))
            fire(F_DISPATCH)
            with activate_traces(traces), self._transfer_guard():
                served = predict_serve_batch(algorithms, models, serving,
                                             parsed, timings=phases)
            for j, i in enumerate(ok_rows):
                prediction = served[j]
                if isinstance(prediction, Exception):
                    out[i] = HTTPError(500, str(prediction))
                    continue
                try:
                    tr0 = time.monotonic()
                    result = to_jsonable(prediction)
                    tr1 = time.monotonic()
                    # batch-phase readback is the MAX per-query
                    # serialization, not the sum: the sum overstated
                    # the phase ~B× at large batches in the status
                    # page's percentile table (per_query_ms below
                    # keeps each query's own split)
                    phases["readback"] = max(phases.get("readback", 0.0),
                                             tr1 - tr0)
                    per_query_ms[i]["readbackMs"] = round(
                        (tr1 - tr0) * 1000, 3)
                    if self.config.feedback:
                        result = self._feedback(parsed[j], query_jsons[i],
                                                result, instance_id)
                        tf = time.monotonic() - tr1
                        phases["feedback"] = (phases.get("feedback", 0.0)
                                              + tf)
                        per_query_ms[i]["feedbackMs"] = round(tf * 1000, 3)
                    out[i] = self.plugins.process_output(query_jsons[i],
                                                         result)
                except Exception as e:  # noqa: BLE001 — per-query slot
                    out[i] = HTTPError(500, str(e))
        dt = time.monotonic() - t0
        self._record_phases(phases)
        self._batch_occupancy.observe(len(query_jsons))
        if lane is not None:
            self._lane_latency.labels(lane=str(lane)).observe(dt)
            self._lane_dispatches.labels(lane=str(lane)).inc()
        batch_obs = {"batchSize": len(query_jsons)}
        if lane is not None:
            batch_obs["lane"] = lane
        batch_obs.update({f"{k}Ms": round(v * 1000, 3)
                          for k, v in phases.items()})
        for i, result in enumerate(out):
            # each coalesced query experienced the batch's wall time
            self._latency_hist.observe(dt)
            is_err = isinstance(result, HTTPError)
            self._observe_release(
                ARM_STABLE, dt, error=is_err and result.status >= 500)
            if is_err:
                self._query_errors.labels(
                    status=str(result.status)).inc()
            if traces[i] is not None:
                # per-batch AND per-query spans (ISSUE 12): one
                # "batch" parent carrying the shared attributes, the
                # stage children laid sequentially from the batch
                # start (this serial path really is sequential)
                tr = traces[i]
                tr.set_attr("engineInstanceId", instance_id)
                tr.set_attr("arm", ARM_STABLE)
                if lane is not None:
                    tr.set_attr("lane", lane)
                parent = tr.add_span(
                    "batch", t0, t0 + dt,
                    batchSize=len(query_jsons),
                    **({"lane": lane} if lane is not None else {}))
                add_stage_spans(tr, t0, phases,
                                parent_id=parent.span_id,
                                skip=("queue_wait",))
                tr.exemplar(self._latency_hist.labels(), dt)
            if obs_list is not None and i < len(obs_list) \
                    and obs_list[i] is not None:
                obs_list[i].update(batch_obs)
                obs_list[i].update(per_query_ms[i])
        with self._lock:
            self.last_serving_sec = dt / max(len(query_jsons), 1)
            n = self.request_count
            self.avg_serving_sec = (
                (self.avg_serving_sec * n + dt)
                / (n + len(query_jsons)))
            self.request_count += len(query_jsons)
        return out

    def _finish_pipeline_batch(self, ab: "_AssembledBatch",
                               results: List[Any]) -> None:
        """Readback-stage tail of the staged pipeline (ISSUE 9): the
        per-query host work the serial drainer did inline after
        blocking on the device — serialization (``to_jsonable``),
        feedback, output plugins, metric recording, caller wake. The
        staged twin of :meth:`query_batch`'s post-dispatch section;
        ``results`` is the resolved :class:`PendingBatch` output,
        aligned with ``ab.entries``."""
        cfg = self.config
        phases = ab.phases
        per_query_ms: List[dict] = [{} for _ in ab.entries]
        final: List[Any] = [None] * len(ab.entries)
        for i, (entry, result) in enumerate(zip(ab.entries, results)):
            if isinstance(result, HTTPError):
                final[i] = result
                continue
            if isinstance(result, Exception):
                final[i] = HTTPError(500, str(result))
                continue
            try:
                tr0 = time.monotonic()
                jsonable = to_jsonable(result)
                tr1 = time.monotonic()
                # max-not-sum: the batch phase reports the worst
                # query's serialization (see query_batch)
                phases["readback"] = max(phases.get("readback", 0.0),
                                         tr1 - tr0)
                per_query_ms[i]["readbackMs"] = round(
                    (tr1 - tr0) * 1000, 3)
                if cfg.feedback:
                    jsonable = self._feedback(
                        ab.queries[i], entry.query_json, jsonable,
                        ab.instance_id)
                    tf = time.monotonic() - tr1
                    phases["feedback"] = (phases.get("feedback", 0.0)
                                          + tf)
                    per_query_ms[i]["feedbackMs"] = round(tf * 1000, 3)
                final[i] = self.plugins.process_output(entry.query_json,
                                                       jsonable)
            except Exception as e:  # noqa: BLE001 — per-query slot
                final[i] = HTTPError(500, str(e))
        now = time.monotonic()
        self._record_phases(phases)
        self._batch_occupancy.observe(len(ab.entries))
        if ab.lane is not None and ab.t_dispatched is not None:
            self._lane_latency.labels(lane=str(ab.lane)).observe(
                now - ab.t_dispatched)
            self._lane_dispatches.labels(lane=str(ab.lane)).inc()
        self._trace_pipeline_batch(ab, now)
        batch_obs = {"batchSize": len(ab.entries), "pipeline": "staged"}
        if ab.lane is not None:
            batch_obs["lane"] = ab.lane
        batch_obs.update({f"{k}Ms": round(v * 1000, 3)
                          for k, v in phases.items()})
        total_dt = 0.0
        for i, (entry, result) in enumerate(zip(ab.entries, final)):
            # end-to-end per query INCLUDING its queue wait — the
            # latency the caller actually experienced (the serial
            # drainer recorded only the batch's own wall time)
            dt = now - entry.t_enq
            total_dt += dt
            self._latency_hist.observe(dt)
            is_err = isinstance(result, HTTPError)
            self._observe_release(
                ARM_STABLE, dt, error=is_err and result.status >= 500)
            if is_err:
                self._query_errors.labels(
                    status=str(result.status)).inc()
            if entry.obs is not None:
                entry.obs.update(batch_obs)
                entry.obs.update(per_query_ms[i])
            entry.slot[0] = result
            entry.done.set()
        n_q = len(ab.entries)
        if n_q:
            with self._lock:
                n = self.request_count
                self.last_serving_sec = total_dt / n_q
                self.avg_serving_sec = ((self.avg_serving_sec * n
                                         + total_dt) / (n + n_q))
                self.request_count += n_q

    def _trace_pipeline_batch(self, ab: "_AssembledBatch",
                              now: float) -> None:
        """Reconstruct the staged-pipeline timeline onto every traced
        query of the batch (ISSUE 12): a ``batch`` parent span plus
        stage children — ``queue_wait`` from each entry's own enqueue
        time, host stages (assemble/supplement) from the pickup, and
        device stages (dispatch/device_wait/serve/readback/feedback)
        anchored at the REAL dispatch time, so the inter-stage queue
        hops show up as gaps on the Perfetto timeline instead of being
        smeared into the stages."""
        if self.tracer is None:
            return
        phases = ab.phases
        host = {k: phases[k] for k in ("assemble", "supplement")
                if k in phases}
        device = {k: phases[k]
                  for k in ("dispatch", "device_wait", "serve",
                            "readback", "feedback") if k in phases}
        for entry in ab.entries:
            tr = self._trace_of(entry.obs)
            if tr is None:
                continue
            tr.set_attr("engineInstanceId", ab.instance_id)
            tr.set_attr("arm", ARM_STABLE)
            tr.set_attr("pipeline", "staged")
            if ab.lane is not None:
                tr.set_attr("lane", ab.lane)
            wait = ((entry.obs or {}).get("queueWaitMs", 0.0)) / 1000.0
            t_pick = entry.t_enq + wait
            parent = tr.add_span(
                "batch", t_pick, now, batchSize=len(ab.entries),
                **({"lane": ab.lane} if ab.lane is not None else {}))
            if wait > 0:
                tr.add_span("queue_wait", entry.t_enq, t_pick,
                            parent_id=parent.span_id)
            add_stage_spans(tr, t_pick, host,
                            order=("assemble", "supplement"),
                            parent_id=parent.span_id)
            add_stage_spans(
                tr, ab.t_dispatched if ab.t_dispatched is not None
                else t_pick, device,
                order=("dispatch", "device_wait", "serve", "readback",
                       "feedback"),
                parent_id=parent.span_id)
            tr.exemplar(self._latency_hist.labels(),
                        now - entry.t_enq)

    def pipeline_status(self) -> dict:
        """Serving batch-path state for ``/status.json`` and the status
        page (ISSUE 9): architecture, deadline accounting, and the
        overlap snapshot that proves (or disproves) the device stays
        busy while host stages run."""
        b = self.batcher
        mode = ("staged" if isinstance(b, StagedPipeline)
                else "serial" if b is not None else "off")
        out: dict = {
            "mode": mode,
            "deadlineMs": self.config.queue_deadline_ms,
            "deadlineExceeded": int(self._deadline_exceeded
                                    .labels().value),
        }
        if isinstance(b, StagedPipeline):
            out["assembleWorkers"] = self.config.assemble_workers
            out["readbackWorkers"] = self.config.readback_workers
            out["depth"] = b.depth  # resolved (0 = auto in config)
            out["inFlight"] = self.overlap.active("device")
        snap = self.overlap.snapshot()
        if snap["wall_sec"] > 0:
            out["overlap"] = {
                "wallSec": round(snap["wall_sec"], 3),
                "deviceBusySec": round(snap["device_busy_sec"], 3),
                "deviceIdleFraction": round(
                    snap["device_idle_fraction"], 4),
                "overlapFraction": round(snap["overlap_fraction"], 4),
                "overlappedDispatches": int(
                    self._pipeline_overlapped.labels().value),
            }
        return out

    def _trace_of(self, obs: Optional[dict]):
        """The live request trace riding the obs dict (None when the
        caller is untraced or tracing is off)."""
        if obs is None or self.tracer is None:
            return None
        return obs.get("_trace")

    # -- the per-query hot path (CreateServer.scala:484-633) ---------------
    def query(self, query_json: Any, obs: Optional[dict] = None) -> Any:
        t0 = time.monotonic()
        phases: dict = {}
        trace = self._trace_of(obs)
        with self._lock:
            algorithms, models, serving = \
                self.algorithms, self.models, self.serving
            instance_id = self.instance.id
        if trace is not None:
            trace.set_attr("engineInstanceId", instance_id)
            trace.set_attr("arm", ARM_STABLE)
        query_cls = algorithms[0].query_class
        try:
            query = from_jsonable(query_cls, query_json)
        except (TypeError, ValueError) as e:
            self._query_errors.labels(status="400").inc()
            raise HTTPError(400, str(e))
        t1 = time.monotonic()
        phases["assemble"] = t1 - t0
        try:
            with activate_traces([trace]), self._transfer_guard():
                supplemented = serving.supplement(query)
                t2 = time.monotonic()
                phases["supplement"] = t2 - t1
                predictions = self._dispatch_predictions(
                    algorithms, models, supplemented)
                t3 = time.monotonic()
                phases["dispatch"] = t3 - t2
                # by design: serve sees the original query
                # (CreateServer.scala:511)
                prediction = serving.serve(query, predictions)
                t4 = time.monotonic()
                phases["serve"] = t4 - t3
            result = to_jsonable(prediction)
            t5 = time.monotonic()
            phases["readback"] = t5 - t4

            if self.config.feedback:
                result = self._feedback(query, query_json, result,
                                        instance_id)
                phases["feedback"] = time.monotonic() - t5
            result = self.plugins.process_output(query_json, result)
        except Exception:
            self._query_errors.labels(status="500").inc()
            self._observe_release(ARM_STABLE, time.monotonic() - t0,
                                  error=True)
            self._record_phases(phases)
            add_stage_spans(trace, t0, phases)
            raise

        dt = time.monotonic() - t0
        self._record_phases(phases)
        self._latency_hist.observe(dt)
        self._observe_release(ARM_STABLE, dt, error=False)
        if trace is not None:
            # per-query child spans (ISSUE 12): the phases run
            # back-to-back on this thread, so the sequential layout
            # from t0 IS the real timeline
            add_stage_spans(trace, t0, phases)
            trace.exemplar(self._latency_hist.labels(), dt)
        if obs is not None:
            obs.update({f"{k}Ms": round(v * 1000, 3)
                        for k, v in phases.items()})
        with self._lock:
            self.last_serving_sec = dt
            self.avg_serving_sec = (
                (self.avg_serving_sec * self.request_count + dt)
                / (self.request_count + 1))
            self.request_count += 1
        return result

    def _feedback(self, query: Any, query_json: Any, result: Any,
                  instance_id: str) -> Any:
        """Record the prediction as a ``predict`` event on entity type
        ``pio_pr`` (``CreateServer.scala:527-589``); injects ``prId`` into
        the response when the prediction carries one."""
        pr_id = _gen_pr_id()
        if isinstance(result, dict) and result.get("prId"):
            pr_id = result["prId"]
        properties = {"engineInstanceId": instance_id,
                      "query": to_jsonable(query_json),
                      "prediction": result}
        event = Event(event="predict", entity_type="pio_pr", entity_id=pr_id,
                      properties=properties,
                      pr_id=(query_json or {}).get("prId")
                      if isinstance(query_json, dict) else None)
        app_name = self.config.feedback_app_name
        try:
            app = self.ctx.storage.apps().get_by_name(app_name or "")
            if app is None:
                raise RuntimeError(
                    f"feedback app {app_name!r} not found")
            self.ctx.storage.events().insert(event, app.id)
        except Exception as e:  # feedback must never fail the query
            log.error("feedback event failed: %s", e)
        if isinstance(result, dict):
            result = dict(result, prId=pr_id)
        return result

    # -- progressive delivery (ISSUE 3) -------------------------------------
    def bind_candidate(self, instance: EngineInstance,
                       engine_params: Optional[EngineParams] = None,
                       models: Optional[List[Any]] = None) -> None:
        """Bind a candidate release ALONGSIDE the stable one (stable
        serving is untouched). The candidate serves per-query (batch 1)
        — at canary fractions there is nothing to coalesce — and warms
        its serving shapes in the background."""
        from ..workflow import core as wf

        with self._lock:
            stable_params = self.engine_params
        ep = engine_params or stable_params
        if models is None:
            models = wf.load_models_for_deploy(self.ctx, self.engine,
                                               instance, ep)
        algorithms = self.engine.make_algorithms(ep)
        for algo in algorithms:
            algo.bind_serving(self.ctx)
            self._bind_feature_cache(algo)
        # the candidate serves under the same quant policy as stable
        # (an A/B across precision is a config change, not a canary);
        # raw_models stay unquantized so promote re-derives through
        # the normal _bind
        to_prepare = models
        if self.config.serving_quant != "off":
            to_prepare = []
            for a, m in zip(algorithms, models):
                q = getattr(a, "quantize_serving_model", None)
                to_prepare.append(
                    q(m, self.config.serving_quant)
                    if q is not None else m)
        prepared = [a.prepare_serving_model(m, 1)
                    for a, m in zip(algorithms, to_prepare)]
        with self._lock:
            mode, mesh = self.serving_mode_resolved, self.serving_mesh
        if mode == "sharded" and mesh is not None:
            # sharded warm-swap (ISSUE 6): a candidate for a >1-HBM
            # stable must bind row-sharded too — a single-device copy
            # of it may not physically fit. Promote later re-places
            # through the normal _bind, so the stable arm re-derives
            # its own sharding rather than inheriting this one.
            prepared = self._shard_models(algorithms, prepared, mesh)
        binding = CandidateBinding(
            engine_params=ep, algorithms=algorithms, models=prepared,
            raw_models=list(models),
            serving=self.engine.make_serving(ep),
            instance=instance, warm_done=threading.Event())

        def _warm_candidate():
            for algo, model in zip(algorithms, prepared):
                warm = getattr(algo, "warm_serving", None)
                if warm is None:
                    continue
                try:
                    warm(model, 1)
                except Exception as e:  # noqa: BLE001 — cold is slow,
                    log.warning(        # not broken
                        "candidate warmup failed for %s: %s",
                        type(algo).__name__, e)
            binding.warm_done.set()

        threading.Thread(target=_warm_candidate, daemon=True,
                         name="candidate-warmup").start()
        with self._lock:
            self._candidate = binding
            stable_id = self.instance.id
        log.info("candidate release %s bound alongside stable %s",
                 instance.id, stable_id)

    def drop_candidate(self) -> None:
        with self._lock:
            cand = self._candidate
            self._candidate = None
        if cand is not None and self.cache is not None:
            # rollback: the dead arm's cached results must die with it
            # (stable's namespace — still serving — is left intact)
            self.cache.flush_namespace(cand.instance.id)

    @property
    def candidate_instance_id(self) -> Optional[str]:
        with self._lock:
            cand = self._candidate
        return cand.instance.id if cand is not None else None

    def promote_candidate(self) -> str:
        """Swap the candidate in as the stable release. The swap is the
        same single-lock ``_bind`` every deploy/reload takes —
        concurrent queries see either the old or the new binding in
        full, never a mix — and the batch ladder re-warms so
        post-promote traffic pays no cold compiles."""
        with self._lock:
            cand = self._candidate
            self._candidate = None
        if cand is None:
            raise HTTPError(409, "no candidate release bound")
        self._bind(cand.engine_params, cand.raw_models, cand.instance)
        self._rewarm()
        log.info("candidate %s promoted to serving stable",
                 cand.instance.id)
        return cand.instance.id

    def start_canary(self, instance_id: str,
                     fraction: Optional[float] = None,
                     shadow: bool = False, actor: str = "",
                     reason: str = "", policy=None,
                     models: Optional[List[Any]] = None):
        """Bind ``instance_id`` as the candidate and start the
        health-gated rollout loop (canary split or shadow mirror).
        Returns the live :class:`~..rollout.RolloutController`."""
        from ..rollout import HealthPolicy, RolloutController

        if self.rollout is not None and self.rollout.active:
            raise HTTPError(409, "a rollout is already in progress "
                            f"(candidate {self.rollout.instance_id})")
        inst = self.ctx.storage.engine_instances().get(instance_id)
        if inst is None:
            raise HTTPError(
                404, f"engine instance {instance_id!r} not found")
        if inst.status != STATUS_COMPLETED:
            raise HTTPError(
                400, f"instance {instance_id!r} is {inst.status}, "
                     f"not {STATUS_COMPLETED}")
        with self._lock:
            stable_id = self.instance.id
        if inst.id == stable_id:
            raise HTTPError(
                400, f"instance {instance_id!r} is already the "
                     f"serving stable")
        self.bind_candidate(inst, models=models)
        pol = policy or HealthPolicy()
        mode = "shadow" if shadow else "canary"
        start_fraction = (fraction if fraction is not None
                          else (1.0 if shadow else pol.ramp[0]))
        try:
            self.releases.start_candidate(
                inst.id, start_fraction, mode=mode, actor=actor,
                reason=reason)
        except Exception as e:  # noqa: BLE001 — history is best-effort
            log.error("release history write failed on %s: %s", mode, e)
        controller = RolloutController(
            self, self.releases, inst.id, policy=pol,
            fraction=start_fraction, shadow=shadow,
            actor=actor or "engine-server")
        self.rollout = controller
        controller.start()
        return controller

    def query_candidate(self, query_json: Any,
                        obs: Optional[dict] = None) -> Any:
        """Serve one query off the CANDIDATE binding (canary route or
        shadow mirror). Leaner than the stable path by design: no
        feedback events (the ``prId`` lineage belongs to the stable
        release — a rolled-back candidate must leave no trace in the
        event store) and no micro-batching."""
        t0 = time.monotonic()
        with self._lock:
            cand = self._candidate
        if cand is None:
            raise HTTPError(503, "no candidate release bound")
        try:
            query = from_jsonable(cand.algorithms[0].query_class,
                                  query_json)
        except (TypeError, ValueError) as e:
            # malformed input is the client's fault: it must not count
            # against the candidate's health
            self._query_errors.labels(status="400").inc()
            raise HTTPError(400, str(e))
        try:
            with self._transfer_guard():
                supplemented = cand.serving.supplement(query)
                predictions = self._predict_all(
                    cand.algorithms, cand.models, supplemented)
                prediction = cand.serving.serve(query, predictions)
            result = to_jsonable(prediction)
            result = self.plugins.process_output(query_json, result)
        except Exception:
            self._query_errors.labels(status="500").inc()
            self._observe_release(ARM_CANDIDATE,
                                  time.monotonic() - t0, error=True)
            raise
        dt = time.monotonic() - t0
        self._observe_release(ARM_CANDIDATE, dt, error=False)
        if obs is not None:
            obs["releaseArm"] = ARM_CANDIDATE
            tr = self._trace_of(obs)
            if tr is not None:
                tr.set_attr("arm", ARM_CANDIDATE)
                tr.set_attr("engineInstanceId", cand.instance.id)
                tr.add_span("candidate_serve", t0, t0 + dt)
        return result

    def mirror_to_candidate(self, query_json: Any) -> None:
        """Shadow mode: replay the query against the candidate from a
        pool thread. The answer is discarded (the arm metrics keep the
        outcome); errors are counted and swallowed — mirroring must
        never slow or fail stable traffic."""
        with self._lock:
            if self._mirror_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._mirror_pool = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="shadow-mirror")
            pool = self._mirror_pool

        def _mirror():
            try:
                self.query_candidate(query_json)
            except Exception:  # noqa: BLE001 — counted in arm metrics
                pass

        self._shadow_mirrors.inc()
        pool.submit(_mirror)

    # -- streaming incremental training (ISSUE 10) --------------------------
    def stream_snapshot(self, algo_index: int = 0):
        """The streaming trainer's read side: ``(instance_id, model)``
        of the CURRENT stable binding, or None when the indexed
        algorithm's model is not foldable (no id maps — not an ALS
        factor model). The pair is snapshotted under the binding lock
        so the fold-in solves against a model that actually served
        together with that instance id; the apply re-checks the id."""
        with self._lock:
            if not (0 <= algo_index < len(self.models)):
                return None
            model = self.models[algo_index]
            instance_id = self.instance.id
        if getattr(model, "user_ids", None) is None \
                or getattr(model, "item_ids", None) is None:
            return None
        return instance_id, model

    def apply_stream_delta(self, algo_index: int, new_model: Any,
                           touched_entities: List[str],
                           base_instance_id: str,
                           rows_updated: int = 0,
                           rows_inserted: int = 0) -> bool:
        """Hot-swap a fold-in delta into the serving binding: the
        streaming twin of promote's ``_bind``, scoped to one
        algorithm's model. Under the binding lock the base instance id
        is re-checked — a reload/promote that raced the fold-in wins
        and the apply returns False (the trainer's unadvanced cursor
        re-folds against the new base). Replicated lanes re-derive
        their per-device copies from the folded model so every lane
        serves the new rows. After the swap, cached results and pinned
        hot-tier rows for exactly the touched entities are
        invalidated (docs/streaming.md)."""
        with self._lock:
            if self.instance.id != base_instance_id:
                return False
            if not (0 <= algo_index < len(self.algorithms)):
                return False
            has_lanes = bool(self.lane_models)
            rep = (getattr(self.algorithms[algo_index],
                           "replicate_serving_model", None)
                   if has_lanes else None)
            devices = list(self.lane_devices) if has_lanes else []
        # per-device replication OUTSIDE the lock: device_put of a
        # whole factor table must not stall queries, and the algorithm
        # hook is dynamically bound. The id re-check below voids the
        # copies if a rebind raced us.
        lane_copies = ([rep(new_model, dev) for dev in devices]
                       if rep is not None
                       else [new_model] * len(devices))
        with self._lock:
            if self.instance.id != base_instance_id:
                return False
            self.models[algo_index] = new_model
            if self.lane_models:
                for lane, copy in enumerate(lane_copies):
                    self.lane_models[lane][algo_index] = copy
            self._stream_generation += 1
            self._stream_rows += int(rows_updated) + int(rows_inserted)
            self._stream_last_apply = time.time()
            cache = self.cache
        if cache is not None and touched_entities:
            # per-entity, not a flush: untouched entities' cached
            # results are still exactly right — that precision is the
            # point of folding rows instead of rebinding
            cache.invalidate_entities("user", touched_entities)
            if cache.hot is not None:
                # refresh ONLY when the swap actually dropped a pinned
                # entry: an unconditional refresh re-gathered the full
                # pinned table and re-warmed its k-ladder on every
                # fold-in even when no pinned entity was touched
                # (ISSUE 13 satellite) — pure wasted device work at
                # streaming cadence
                if cache.hot.invalidate(touched_entities):
                    cache.hot.refresh(wait=False)  # re-pin new rows
        return True

    def start_stream(self, config=None):
        """Attach (and start) the streaming trainer. ``config`` is a
        :class:`~predictionio_tpu.streaming.StreamConfig`; None builds
        one from the ``ServerConfig.stream_*`` knobs. Raises
        ``ValueError`` on a bad app/channel (deploy fails fast) and
        ``HTTPError`` 409 when one is already running."""
        from ..streaming import StreamConfig, StreamTrainer

        with self._lock:
            if self.stream is not None and self.stream.running:
                raise HTTPError(
                    409, "streaming trainer already running (consumer "
                         f"{self.stream.config.consumer!r}); stop it "
                         f"first")
        cfg = config or StreamConfig(
            interval_ms=self.config.stream_interval_ms,
            max_events=self.config.stream_max_events,
            consumer=self.config.stream_consumer,
            drift_threshold=self.config.stream_drift_threshold,
            canary_probes=self.config.stream_canary_probes)
        if not cfg.app_name:
            cfg.app_name = (self.config.stream_app_name
                            or self.config.feedback_app_name or "")
        if not cfg.app_name:
            raise ValueError(
                "streaming requires an app name (ServerConfig."
                "stream_app_name, --stream-app, or the request's "
                "appName) — the app whose event log the trainer tails")
        trainer = StreamTrainer(self, cfg)
        with self._lock:
            self.stream = trainer
            instance_id = self.instance.id
        trainer.start()
        try:
            self.releases.record(
                "stream-start", instance_id=instance_id,
                actor=f"stream-trainer:{cfg.consumer}",
                reason=f"tailing app {cfg.app_name!r} every "
                       f"{cfg.interval_ms:g}ms")
        except Exception as e:  # noqa: BLE001 — history is best-effort
            log.error("release history write failed on stream-start: "
                      "%s", e)
        log.info("streaming trainer started (app %s, consumer %s)",
                 cfg.app_name, cfg.consumer)
        return trainer

    def stop_stream(self, timeout: float = 10.0) -> bool:
        """Stop and detach the streaming trainer; False when none is
        attached. The durable cursor stays in EVENTDATA — a later
        start with the same consumer resumes exactly where this one
        stopped."""
        with self._lock:
            trainer = self.stream
            self.stream = None
            instance_id = self.instance.id
        if trainer is None:
            return False
        trainer.stop(timeout=timeout)
        try:
            self.releases.record(
                "stream-stop", instance_id=instance_id,
                actor=f"stream-trainer:{trainer.config.consumer}",
                reason=f"{trainer.applies} deltas applied, "
                       f"{trainer.events_consumed} events consumed")
        except Exception as e:  # noqa: BLE001 — history is best-effort
            log.error("release history write failed on stream-stop: "
                      "%s", e)
        return True

    def stream_lineage(self) -> dict:
        """What blend of batch + stream is actually serving (ISSUE 10
        satellite): the base full-retrain instance, how many fold-in
        generations sit on top of it, and how stale the serving model
        is — seconds since it last absorbed data (the last fold-in,
        else the base retrain's completion)."""
        with self._lock:
            base = self.instance
            gen = self._stream_generation
            rows = self._stream_rows
            last = self._stream_last_apply
            bound = self._stream_base_bound_at
            trainer = self.stream
        now = time.time()
        trained = getattr(base, "end_time", None)
        if last is not None:
            staleness = now - last
        elif trained is not None:
            try:
                staleness = max(0.0, now - trained.timestamp())
            except (OSError, OverflowError, ValueError):
                staleness = now - bound
        else:
            staleness = now - bound
        return {
            "baseInstanceId": base.id,
            "incrementalGeneration": gen,
            "incrementalRows": rows,
            "lastFoldInSecAgo": (round(now - last, 3)
                                 if last is not None else None),
            "stalenessSec": round(staleness, 3),
            "streaming": trainer is not None and trainer.running,
        }

    def remote_log(self, message: str, wait: bool = False) -> None:
        """Ship an error to the configured log collector
        (``remoteLog``, ``CreateServer.scala:435-446``); failures to ship
        are logged and swallowed. Ships from a daemon thread so a slow or
        dead collector never delays the error response (pass ``wait=True``
        to block, e.g. in tests)."""
        if not self.config.log_url:
            return
        import urllib.request

        with self._lock:
            instance_id = self.instance.id
        payload = (self.config.log_prefix + json.dumps({
            "engineInstance": instance_id,
            "message": message})).encode("utf-8")

        def ship():
            try:
                req = urllib.request.Request(self.config.log_url,
                                             data=payload, method="POST")
                with urllib.request.urlopen(req, timeout=5) as resp:
                    resp.read()
            except Exception as e:  # noqa: BLE001 — must not fail us
                log.error("Unable to send remote log: %s", e)

        if wait:
            ship()
        else:
            threading.Thread(target=ship, daemon=True,
                             name="remote-log").start()

    def _rewarm(self) -> None:
        """Re-warm after a rebind (reload/promote): the swapped-in
        models may have new device shapes (catalog growth changes the
        compiled [B, n_items] kernels) — re-warm so post-rebind traffic
        doesn't pay cold compiles while /status.json still says warm."""
        if not self.config.warm_start:
            return
        with self._lock:  # pairs with _warm_serving's check+set
            self._warm_gen += 1
            gen = self._warm_gen
            self.warm_done.clear()
        threading.Thread(target=self._warm_serving,
                         args=(gen,), daemon=True,
                         name="serving-rewarm").start()

    def reload(self) -> str:
        """Rebind through the release registry: the PINNED release when
        one is set, else the latest COMPLETED instance (the reference's
        ``MasterActor.receive`` :342-371 semantics). Every reload is a
        recorded release action."""
        from ..workflow import core as wf

        instances = self.ctx.storage.engine_instances()
        pinned = None
        try:
            pinned = self.releases.pinned_instance()
        except Exception as e:  # noqa: BLE001 — registry must never
            log.error(          # make a model unreloadable
                "release registry read failed; reloading latest: %s", e)
        with self._lock:
            serving_instance = self.instance
            engine_params = self.engine_params
        if pinned:
            latest = instances.get(pinned)
            if latest is None or latest.status != STATUS_COMPLETED:
                raise HTTPError(
                    409, f"pinned release {pinned!r} is not a "
                         f"COMPLETED engine instance (unpin or re-pin)")
        else:
            latest = instances.get_latest_completed(
                serving_instance.engine_id,
                serving_instance.engine_version,
                serving_instance.engine_variant)
            if latest is None:
                raise HTTPError(
                    404, "no COMPLETED engine instance to reload")
        models = wf.load_models_for_deploy(self.ctx, self.engine, latest,
                                           engine_params)
        self._bind(engine_params, models, latest)
        self._rewarm()
        try:
            self.releases.record_deploy(
                latest.id, actor="/reload",
                reason=("pinned release" if pinned
                        else "latest COMPLETED instance"))
        except Exception as e:  # noqa: BLE001 — history is best-effort
            log.error("release history write failed on reload: %s", e)
        log.info("reloaded engine instance %s%s", latest.id,
                 " (pinned)" if pinned else "")
        return latest.id


def build_app(server: QueryServer) -> HTTPApp:
    app = HTTPApp("engineserver")
    cfg = server.config

    _auth = make_key_auth(cfg.accesskey)

    def _phase_table() -> dict:
        """p50/p90/p99 per phase + end-to-end, from the live registry."""
        snap = server.metrics.snapshot()
        out = {}
        for key, label in (("pio_query_phase_seconds", "phases"),
                           ("pio_query_latency_seconds", "latency"),
                           ("pio_batch_occupancy", "batchOccupancy"),
                           ("pio_queue_depth", "queueDepth")):
            v = snap.get(key)
            if v:
                out[label] = v
        return out

    def _release_summary() -> dict:
        """Compact release state for /status.json and the status page."""
        rollout = server.rollout
        active = rollout is not None and rollout.active
        state: dict = {}
        try:
            state = server.releases.state()
        except Exception:  # noqa: BLE001 — status must always render
            pass
        return {
            "stable": server.instance.id,
            "pinned": state.get("pinned", ""),
            "candidate": server.candidate_instance_id or "",
            "mode": (("shadow" if rollout.shadow else "canary")
                     if active else ""),
            "fraction": rollout.splitter.fraction if active else 0.0,
        }

    def _pipeline_line() -> str:
        """One status-page line proving (or disproving) pipeline
        overlap: mode, in-flight, device idle fraction, sheds."""
        p = server.pipeline_status()
        if p["mode"] == "off":
            return ""
        parts = [f"serving pipeline: {p['mode']}"]
        ov = p.get("overlap")
        if ov:
            parts.append(f"device idle {ov['deviceIdleFraction'] * 100:.0f}%")
            parts.append(f"overlap {ov['overlapFraction'] * 100:.0f}%")
        if p.get("deadlineExceeded"):
            parts.append(f"deadline sheds {p['deadlineExceeded']}")
        return "<li>" + html.escape(" · ".join(parts)) + "</li>"

    def _stream_line() -> str:
        """One status-page line on the batch+stream blend serving
        right now (ISSUE 10): base instance, fold-in generations,
        staleness."""
        lin = server.stream_lineage()
        parts = [f"model lineage: base {lin['baseInstanceId']}"]
        if lin["incrementalGeneration"]:
            parts.append(f"+{lin['incrementalGeneration']} fold-ins "
                         f"({lin['incrementalRows']} rows)")
        parts.append(f"staleness {lin['stalenessSec']:.1f}s")
        if lin["streaming"]:
            parts.append("stream live")
        return ("<li>" + html.escape(" · ".join(parts))
                + " (<a href='/stream.json'>stream.json</a>)</li>")

    def _slo_line() -> str:
        """One status-page line on the SLO engine: specs watched,
        anything burning, the thinnest remaining budget (ISSUE 15)."""
        s = server.slo_status()
        if not s.get("enabled", False) or not s.get("specs"):
            return ""
        parts = [f"SLOs: {len(s['specs'])} watched"]
        burning = s.get("burning") or []
        if burning:
            parts.append("BURNING: " + ", ".join(burning))
        budgets = [(sp["budgetRemaining"], sp["name"])
                   for sp in s["specs"]
                   if sp.get("budgetRemaining") is not None]
        if budgets:
            worst, name = min(budgets)
            parts.append(f"thinnest budget {worst * 100:.1f}% "
                         f"({name})")
        return ("<li>" + html.escape(" · ".join(parts))
                + " (<a href='/slo.json'>slo.json</a>)</li>")

    def _trace_line() -> str:
        """One status-page line on the flight recorder: retained
        count/ring, live slow threshold, profiler state."""
        if server.tracer is None:
            return ""
        t = server.tracer.status()
        parts = [f"flight recorder: {t['retained']}/"
                 f"{t['ringCapacity']} retained"]
        if t.get("slowThresholdMs") is not None:
            parts.append(f"slow ≥ {t['slowThresholdMs']:.1f}ms")
        if server.profiler.active:
            parts.append("device profile capturing")
        return ("<li>" + html.escape(" · ".join(parts))
                + " (<a href='/trace.json'>trace.json</a>)</li>")

    def _cache_line() -> str:
        if server.cache is None:
            return ""
        tiers = server.cache.stats()["tiers"]
        parts = [f"{name} {t['hitRatio'] * 100:.0f}% of "
                 f"{t['hits'] + t['misses']}"
                 for name, t in tiers.items()]
        return ("<li>cache hit ratio: " + html.escape(", ".join(parts))
                + " (<a href='/cache.json'>cache.json</a>)</li>")

    def _sharding_line() -> str:
        """Suppressed sharding-debt census (ISSUE 14): how many
        pragma-justified sharding findings this build carries, per
        rule — the static pass's audit trail surfaced where an
        operator looks first."""
        sf = server.sharding_findings_status()
        if not sf["suppressed"]:
            return ""
        parts = ", ".join(f"{rule} {n}"
                          for rule, n in sf["byRule"].items())
        return (f"<li>sharding findings suppressed: "
                f"{sf['suppressed']} ({html.escape(parts)})</li>")

    def _mesh_panel() -> str:
        """Per-device lane/HBM occupancy while a mesh is active
        (ISSUE 6); empty in single mode — the page stays what it was."""
        mesh = server.mesh_status()
        if mesh.get("mode", "single") == "single":
            return ""
        hbm_by_dev = {str(e.get("device")): e for e in hbm_stats()}
        parts = [f"<h2>Mesh serving</h2><ul><li>mode: "
                 f"{html.escape(mesh['mode'])}</li>"]
        if mesh.get("meshShape"):
            shape = " × ".join(f"{k}={v}" for k, v
                               in mesh["meshShape"].items())
            parts.append(f"<li>mesh: {html.escape(shape)}</li>")
        if mesh.get("devices"):
            parts.append(f"<li>devices: {mesh['devices']}</li>")
        parts.append("</ul>")
        rows = []
        for lane in mesh.get("lanes", ()):  # replicated fan-out only
            hbm = hbm_by_dev.get(str(lane["deviceId"]), {})
            used = hbm.get("bytesInUse")
            rows.append(
                f"<tr><td>{lane['lane']}</td>"
                f"<td>{html.escape(str(lane['device']))}</td>"
                f"<td>{lane['dispatches']}</td>"
                f"<td>{lane['batchP50Ms'] if lane['batchP50Ms'] is not None else '-'}</td>"
                f"<td>{lane['batchP99Ms'] if lane['batchP99Ms'] is not None else '-'}</td>"
                f"<td>{used // (1 << 20) if used else '-'}</td></tr>")
        if rows:
            parts.append(
                "<table border='1'><tr><th>lane</th><th>device</th>"
                "<th>dispatches</th><th>batch p50 (ms)</th>"
                "<th>batch p99 (ms)</th><th>HBM used (MiB)</th></tr>"
                + "".join(rows) + "</table>")
        return "".join(parts)

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        inst = server.instance
        # percentile latency table (ISSUE 2): the status page shows
        # tails, not just means
        rows = []
        for name, s in sorted(
                server.spans_summary().items()):
            rows.append(
                f"<tr><td>{html.escape(name)}</td><td>{s['count']}</td>"
                f"<td>{s['p50'] * 1000:.3f}</td>"
                f"<td>{s['p90'] * 1000:.3f}</td>"
                f"<td>{s['p99'] * 1000:.3f}</td>"
                f"<td>{s['max_sec'] * 1000:.3f}</td></tr>")
        table = (
            "<h2>Latency percentiles</h2>"
            "<table border='1'><tr><th>series</th><th>count</th>"
            "<th>p50 (ms)</th><th>p90 (ms)</th><th>p99 (ms)</th>"
            "<th>max (ms)</th></tr>" + "".join(rows) + "</table>"
            if rows else "")
        # release panel (ISSUE 3): which release serves, what is
        # canarying/shadowing at what fraction, recent history
        rel = _release_summary()
        rel_rows = [
            f"<li>stable release: {html.escape(rel['stable'])}</li>"]
        if rel["pinned"]:
            rel_rows.append(
                f"<li>pinned: {html.escape(rel['pinned'])}</li>")
        if rel["candidate"]:
            rel_rows.append(
                f"<li>candidate: {html.escape(rel['candidate'])} "
                f"({html.escape(rel['mode'])} at "
                f"{rel['fraction'] * 100:.0f}%)</li>")
        hist_rows = []
        try:
            for ev in server.releases.history(limit=5):
                hist_rows.append(
                    f"<tr><td>{html.escape(ev.time[:19])}</td>"
                    f"<td>{html.escape(ev.action)}</td>"
                    f"<td>{html.escape(ev.instance_id)}</td>"
                    f"<td>{html.escape(ev.actor)}</td>"
                    f"<td>{html.escape(ev.reason)}</td></tr>")
        except Exception:  # noqa: BLE001 — status must always render
            pass
        release_panel = (
            "<h2>Release</h2><ul>" + "".join(rel_rows) + "</ul>"
            + ("<table border='1'><tr><th>time</th><th>action</th>"
               "<th>instance</th><th>actor</th><th>reason</th></tr>"
               + "".join(hist_rows) + "</table>" if hist_rows else "")
            + "<p><a href='/release.json'>release.json</a></p>")
        body = f"""<html><head><title>{html.escape(inst.engine_id)} \
- predictionio_tpu engine server</title></head><body>
<h1>Engine: {html.escape(inst.engine_id)} v{html.escape(inst.engine_version)}</h1>
<ul>
<li>engine instance: {html.escape(inst.id)}</li>
<li>variant: {html.escape(inst.engine_variant)}</li>
<li>started: {server.start_time.isoformat()}</li>
<li>requests served: {server.request_count}</li>
<li>average serving: {server.avg_serving_sec * 1000:.3f} ms</li>
<li>last serving: {server.last_serving_sec * 1000:.3f} ms</li>
<li>compiles since warm: {server.recompile_sentinel.since_armed}</li>
{_sharding_line()}{_pipeline_line()}{_stream_line()}{_cache_line()}{_slo_line()}{_trace_line()}
</ul>{_mesh_panel()}{release_panel}{table}
<p><a href="/metrics">Prometheus metrics</a> ·
<a href="/status.json">status.json</a></p></body></html>"""
        return Response(body=body, content_type="text/html")

    @app.route("GET", "/status.json")
    def status(req: Request) -> Response:
        from ..obs import TransferGuardCounter

        return json_response({
            "engineId": server.instance.engine_id,
            "engineVersion": server.instance.engine_version,
            "engineVariant": server.instance.engine_variant,
            "engineInstanceId": server.instance.id,
            "release": _release_summary(),
            "requestCount": server.request_count,
            "avgServingSec": server.avg_serving_sec,
            "lastServingSec": server.last_serving_sec,
            "servingWarm": server.warm_done.is_set(),
            # True when THIS warm answered every ladder entry from the
            # AOT artifact store (ISSUE 19) — the lifecycle warm gate
            # logs artifact-vs-compile spin-ups off this flag
            "artifactWarm": bool(server._warm_report.get("artifact")),
            "warmReport": server._warm_report,
            "lifecycle": server.lifecycle,
            "transferGuard": cfg.transfer_guard or "off",
            "transferGuardViolations": TransferGuardCounter.total(),
            "recompile": server.recompile_sentinel.snapshot(),
            "pipeline": server.pipeline_status(),
            "slo": server.slo_status(),
            "trace": (server.tracer.status()
                      if server.tracer is not None
                      else {"enabled": False}),
            "lineage": server.stream_lineage(),
            "stream": (server.stream.status()
                       if server.stream is not None
                       else {"running": False}),
            "mesh": server.mesh_status(),
            "degraded": server.degraded_status(),
            # the serving-quant sizing claim is read off these two
            # blocks together: servingKernel says the wire dtype, hbm
            # says the resident bytes it produced (docs/kernels.md)
            "servingKernel": server.serving_kernel_status(),
            "shardingFindings": server.sharding_findings_status(),
            "hbm": hbm_stats(),
            "cache": (server.cache.stats() if server.cache is not None
                      else {"enabled": False}),
            # hot-key telemetry (ISSUE 17): the fleet aggregator
            # merges these per-replica sketches into the fleet top-K
            "hotKeys": (server.hotkeys.snapshot()
                        if server.hotkeys is not None
                        else {"enabled": False}),
            **_phase_table(),
        })

    # -- service-level objectives (ISSUE 15, docs/slo.md) --------------------
    @app.route("GET", "/slo.json")
    def slo_json(req: Request) -> Response:
        """Live SLO state: per-spec burn rates (fast/slow window),
        error-budget remaining, breach/violation accounting — what
        ``ptpu slo status`` prints."""
        return json_response(server.slo_status())

    # -- streaming incremental training (ISSUE 10) ---------------------------
    @app.route("GET", "/stream.json")
    def stream_json(req: Request) -> Response:
        """Streaming-trainer state + model lineage (what ``ptpu stream
        status`` prints)."""
        trainer = server.stream
        if trainer is None:
            return json_response({
                "running": False,
                "lineage": server.stream_lineage(),
                "hint": "POST /stream/start {\"appName\": ...} (or "
                        "deploy with --stream) to attach the "
                        "incremental trainer"})
        return json_response({**trainer.status(),
                              "lineage": server.stream_lineage()})

    @app.route("POST", "/stream/start")
    def stream_start(req: Request) -> Response:
        """Attach the streaming trainer to this live server:
        ``{"appName": ..., "channelName": ..., "intervalMs": ...,
        "maxEvents": ..., "consumer": ..., "driftThreshold": ...,
        "canaryProbes": ...}`` — every field optional when the deploy
        config already names the app."""
        from ..streaming import StreamConfig

        _auth(req)
        try:
            body = req.json() or {}
        except (ValueError, UnicodeDecodeError):
            body = {}
        scfg = StreamConfig(
            app_name=str(body.get("appName")
                         or cfg.stream_app_name
                         or cfg.feedback_app_name or ""),
            channel_name=body.get("channelName") or None,
            consumer=str(body.get("consumer") or cfg.stream_consumer),
            interval_ms=float(body.get("intervalMs",
                                       cfg.stream_interval_ms)),
            max_events=int(body.get("maxEvents",
                                    cfg.stream_max_events)),
            drift_threshold=float(body.get("driftThreshold",
                                           cfg.stream_drift_threshold)),
            canary_probes=int(body.get("canaryProbes",
                                       cfg.stream_canary_probes)))
        try:
            trainer = server.start_stream(scfg)
        except ValueError as e:
            raise HTTPError(400, str(e))
        return json_response({"message": "Streaming trainer started.",
                              "stream": trainer.status()})

    @app.route("POST", "/stream/stop")
    def stream_stop(req: Request) -> Response:
        _auth(req)
        if not server.stop_stream():
            raise HTTPError(409, "no streaming trainer is running")
        return json_response({"message": "Streaming trainer stopped."})

    # -- serving cache operations (ISSUE 4) ----------------------------------
    @app.route("GET", "/cache.json")
    def cache_json(req: Request) -> Response:
        """Per-tier hit/miss/eviction/invalidation stats (what
        ``ptpu cache stats`` prints)."""
        if server.cache is None:
            return json_response({"enabled": False,
                                  "hint": "deploy with --cache (or "
                                          "ServerConfig(serving_cache="
                                          "True)) to enable the "
                                          "serving cache hierarchy"})
        return json_response(server.cache.stats())

    @app.route("POST", "/cache/flush")
    def cache_flush(req: Request) -> Response:
        """Operator flush of every tier (``ptpu cache flush``);
        key-guarded like the other control routes."""
        _auth(req)
        if server.cache is None:
            raise HTTPError(409, "serving cache is not enabled")
        return json_response({"message": "Flushed.",
                              "removed": server.cache.flush_all()})

    @app.route("POST", "/queries.json")
    def queries(req: Request) -> Response:
        try:
            query_json = req.json()
        except (ValueError, UnicodeDecodeError) as e:
            raise HTTPError(400, str(e))
        try:
            # progressive delivery: the splitter routes a cohort of
            # queries to the candidate (canary) or mirrors them to it
            # (shadow) while the stable arm keeps serving everyone else
            rollout = server.rollout
            if rollout is not None and rollout.active \
                    and rollout.splitter.routes_candidate(query_json):
                if rollout.shadow:
                    server.mirror_to_candidate(query_json)
                else:
                    try:
                        return json_response(server.serve_candidate(
                            query_json, obs=req.obs))
                    except HTTPError as e:
                        if e.status != 503:
                            raise
                        # the candidate unbound mid-flight (rollback
                        # won the race) — the stable arm serves below
            # the cached stable entry: query cache → singleflight →
            # micro-batcher / per-query pipeline (ISSUE 4)
            result = server.serve(query_json, obs=req.obs)
            if isinstance(result, HTTPError):
                raise result
            return json_response(result)
        except HTTPError as e:
            # batch-wide failures are logged ONCE by the batcher, not by
            # each of the coalesced handler threads
            if e.status >= 500 and not getattr(e, "_remote_logged", False):
                server.remote_log(e.message)
            raise
        except Exception as e:  # noqa: BLE001 — log then surface as 500
            server.remote_log(str(e))
            raise

    @app.route("POST", "/reload")
    def reload(req: Request) -> Response:
        _auth(req)
        instance_id = server.reload()
        return json_response({"message": "Reloading...",
                              "engineInstanceId": instance_id})

    # -- progressive delivery routes (ISSUE 3) ------------------------------
    @app.route("GET", "/release.json")
    def release_json(req: Request) -> Response:
        payload = server.releases.to_json()
        rollout = server.rollout
        payload["serving"] = {
            "stableInstanceId": server.instance.id,
            "candidateInstanceId": server.candidate_instance_id,
        }
        payload["rollout"] = (rollout.status()
                              if rollout is not None else None)
        payload["arms"] = server.release_arms()
        return json_response(payload)

    @app.route("POST", "/release/canary")
    def release_canary(req: Request) -> Response:
        """Start a canary (or shadow) rollout of a COMPLETED instance:
        ``{"instanceId": ..., "fraction": 0.05, "shadow": false,
        "reason": ...}``. The health gate ramps or rolls back from
        here; ``/release.json`` tracks it."""
        from ..rollout.splitter import parse_fraction

        _auth(req)
        try:
            body = req.json() or {}
        except (ValueError, UnicodeDecodeError) as e:
            raise HTTPError(400, str(e))
        instance_id = body.get("instanceId") or ""
        if not instance_id:
            raise HTTPError(400, "instanceId required")
        fraction = None
        if body.get("fraction") is not None:
            try:
                fraction = parse_fraction(body["fraction"])
            except ValueError as e:
                raise HTTPError(400, str(e))
        controller = server.start_canary(
            instance_id, fraction=fraction,
            shadow=bool(body.get("shadow")),
            actor=body.get("actor") or "http",
            reason=body.get("reason") or "")
        return json_response({"message": "Rollout started.",
                              "rollout": controller.status()})

    @app.route("POST", "/release/promote")
    def release_promote(req: Request) -> Response:
        """Force-promote the live candidate to stable (skips the rest
        of the ramp; the operator override for shadow rollouts)."""
        _auth(req)
        try:
            body = req.json() or {}
        except (ValueError, UnicodeDecodeError):
            body = {}
        reason = body.get("reason") or "operator promote"
        rollout = server.rollout
        if rollout is not None and rollout.active:
            rollout.promote(reason)
            return json_response({"message": "Promoted.",
                                  "engineInstanceId":
                                      rollout.instance_id})
        instance_id = server.promote_candidate()  # 409 when none bound
        try:
            server.releases.promote(instance_id, actor="http",
                                    reason=reason)
        except Exception as e:  # noqa: BLE001 — serving already moved
            log.error("release history write failed on promote: %s", e)
        return json_response({"message": "Promoted.",
                              "engineInstanceId": instance_id})

    @app.route("POST", "/release/rollback")
    def release_rollback(req: Request) -> Response:
        """Roll back: abort the live candidate, or — with none bound —
        revert stable to the previous release and rebind it."""
        _auth(req)
        try:
            body = req.json() or {}
        except (ValueError, UnicodeDecodeError):
            body = {}
        reason = body.get("reason") or "operator rollback"
        rollout = server.rollout
        if rollout is not None and rollout.active:
            rollout.rollback(reason)
            return json_response({"message": "Rolled back.",
                                  "engineInstanceId":
                                      server.instance.id})
        try:
            server.releases.rollback(actor="http", reason=reason)
        except ValueError as e:
            raise HTTPError(409, str(e))
        instance_id = server.reload()  # binds the re-pinned previous
        return json_response({"message": "Rolled back.",
                              "engineInstanceId": instance_id})

    @app.route("POST", "/drain")
    def drain(req: Request) -> Response:
        """Flip this replica to lifecycle=draining (ISSUE 18): it
        keeps serving until in-flight/in-deadline work finishes, but
        advertises the state so the router sends nothing new and the
        fleet aggregator retires it from rollups without an up-flap.
        Idempotent; does NOT shut the server down — the lifecycle
        manager (or operator) does that once inflight hits zero."""
        _auth(req)
        server.enter_drain()
        return json_response({"lifecycle": server.lifecycle})

    @app.route("POST", "/stop")
    def stop(req: Request) -> Response:
        _auth(req)
        if server.rollout is not None:
            server.rollout.stop()  # loop only; bindings die with us
        server.stop_stream()  # cursor already persisted; no-op if off
        server.stop_slo()  # evaluator thread only; series stay readable

        def delayed_shutdown():
            # grace period so THIS response flushes before the listener
            # dies (otherwise the client sees a closed connection and
            # `undeploy` reports failure for a stop that worked)
            time.sleep(0.25)
            app_server_ref[0].shutdown()
            # listener down → no new submits; drain the batcher /
            # pipeline workers and the sniffer pump
            server.close()

        threading.Thread(target=delayed_shutdown, daemon=True).start()
        return json_response({"message": "Shutting down..."})

    @app.route("GET", "/plugins.json")
    def plugins_json(req: Request) -> Response:
        return json_response({"plugins": server.plugins.describe()})

    @app.route("GET", r"/plugins/(?P<ptype>[^/]+)/(?P<pname>[^/]+)"
                      r"(?P<rest>(/[^/]+)*)")
    def plugin_rest(req: Request) -> Response:
        """Per-plugin REST surface (``CreateServer.scala:684-689``):
        ``/plugins/<outputblockers|outputsniffers>/<name>/<args…>`` calls
        the plugin's ``handle_rest`` with the remaining segments.
        Key-guarded like the other control routes (plugins may expose
        internal state)."""
        from .plugins import resolve_plugin

        _auth(req)
        plugin, args = resolve_plugin(
            {"outputblockers": server.plugins.output_blockers,
             "outputsniffers": server.plugins.output_sniffers},
            req.path_params["ptype"], req.path_params["pname"],
            req.path_params["rest"])
        return json_response(plugin.handle_rest(args))

    # -- on-demand device profiling (ISSUE 12, docs/tracing.md) -------------
    @app.route("POST", "/profile")
    def profile_start(req: Request) -> Response:
        """Capture a ``jax.profiler`` device trace for a bounded window
        into the served artifact dir: ``{"durationMs": 1000}``.
        Key-guarded like every control route — profiles expose
        internals and cost real overhead while running."""
        _auth(req)
        try:
            body = req.json() or {}
        except (ValueError, UnicodeDecodeError):
            body = {}
        try:
            info = server.profiler.start(
                float(body.get("durationMs", 1000.0)))
        except ValueError as e:
            raise HTTPError(400, str(e))
        except RuntimeError as e:
            raise HTTPError(409, str(e))
        return json_response({
            "message": "Profiling.", **info,
            "hint": "poll GET /profile.json; load the artifact dir "
                    "with TensorBoard's profile plugin or "
                    "ui.perfetto.dev"}, 202)

    @app.route("GET", "/profile.json")
    def profile_json(req: Request) -> Response:
        """Capture status + served artifacts + the per-executable
        compile-time table (what ``pio_compiles_since_warm`` counts,
        itemized)."""
        return json_response({
            **server.profiler.status(),
            "compileTable": server.recompile_sentinel.compile_table(),
        })

    # /metrics + request instrumentation through the server's own
    # registry (the engine server keeps its bespoke /status.json above);
    # the tracer mount adds traceparent propagation + GET /trace.json
    mount_metrics(app, server.metrics, server_name="engineserver",
                  tracer=(server.tracer if server.tracer is not None
                          else False))
    app.access_log_sample = cfg.access_log_sample

    app_server_ref: List[AppServer] = []
    app._server_ref = app_server_ref  # type: ignore[attr-defined]
    return app


class _Submit:
    """One caller's queue entry: query + completion slot + timing. The
    caller blocks on ``done``; whichever stage finishes (or sheds) the
    entry writes ``slot[0]`` and sets the event. ``abandoned`` flips
    when the submitter's deadline expired — later stages skip the
    corpse instead of doing device work nobody will read."""

    __slots__ = ("query_json", "done", "slot", "t_enq", "deadline",
                 "obs", "abandoned")

    def __init__(self, query_json: Any, obs: Optional[dict],
                 deadline_sec: float):
        self.query_json = query_json
        self.done = threading.Event()
        self.slot: List[Any] = [None]
        self.t_enq = time.monotonic()
        self.deadline = (self.t_enq + deadline_sec) if deadline_sec > 0 \
            else None
        self.obs = obs
        self.abandoned = False


#: close sentinel for the batcher worker queues: each worker consumes
#: exactly one and exits; ``_form_batch`` re-queues any it pulls on a
#: sibling's behalf (see ``MicroBatcher.close`` / ``StagedPipeline.close``)
_CLOSE = object()


def _deadline_submit(batcher, server: QueryServer, query_json: Any,
                     obs: Optional[dict]) -> Any:
    """Shared submit with the per-query deadline (ISSUE 9 satellite):
    enqueue, wait at most the deadline, and on expiry shed — count it,
    mark the entry abandoned so pickup skips it, and return a 503
    instead of hanging the HTTP worker on a wedged dispatch forever."""
    e = _Submit(query_json, obs, batcher.deadline_sec)
    batcher._q.put(e)
    if e.deadline is None:
        e.done.wait()
        return e.slot[0]
    if e.done.wait(timeout=batcher.deadline_sec):
        return e.slot[0]
    e.abandoned = True
    server._deadline_exceeded.inc()
    server._query_errors.labels(status="503").inc()
    ms = batcher.deadline_sec * 1000.0
    return HTTPError(
        503, f"query shed: not served within the {ms:.0f}ms queue "
             f"deadline (server saturated or dispatch wedged)")


def _form_batch(q, first: _Submit, max_batch: int,
                window: float) -> List[_Submit]:
    """Greedy ADAPTIVE batch formation, shared by both batch-path
    architectures: while a dispatch is in flight, arrivals pile up and
    the next batch takes everything queued (up to ``max_batch``) with
    no timed wait — batch size self-tunes to arrival rate × service
    time. The ``window`` wait applies only when the queue held a single
    query, giving truly concurrent arrivals one chance to coalesce.
    (The round-4 batcher waited the window from EVERY first arrival —
    under 8-thread load the backlog grew unboundedly and p99 hit 11.4s;
    greedy draining is the fix.) Entries whose submitter already gave
    up (deadline expired → ``abandoned``) are completed as shed corpses
    and never join the batch."""
    import queue

    batch: List[_Submit] = []

    def admit(e: _Submit) -> None:
        if e.abandoned or (e.deadline is not None
                           and time.monotonic() > e.deadline):
            # the submitter timed out and already returned (and
            # counted) its 503 — complete the corpse so nothing
            # downstream spends device time on it
            e.slot[0] = HTTPError(503, "query deadline exceeded "
                                       "while queued")
            e.done.set()
            return
        batch.append(e)

    admit(first)
    waited = False
    while len(batch) < max_batch:
        try:
            nxt = q.get_nowait()
        except queue.Empty:
            if waited or len(batch) > 1 or window <= 0:
                break
            # a lone query waits the window once: either a concurrent
            # burst lands (batch grows, greedy loop resumes) or it
            # serves solo with bounded latency
            waited = True
            try:
                nxt = q.get(timeout=window)
            except queue.Empty:
                break
        if nxt is _CLOSE:
            # a close sentinel meant for a sibling drainer — put it
            # back for that thread and stop batching
            q.put(nxt)
            break
        admit(nxt)
    return batch


class MicroBatcher:
    """Coalesces concurrent queries into one device dispatch — the
    SERIAL drainer architecture (``ServerConfig.serving_pipeline=
    "serial"``; the staged :class:`StagedPipeline` is the default).

    Each HTTP worker thread enqueues its query and blocks; ``pipeline``
    drainer threads run ``QueryServer.query_batch`` — parse, supplement,
    dispatch, block on the device, serialize — and wake the callers.

    With ``lanes`` > 1 (replicated fan-out, ISSUE 6), drainer ``i``
    serves lane ``i % lanes``: consecutive micro-batches land
    round-robin on different devices (each with its own full model
    copy and its own compiled executables), so N chips serve ~N×
    the single-lane micro-batch qps with zero cross-device traffic
    on the serve path.
    """

    def __init__(self, server: QueryServer, window_ms: float = 2.0,
                 max_batch: int = 128, pipeline: int = 4,
                 lanes: int = 1, deadline_ms: float = 0.0):
        import queue

        self.server = server
        self.window = max(window_ms, 0.0) / 1000.0
        self.max_batch = max(max_batch, 1)
        self.lanes = max(lanes, 1)
        self.deadline_sec = max(deadline_ms, 0.0) / 1000.0
        # ptpu: allow[unbounded-queue] — every entry has an HTTP worker
        # thread blocked on its done-Event, so depth is bounded by the
        # server's connection concurrency; past the queue deadline,
        # _deadline_submit sheds with a counted 503
        self._q: "queue.Queue" = queue.Queue()
        self._threads = [
            threading.Thread(target=self._drain, daemon=True,
                             args=(i % self.lanes
                                   if self.lanes > 1 else None,),
                             name=f"query-microbatcher-{i}")
            for i in range(max(pipeline, 1))]
        for t in self._threads:
            t.start()

    def submit(self, query_json: Any, obs: Optional[dict] = None) -> Any:
        return _deadline_submit(self, self.server, query_json, obs)

    def close(self, timeout: float = 5.0) -> None:
        """Stop the drainer threads: one close sentinel per live
        drainer (each consumes exactly one and exits; ``_form_batch``
        re-queues any it pulls on a sibling's behalf), then join.
        Queued work ahead of the sentinels still serves — no caller
        blocked on its done-Event is stranded. Idempotent."""
        live = [t for t in self._threads if t.is_alive()]
        for _ in live:
            self._q.put(_CLOSE)
        deadline = time.monotonic() + timeout
        for t in live:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def _drain(self, lane: Optional[int] = None) -> None:
        while True:
            first = self._q.get()
            if first is _CLOSE:
                return
            # queue depth at pickup: how much backlog this batch found —
            # the arrival-rate × service-time signal the round-4
            # unbounded-backlog pathology would have shown immediately
            depth = self._q.qsize() + 1
            self.server._queue_depth.observe(depth)
            if lane is not None:
                self.server._lane_depth.labels(
                    lane=str(lane)).observe(depth)
            batch = _form_batch(self._q, first, self.max_batch,
                                self.window)
            if not batch:
                continue
            t_pick = time.monotonic()
            phase = self.server._phase_hist.labels(phase="queue_wait")
            obs_list: List[Optional[dict]] = []
            for e in batch:
                wait = t_pick - e.t_enq
                phase.observe(wait)
                if e.obs is not None:
                    e.obs["queueWaitMs"] = round(wait * 1000, 3)
                    tr = self.server._trace_of(e.obs)
                    if tr is not None:
                        tr.add_span("queue_wait", e.t_enq, t_pick)
                obs_list.append(e.obs)
            # lane supervision (ISSUE 11): redistribute a dead lane's
            # traffic at pickup and fail a dispatch over to surviving
            # lanes before failing the batch (mirrors StagedPipeline)
            attempts = ([None] if lane is None
                        else self.server.lane_attempt_order(lane))
            results = None
            for n_try, eff in enumerate(attempts):
                try:
                    results = self.server.query_batch(
                        [e.query_json for e in batch], obs_list=obs_list,
                        lane=eff)
                    if eff is not None:
                        self.server._lane_ok(eff)
                    break
                except Exception as exc:  # noqa: BLE001 — isolate batch
                    if eff is not None:
                        self.server._lane_error(eff, exc)
                    if n_try + 1 < len(attempts):
                        continue
                    self.server.remote_log(str(exc))  # once per batch
                    err = HTTPError(500, str(exc))
                    err._remote_logged = True
                    results = [err] * len(batch)
            for e, result in zip(batch, results):
                e.slot[0] = result
                e.done.set()


class _AssembledBatch:
    """A batch between pipeline stages: the parse/supplement output
    plus the binding SNAPSHOT it was assembled against. Every stage
    uses the carried snapshot — a reload/promote mid-flight serves
    either the old or the new binding in full, never a mix."""

    __slots__ = ("entries", "queries", "out", "live", "supplemented",
                 "algorithms", "models", "lane_models", "serving",
                 "instance_id", "phases", "pending", "lane",
                 "t_dispatched")

    def __init__(self, entries, queries, out, live, supplemented,
                 algorithms, models, lane_models, serving, instance_id,
                 phases):
        self.entries = entries
        self.queries = queries
        self.out = out
        self.live = live
        self.supplemented = supplemented
        self.algorithms = algorithms
        self.models = models
        self.lane_models = lane_models
        self.serving = serving
        self.instance_id = instance_id
        self.phases = phases
        self.pending = None
        self.lane: Optional[int] = None
        self.t_dispatched: Optional[float] = None


class StagedPipeline:
    """Continuous-batching serving pipeline (ISSUE 9,
    docs/serving-pipeline.md) — the staged replacement for the serial
    drainer on the hottest path in the repo.

    Three stages with bounded hand-off queues:

    - **assemble** (host pool, ``assemble_workers`` threads): greedy
      adaptive batch formation (same policy as the serial drainer),
      JSON→query parse — per-query 400s complete IMMEDIATELY, a
      malformed query never waits on a device round trip — and
      concurrent supplement. All of it runs while the device chews on
      earlier batches.
    - **dispatch** (one thread per lane): takes the next assembled
      batch and ENQUEUES its device executables via
      ``workflow.batch_predict.dispatch_batch``. JAX async dispatch
      returns as soon as the work is queued, so batch k+1 launches
      before batch k's results exist — the device never waits for
      host work. In replicated fan-out each dispatcher owns its lane's
      device; in sharded mode the single dispatcher serializes the
      mesh launches exactly as ``_mesh_dispatch_lock`` requires.
    - **readback** (host pool, ``readback_workers`` threads): blocks on
      the device arrays (``PendingBatch.resolve``), serves, serializes,
      records feedback and metrics, wakes the callers
      (``QueryServer._finish_pipeline_batch``).

    Backpressure: the dispatch and readback queues are bounded at
    ``depth`` entries per lane. When the device (or readback) falls
    behind, assemble blocks on the put, arrivals pool in the submit
    queue, and the per-query deadline sheds them with 503 —
    queueing collapse degrades into fast, counted rejections instead
    of unbounded latency.
    """

    def __init__(self, server: QueryServer, window_ms: float = 2.0,
                 max_batch: int = 128, lanes: int = 1,
                 assemble_workers: int = 2, readback_workers: int = 2,
                 depth: int = 4, deadline_ms: float = 0.0,
                 dispatch_workers: int = 1):
        import queue

        self.server = server
        self.window = max(window_ms, 0.0) / 1000.0
        self.max_batch = max(max_batch, 1)
        self.lanes = max(lanes, 1)
        self.deadline_sec = max(deadline_ms, 0.0) / 1000.0
        if depth <= 0:  # auto (ServerConfig.pipeline_depth = 0):
            # shallow where the "device" shares the host cores (CPU —
            # occupancy wins; deep pipelines just shred batch size),
            # deep where readback pays a real transfer/tunnel RTT that
            # must be hidden behind later batches' compute
            try:
                import jax

                depth = 2 if jax.default_backend() == "cpu" else 4
            except Exception:  # noqa: BLE001 — no backend: middle road
                depth = 2
        self.depth = depth
        # ptpu: allow[unbounded-queue] — every entry has an HTTP worker
        # thread blocked on its done-Event, so depth is bounded by the
        # server's connection concurrency; past the queue deadline,
        # _deadline_submit sheds with a counted 503
        self._q: "queue.Queue" = queue.Queue()
        self._dispatch_q: "queue.Queue" = queue.Queue(
            maxsize=depth * self.lanes)
        self._readback_q: "queue.Queue" = queue.Queue(
            maxsize=depth * self.lanes)
        # THE batching-dynamics knob: an assemble worker takes an
        # in-flight slot BEFORE it picks anything up, and the slot
        # frees only when a batch fully resolves. While the pipeline
        # holds `depth` unresolved batches per lane, no one is even
        # reading the submit queue — arrivals pool, and the next
        # pickup drains them greedily into one fat batch. Without
        # this, a fast assemble stage races ahead of the device and
        # shreds the workload into minimum-size batches (measured:
        # mean occupancy 1.7 vs the serial drainer's 4.8 at the same
        # load — and device efficiency scales with occupancy).
        self._inflight = threading.BoundedSemaphore(depth * self.lanes)
        # per-stage rosters so close() can stop the stages in pipeline
        # order (assemble first, readback last)
        self._assemble_threads: List[threading.Thread] = []
        self._dispatch_threads: List[threading.Thread] = []
        self._readback_threads: List[threading.Thread] = []
        for i in range(max(assemble_workers, 1)):
            self._assemble_threads.append(threading.Thread(
                target=self._assemble_loop, daemon=True,
                name=f"pipeline-assemble-{i}"))
        if self.lanes > 1:
            # replicated fan-out: ONE dispatcher per lane — a lane's
            # launches stay ordered on its own device
            for lane in range(self.lanes):
                self._dispatch_threads.append(threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    args=(lane,), name=f"pipeline-dispatch-{lane}"))
        else:
            # single binding: several dispatchers enqueue concurrently
            # (JAX async dispatch is thread-safe; sharded-mesh launches
            # serialize on _mesh_dispatch_lock inside the model). On a
            # TPU the device still executes in order; on backends whose
            # runtime can overlap independent executions (CPU CI, some
            # tunnels) this matches the serial drainer's in-flight
            # concurrency instead of regressing below it.
            for i in range(max(dispatch_workers, 1)):
                self._dispatch_threads.append(threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    args=(None,), name=f"pipeline-dispatch-{i}"))
        for i in range(max(readback_workers, 1)):
            self._readback_threads.append(threading.Thread(
                target=self._readback_loop, daemon=True,
                name=f"pipeline-readback-{i}"))
        self._threads: List[threading.Thread] = (
            self._assemble_threads + self._dispatch_threads
            + self._readback_threads)
        for t in self._threads:
            t.start()

    def close(self, timeout: float = 5.0) -> None:
        """Drain and stop the pipeline stage by stage, upstream first:
        assemble workers get their sentinels and join (nothing new
        enters the pipeline), then dispatch, then readback. Joining a
        stage before signalling the next guarantees a sentinel never
        overtakes an in-flight batch — every real batch still resolves
        and wakes its caller before the stage serving it exits.
        Idempotent."""
        deadline = time.monotonic() + timeout
        for q, roster in ((self._q, self._assemble_threads),
                          (self._dispatch_q, self._dispatch_threads),
                          (self._readback_q, self._readback_threads)):
            live = [t for t in roster if t.is_alive()]
            for _ in live:
                q.put(_CLOSE)
            for t in live:
                t.join(timeout=max(0.0, deadline - time.monotonic()))

    def submit(self, query_json: Any, obs: Optional[dict] = None) -> Any:
        return _deadline_submit(self, self.server, query_json, obs)

    # -- stage 1: assemble ---------------------------------------------------
    def _assemble_loop(self) -> None:
        server = self.server
        while True:
            # take an in-flight slot FIRST (see __init__): while the
            # pipeline is full, arrivals pool in the submit queue and
            # the eventual pickup coalesces them — adaptive batching
            self._inflight.acquire()
            handed_off = False
            try:
                first = self._q.get()
                if first is _CLOSE:
                    return  # the finally releases our in-flight slot
                depth = self._q.qsize() + 1
                server._queue_depth.observe(depth)
                server._pipeline_qdepth.labels(
                    queue="submit").observe(depth)
                batch = _form_batch(self._q, first, self.max_batch,
                                    self.window)
                if not batch:
                    continue
                t0 = time.monotonic()
                server.overlap.enter("assemble")
                try:
                    ab = self._assemble(batch)
                except Exception as e:  # noqa: BLE001 — isolate batch
                    server.remote_log(str(e))
                    err = HTTPError(500, str(e))
                    err._remote_logged = True
                    for entry in batch:
                        entry.slot[0] = err
                        entry.done.set()
                    ab = None
                finally:
                    server.overlap.exit("assemble")
                    server._pipeline_stage_hist.labels(
                        stage="assemble").observe(time.monotonic() - t0)
                if ab is not None and ab.entries:
                    self._dispatch_q.put(ab)
                    handed_off = True  # slot rides with the batch; the
                    # readback stage releases it after resolve
            finally:
                if not handed_off:
                    self._inflight.release()

    def _assemble(self, batch: List[_Submit]) -> _AssembledBatch:
        from ..workflow.batch_predict import supplement_batch

        server = self.server
        with server._lock:
            algorithms = server.algorithms
            models = server.models
            lane_models = list(server.lane_models)
            serving = server.serving
            instance_id = server.instance.id
        t_pick = time.monotonic()
        qwait = server._phase_hist.labels(phase="queue_wait")
        for e in batch:
            wait = t_pick - e.t_enq
            qwait.observe(wait)
            if e.obs is not None:
                e.obs["queueWaitMs"] = round(wait * 1000, 3)
        query_cls = algorithms[0].query_class
        entries: List[_Submit] = []
        queries: List[Any] = []
        t0 = time.monotonic()
        for e in batch:
            try:
                queries.append(from_jsonable(query_cls, e.query_json))
                entries.append(e)
            except (TypeError, ValueError) as err:
                # a malformed query completes HERE: its 400 never
                # rides the batch through the device
                server._query_errors.labels(status="400").inc()
                server._latency_hist.observe(time.monotonic() - e.t_enq)
                e.slot[0] = HTTPError(400, str(err))
                e.done.set()
        phases: dict = {"assemble": time.monotonic() - t0}
        out: List[Any] = [None] * len(entries)
        live: List[int] = []
        supplemented: List[Any] = []
        if entries:
            with server._transfer_guard():
                supplemented, live = supplement_batch(
                    serving, queries, out, timings=phases)
        return _AssembledBatch(
            entries=entries, queries=queries, out=out, live=live,
            supplemented=supplemented, algorithms=algorithms,
            models=models, lane_models=lane_models, serving=serving,
            instance_id=instance_id, phases=phases)

    # -- stage 2: dispatch ---------------------------------------------------
    def _dispatch_loop(self, lane: Optional[int] = None) -> None:
        from ..workflow.batch_predict import PendingBatch, dispatch_batch

        server = self.server
        while True:
            ab = self._dispatch_q.get()
            if ab is _CLOSE:
                return
            server._pipeline_qdepth.labels(queue="dispatch").observe(
                self._dispatch_q.qsize() + 1)
            if lane is not None and ab.lane_models:
                # lane supervision (ISSUE 11): a dead lane's batches
                # redistribute across survivors at pickup, and a
                # dispatch failure fails over to the other lanes
                # before it is allowed to fail the batch — during
                # detection no caller sees an error as long as one
                # lane still serves
                attempts = server.lane_attempt_order(lane)
                ab.lane = attempts[0]
                models = ab.lane_models[ab.lane]
                server._lane_depth.labels(lane=str(ab.lane)).observe(
                    self._dispatch_q.qsize() + 1)
            else:
                attempts = [None]
                models = ab.models
            t0 = time.monotonic()
            in_flight_before = server.overlap.enter("device")
            # fault attribution (ISSUE 12): an injection delivered on
            # this dispatch thread flags exactly this batch's traces
            batch_traces = [server._trace_of(e.obs)
                            for e in ab.entries]
            for n_try, eff in enumerate(attempts):
                if eff is not None:
                    ab.lane = eff
                    models = ab.lane_models[eff]
                try:
                    with activate_traces(batch_traces):
                        if eff is not None:
                            fire(F_LANE, lane=str(eff))
                        fire(F_DISPATCH)
                        with server._transfer_guard():
                            resolvers = dispatch_batch(
                                ab.algorithms, models, ab.supplemented,
                                timings=ab.phases) if ab.live else []
                    ab.pending = PendingBatch(ab.queries, ab.serving,
                                              ab.out, ab.live, resolvers)
                    if eff is not None:
                        server._lane_ok(eff)
                    break
                except Exception as e:  # noqa: BLE001 — one dispatch,
                    if eff is not None:  # count + maybe fail over
                        server._lane_error(eff, e)
                    if n_try + 1 < len(attempts):
                        continue
                    for i in ab.live:   # whole batch, no lane left
                        ab.out[i] = e
                    ab.pending = PendingBatch(ab.queries, ab.serving,
                                              ab.out, [], [])
            if in_flight_before > 0:
                # launched while an earlier batch was still on the
                # device: the continuous-batching overlap, counted
                server._pipeline_overlapped.inc()
            ab.t_dispatched = t0
            server._pipeline_stage_hist.labels(stage="dispatch").observe(
                time.monotonic() - t0)
            self._readback_q.put(ab)

    # -- stage 3: readback ---------------------------------------------------
    def _readback_loop(self) -> None:
        server = self.server
        while True:
            ab = self._readback_q.get()
            if ab is _CLOSE:
                return
            server._pipeline_qdepth.labels(queue="readback").observe(
                self._readback_q.qsize() + 1)
            t0 = time.monotonic()
            try:
                results = ab.pending.resolve(ab.phases)
            except Exception as e:  # noqa: BLE001 — resolve isolates
                results = [e] * len(ab.entries)  # internally; belt +
            finally:                             # braces for the rest
                server.overlap.exit("device")
                # the batch is off the device: free its in-flight slot
                # so assemble picks up the pooled backlog while WE are
                # still serializing results (that is the overlap)
                self._inflight.release()
            server.overlap.enter("readback")
            try:
                server._finish_pipeline_batch(ab, results)
            except Exception as e:  # noqa: BLE001 — isolate to batch
                server.remote_log(str(e))
                err = HTTPError(500, str(e))
                err._remote_logged = True
                for entry in ab.entries:
                    if not entry.done.is_set():
                        entry.slot[0] = err
                        entry.done.set()
            finally:
                server.overlap.exit("readback")
                server._pipeline_stage_hist.labels(
                    stage="readback").observe(time.monotonic() - t0)


def create_engine_server(server: QueryServer, host: str = "0.0.0.0",
                         port: int = 8000, ssl_context=None) -> AppServer:
    """Bind the engine server (reference default port 8000,
    ``CreateServer.scala:78``)."""
    app = build_app(server)
    srv = AppServer(app, host, port, ssl_context=ssl_context)
    app._server_ref.append(srv)  # type: ignore[attr-defined]
    return srv


def deploy(ctx: Context, engine: Engine, engine_params: EngineParams,
           engine_id: str = "default", engine_version: str = "1",
           engine_variant: str = "engine.json",
           config: Optional[ServerConfig] = None,
           host: str = "0.0.0.0", port: int = 8000,
           ssl_context=None) -> AppServer:
    """The ``pio deploy`` flow (``commands/Engine.scala:207`` →
    ``CreateServer``), through the release registry: bind the PINNED
    release when one is set, else the latest COMPLETED instance, and
    record the deploy so every model that reaches traffic has a
    recorded, reversible release."""
    from ..workflow import core as wf

    releases = ReleaseRegistry(ctx.storage, engine_id, engine_version,
                               engine_variant)
    pinned = None
    try:
        pinned = releases.pinned_instance()
    except Exception as e:  # noqa: BLE001 — registry must never make a
        log.error(          # model undeployable
            "release registry read failed; deploying latest: %s", e)
    if pinned:
        instance = ctx.storage.engine_instances().get(pinned)
        if instance is None or instance.status != STATUS_COMPLETED:
            raise RuntimeError(
                f"Pinned release {pinned!r} is not a COMPLETED engine "
                f"instance; `ptpu release pin --clear` or re-pin.")
    else:
        instance = ctx.storage.engine_instances().get_latest_completed(
            engine_id, engine_version, engine_variant)
        if instance is None:
            raise RuntimeError(
                f"No COMPLETED engine instance for {engine_id} "
                f"{engine_version} {engine_variant}; run train first.")
    models = wf.load_models_for_deploy(ctx, engine, instance, engine_params)
    server = QueryServer(ctx, engine, engine_params, models, instance, config)
    try:
        releases.record_deploy(
            instance.id, actor="pio deploy",
            reason=("pinned release" if pinned
                    else "latest COMPLETED instance"))
    except Exception as e:  # noqa: BLE001 — history is best-effort
        log.error("release history write failed on deploy: %s", e)
    return create_engine_server(server, host, port, ssl_context=ssl_context)


def build_artifacts(ctx: Context, engine: Engine,
                    engine_params: EngineParams, artifact_dir: str,
                    engine_id: str = "default",
                    engine_version: str = "1",
                    engine_variant: str = "engine.json",
                    config: Optional[ServerConfig] = None) -> dict:
    """The ``ptpu build --aot`` flow (ISSUE 19, docs/cold-start.md):
    bind the latest COMPLETED instance exactly as deploy would —
    same quantize/prepare/placement — then drive the serving warm
    ladder with AOT capture active, so every executable deploy will
    need lands serialized in ``artifact_dir`` under the store key a
    matching deploy derives. Deploys that pass the same dir warm by
    loading instead of compiling.

    ``config`` must match the eventual deploy on the key-bearing
    serving knobs (mode/quant/topk/batching/max_batch); observability
    side-cars are forced off here — they never affect the artifacts.
    """
    from dataclasses import replace

    from .. import aot
    from ..workflow import core as wf

    config = replace(config or ServerConfig(),
                     warm_start=False, streaming=False, feedback=False,
                     tracing=False, slo_interval_ms=0.0, hot_keys_k=0,
                     faults=None, artifact_dir=None)
    instance = ctx.storage.engine_instances().get_latest_completed(
        engine_id, engine_version, engine_variant)
    if instance is None:
        raise RuntimeError(
            f"No COMPLETED engine instance for {engine_id} "
            f"{engine_version} {engine_variant}; run train first.")
    models = wf.load_models_for_deploy(ctx, engine, instance,
                                       engine_params)
    server = QueryServer(ctx, engine, engine_params, models, instance,
                         config)
    try:
        key = server.artifact_key()
        store = aot.ArtifactStore(artifact_dir, key)
        t0 = time.perf_counter()
        with aot.capture_into(store):
            server._warm_serving(server._warm_gen)
        seconds = time.perf_counter() - t0
        path = store.flush()
        return {"path": path, "entries": len(store), "key": key,
                "seconds": seconds, "instance": instance.id}
    finally:
        server.stop_slo()
