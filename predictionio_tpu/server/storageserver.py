"""Storage server: the event log + metadata DAOs over HTTP.

The network-capable storage story (the role of the reference's
client-server backends — JDBC ``JDBCLEvents.scala:109-247``,
Elasticsearch ``ESLEvents.scala:106-150``, HBase
``HBEventsUtil.scala:76-110``): a TPU pod host with NO shared filesystem
reaches its event store through this server, which fronts any local
backend (SQLite by default). The REMOTE client backend
(``data/storage/remote.py``) speaks this protocol behind the standard
``EventStore``/DAO contracts, so engines and servers are oblivious.

Protocol (JSON unless noted; optional shared-secret auth via the
``X-PIO-Storage-Secret`` header):

- ``POST /v1/events/<app>/init|remove|batch|delete|find|aggregate``
- ``GET  /v1/events/<app>/get?id=``
- ``GET  /v1/events/<app>/columnar`` — ``.npz`` bulk payload
  (``ETag``/``If-None-Match`` so pod hosts re-download only on change)
- ``POST /v1/meta/<dao>/<method>`` — whitelisted DAO RPCs
- ``GET  /v1/status``

The bulk read stays columnar end-to-end: the server answers from its
backend's mmap'd sidecar and streams one compressed-free npz; clients
cache by ETag, so steady-state training reads cost one 304 round-trip.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import threading
import weakref
from typing import Optional

from ..data.event import Event
from ..data.storage import Storage
from ..data.storage.base import EventFilter
from ..data.storage.wire import (
    batch_from_npz,
    batch_to_npz,
    entity_from_doc,
    entity_to_doc,
    filter_from_doc,
)
from ..obs import MetricsRegistry
from .http import AppServer, HTTPApp, HTTPError, Request, Response, \
    json_response, mount_metrics

log = logging.getLogger("predictionio_tpu.storageserver")

#: DAO → RPC methods exposed (exactly the DAO contracts in base.py)
_META_METHODS = {
    "apps": {"insert", "get", "get_by_name", "get_all", "update",
             "delete"},
    "access_keys": {"insert", "get", "get_all", "get_by_app_id",
                    "update", "delete"},
    "channels": {"insert", "get", "get_by_app_id", "delete"},
    "engine_instances": {"insert", "get", "get_all", "update", "delete",
                         "get_completed"},
    "evaluation_instances": {"insert", "get", "get_all",
                             "get_completed", "update", "delete"},
    "models": {"insert", "get", "delete"},
}


#: (app_id, channel, with_props, float_props) → (weakref(event col),
#: version). The props=0 training read gets a FRESH zero-copy view per
#: select, so an on-batch memo never hits there — but every view shares
#: the parent's ``event`` array, which the backend's find_columnar
#: cache keeps alive (and replaces) exactly when the log changes.
_VER_MEMO: dict = {}
_VER_LOCK = threading.Lock()


def _batch_version(batch, memo_key=None) -> str:
    """Content stamp for ETag caching: a sha256 over the FULL bytes of
    every column — strided sampling (advisor r3) let edits on unsampled
    positions that compensate in a per-column sum collide, serving 304s
    over changed data forever. Steady-state polling is one dict lookup:
    the digest is memoized per request identity, anchored (by weakref
    identity) to the parent's ``event`` column, which survives
    zero-copy selects and is swapped for a new array exactly when the
    backend re-encodes."""
    import numpy as np

    # fast path: backends with a segment-log sidecar maintain a chained
    # per-segment content stamp at append time (O(delta), not O(total));
    # it moves exactly when the log content does, so it versions every
    # projection with no byte hashing at serve time. The request
    # identity is folded in: each (props, float_props, shard) view must
    # carry a DISTINCT ETag — clients poll different shards through
    # caches a log-level stamp alone would alias.
    stamp = getattr(batch, "content_stamp", None)
    if stamp:
        if memo_key is None:
            return stamp
        return hashlib.sha256(
            f"{stamp}|{memo_key}".encode()).hexdigest()[:32]
    # anchor on the ROOT buffer: shard/select views allocate a fresh
    # view object per request, but all of them chain (.base) back to
    # the backend's cached parent array / mmap, which is replaced
    # exactly when the log re-encodes
    anchor = batch.event
    while getattr(anchor, "base", None) is not None:
        anchor = anchor.base
    if memo_key is not None:
        with _VER_LOCK:
            ent = _VER_MEMO.get(memo_key)
        if ent is not None and ent[0]() is anchor:
            return ent[1]
    h = hashlib.sha256()
    h.update(str(batch.n).encode())
    cols = [batch.event, batch.entity_type, batch.entity_id,
            batch.target_type, batch.target_id, batch.event_time,
            batch.props_offsets, batch.props_blob]
    cols += [batch.float_props[k] for k in sorted(batch.float_props)]
    for arr in cols:
        # hashing inherently needs host bytes, but ONE C-ordered landing
        # suffices — the former asarray+ascontiguousarray pair copied
        # device columns twice. ptpu: allow[host-sync-in-hot-path]
        a = np.asarray(arr, order="C")
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    version = h.hexdigest()[:32]
    if memo_key is not None:
        try:
            ref = weakref.ref(anchor)
        except TypeError:
            ref = lambda: None  # noqa: E731 — non-ndarray anchors
        with _VER_LOCK:
            if len(_VER_MEMO) >= 4096:
                # keys carry client-controlled params (float_props,
                # shard_n): bound the table. Dead-anchor entries go
                # first; an adversarial residue is dropped wholesale.
                for k in [k for k, (r, _) in _VER_MEMO.items()
                          if r() is None]:
                    del _VER_MEMO[k]
                if len(_VER_MEMO) >= 4096:
                    _VER_MEMO.clear()
            _VER_MEMO[memo_key] = (ref, version)
    return version


def build_app(storage: Storage, secret: Optional[str] = None) -> HTTPApp:
    app = HTTPApp("storageserver")

    # telemetry (ISSUE 2): columnar-read cache efficiency + payload
    # volume ride beside the shared per-route latency histograms —
    # steady-state pod-host training should be ~all ETag hits
    registry = MetricsRegistry()
    columnar_reqs = registry.counter(
        "pio_columnar_requests_total",
        "Columnar bulk reads by outcome (hit = 304 ETag match)")
    columnar_bytes = registry.counter(
        "pio_columnar_bytes_total",
        "npz payload bytes served by columnar bulk reads")
    ingest_block_events = registry.counter(
        "pio_ingest_block_events_total",
        "events written via columnar block ingest")
    ingest_block_bytes = registry.counter(
        "pio_ingest_block_bytes_total",
        "npz payload bytes received by columnar block ingest")
    ingest_block_seconds = registry.histogram(
        "pio_ingest_block_seconds",
        "wall time of one columnar block decode+insert",
        bounds=[0.001, 0.005, 0.025, 0.1, 0.5, 2.0])
    mount_metrics(app, registry, server_name="storageserver",
                  status=lambda: {"status": "alive"})
    app.metrics_registry = registry  # type: ignore[attr-defined]

    def hdr(req: Request, name: str) -> str:
        # Request.headers preserves as-sent case; match insensitively
        for k, v in req.headers.items():
            if k.lower() == name:
                return v
        return ""

    def auth(req: Request) -> None:
        if secret and not hmac.compare_digest(
                hdr(req, "x-pio-storage-secret"), secret):
            raise HTTPError(401, "Invalid storage secret.")

    def chan(req: Request) -> Optional[int]:
        c = req.query.get("channel")
        return int(c) if c else None

    @app.route("GET", r"/v1/status")
    def status(req: Request) -> Response:
        auth(req)
        return json_response({"status": "alive"})

    # -- events ------------------------------------------------------------
    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/init")
    def ev_init(req: Request) -> Response:
        auth(req)
        ok = storage.events().init(int(req.path_params["app_id"]),
                                   chan(req))
        return json_response({"ok": bool(ok)})

    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/remove")
    def ev_remove(req: Request) -> Response:
        auth(req)
        ok = storage.events().remove(int(req.path_params["app_id"]),
                                     chan(req))
        return json_response({"ok": bool(ok)})

    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/batch")
    def ev_batch(req: Request) -> Response:
        auth(req)
        events = [Event.from_json(d) for d in req.json()]
        ids = storage.events().insert_batch(
            events, int(req.path_params["app_id"]), chan(req))
        return json_response({"ids": ids})

    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/import_jsonl")
    def ev_import(req: Request) -> Response:
        """Bulk import: body is a raw block of API-format JSON lines,
        loaded through the backing store's ``import_jsonl`` lane (the
        native C++ encode when the backing is segmentfs). Errors come
        back as a 200 with an ``error`` doc carrying the block-relative
        durable prefix — the client re-anchors it to file-global line
        numbers, which a transport-level error code could not carry."""
        auth(req)
        from ..data.storage.base import JsonlImportError

        try:
            # chunk > any block: the whole POST commits all-or-nothing,
            # so the client's acknowledged-blocks line accounting is
            # exact (a mid-block partial commit would make its resume
            # recipe duplicate events)
            n = storage.events().import_jsonl(
                req.body, int(req.path_params["app_id"]), chan(req),
                chunk=1 << 62)
        except JsonlImportError as e:
            return json_response({"error": {
                "lineno": e.lineno,
                "committed_lines": e.committed_lines,
                "committed_events": e.committed_events,
                "message": str(e.cause)}})
        return json_response({"imported": n})

    @app.route("GET", r"/v1/events/(?P<app_id>\d+)/get")
    def ev_get(req: Request) -> Response:
        auth(req)
        e = storage.events().get(req.query.get("id", ""),
                                 int(req.path_params["app_id"]),
                                 chan(req))
        return json_response({"event": e.to_json() if e else None})

    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/delete")
    def ev_delete(req: Request) -> Response:
        auth(req)
        ok = storage.events().delete(req.json()["id"],
                                     int(req.path_params["app_id"]),
                                     chan(req))
        return json_response({"ok": bool(ok)})

    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/find")
    def ev_find(req: Request) -> Response:
        auth(req)
        f = filter_from_doc(req.json())
        out = [e.to_json() for e in storage.events().find(
            int(req.path_params["app_id"]), chan(req), f)]
        return json_response({"events": out})

    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/aggregate")
    def ev_aggregate(req: Request) -> Response:
        auth(req)
        from datetime import datetime

        d = req.json() or {}

        def dt(s):
            return datetime.fromisoformat(s) if s else None

        props = storage.events().aggregate_properties(
            int(req.path_params["app_id"]), chan(req),
            entity_type=d["entity_type"],
            start_time=dt(d.get("start_time")),
            until_time=dt(d.get("until_time")),
            required=d.get("required"))
        return json_response({"properties": {
            k: {"fields": v.to_dict(),
                "first_updated": v.first_updated.isoformat(),
                "last_updated": v.last_updated.isoformat()}
            for k, v in props.items()}})

    @app.route("GET", r"/v1/events/(?P<app_id>\d+)/columnar")
    def ev_columnar(req: Request) -> Response:
        auth(req)
        with_props = req.query.get("props", "1") != "0"
        fp = tuple(p for p in
                   (req.query.get("float_props") or "rating").split(",")
                   if p)
        shard = None
        if req.query.get("shard_n"):
            try:
                shard = (int(req.query.get("shard_i", "0")),
                         int(req.query["shard_n"]))
            except ValueError:
                raise HTTPError(400, "shard_i/shard_n must be integers")
            if not 0 <= shard[0] < shard[1]:
                raise HTTPError(400,
                                f"shard {shard[0]} of {shard[1]}")
        batch = storage.events().find_columnar(
            int(req.path_params["app_id"]), chan(req), EventFilter(),
            float_props=fp, ordered=False, with_props=with_props,
            shard=shard)
        version = _batch_version(
            batch, memo_key=(int(req.path_params["app_id"]), chan(req),
                             with_props, fp, shard))
        headers = {"ETag": version}
        if shard is not None:
            # global-row bookkeeping for the multihost feeding layer
            headers["X-Shard-Offset"] = str(
                getattr(batch, "shard_offset", 0))
            headers["X-Shard-Total"] = str(
                getattr(batch, "shard_total", batch.n))
        if hdr(req, "if-none-match") == version:
            columnar_reqs.labels(outcome="hit").inc()
            return Response(status=304, body=b"", headers=headers)
        payload = batch_to_npz(batch)
        columnar_reqs.labels(outcome="miss").inc()
        columnar_bytes.inc(len(payload))
        return Response(status=200, body=payload,
                        content_type="application/octet-stream",
                        headers=headers)

    @app.route("POST", r"/v1/events/(?P<app_id>\d+)/columnar")
    def ev_columnar_ingest(req: Request) -> Response:
        """Zero-copy block ingest: the body is the same npz wire format
        the bulk read serves — dictionary-coded numpy columns, no
        per-event JSON. The backend's ``insert_columnar`` lane writes
        the block in one transaction (all-or-nothing), so a client
        retry after a transport error cannot half-duplicate a block."""
        auth(req)
        import time as _time

        try:
            batch = batch_from_npz(req.body)
        except Exception as e:
            raise HTTPError(400, f"bad columnar block: {e}")
        t0 = _time.perf_counter()
        n = storage.events().insert_columnar(
            batch, int(req.path_params["app_id"]), chan(req))
        ingest_block_seconds.observe(_time.perf_counter() - t0)
        ingest_block_events.inc(n)
        ingest_block_bytes.inc(len(req.body))
        return json_response({"accepted": n})

    # -- metadata ----------------------------------------------------------
    @app.route("POST", r"/v1/meta/(?P<dao>[a-z_]+)/(?P<method>[a-z_]+)")
    def meta_rpc(req: Request) -> Response:
        auth(req)
        dao_name = req.path_params["dao"]
        method = req.path_params["method"]
        allowed = _META_METHODS.get(dao_name)
        if allowed is None or method not in allowed:
            raise HTTPError(404, f"unknown RPC {dao_name}/{method}")
        dao = getattr(storage, dao_name)()
        body = req.json() or {}
        args = body.get("args", [])
        if dao_name == "models":
            import base64

            from ..data.storage.base import Model
            if method == "insert":
                m = body["model"]
                dao.insert(Model(id=m["id"],
                                 models=base64.b64decode(m["models"])))
                return json_response({"ok": True})
            if method == "get":
                m = dao.get(*args)
                return json_response({"model": None if m is None else {
                    "id": m.id,
                    "models": base64.b64encode(m.models).decode()}})
            dao.delete(*args)
            return json_response({"ok": True})
        if "entity" in body:
            args = [entity_from_doc(dao_name, body["entity"])] + args
        result = getattr(dao, method)(*args)
        if result is None or isinstance(result, (int, str)):
            return json_response({"result": result})
        if isinstance(result, list):
            return json_response(
                {"entities": [entity_to_doc(e) for e in result]})
        return json_response({"entity": entity_to_doc(result)})

    return app


def create_storage_server(storage: Optional[Storage] = None,
                          host: str = "0.0.0.0", port: int = 7077,
                          secret: Optional[str] = None) -> AppServer:
    """Bind the storage server (default port 7077 — beside the event
    server's reference port 7070)."""
    return AppServer(build_app(storage or Storage(), secret=secret),
                     host, port)
