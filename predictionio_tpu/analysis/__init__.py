"""``ptpu check`` — JAX-aware + concurrency static analysis.

Public surface:

- :func:`run_check` / :func:`check_source` — run the rule suite over
  paths or a source blob, returning :class:`Finding`\\ s. Module rules
  run per file; project rules (the cross-file lock-order graph) run
  once over the whole parsed set.
- :data:`RULES` — the rule registry (name → :class:`Rule`): five JAX
  rules plus the concurrency family (:mod:`.concurrency`).
- :func:`findings_to_json` / :func:`findings_to_sarif` — machine
  output (:mod:`.report`); SARIF feeds GitHub code-scanning.
- :func:`write_baseline` / :func:`load_baseline` /
  :func:`new_findings` — gate CI on *no new findings*
  (:mod:`.baseline`).
- ``# ptpu: allow[rule] — why`` pragmas suppress a finding on that line
  or via the comment block directly above; ``# ptpu: guarded-by[lock]``
  is the lock-contract annotation ``unguarded-shared-state`` honors.

See ``docs/static-analysis.md`` for the operator-facing rule catalogue.
"""

from .baseline import load_baseline, new_findings, write_baseline
from .core import (
    CheckContext,
    Finding,
    check_source,
    default_context,
    iter_py_files,
    run_check,
)
from .report import findings_to_json, findings_to_sarif
from .rules import RULES, Rule

__all__ = [
    "CheckContext",
    "Finding",
    "RULES",
    "Rule",
    "check_source",
    "default_context",
    "findings_to_json",
    "findings_to_sarif",
    "iter_py_files",
    "load_baseline",
    "new_findings",
    "run_check",
    "write_baseline",
]
