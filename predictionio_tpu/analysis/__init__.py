"""``ptpu check`` — JAX-aware static analysis for serving code.

Public surface:

- :func:`run_check` / :func:`check_source` — run the rule suite over
  paths or a source blob, returning :class:`Finding`\\ s.
- :data:`RULES` — the rule registry (name → :class:`Rule`).
- ``# ptpu: allow[rule] — why`` pragmas suppress a finding on that line
  or the line below the comment.

See ``docs/static-analysis.md`` for the operator-facing rule catalogue.
"""

from .core import (
    CheckContext,
    Finding,
    check_source,
    default_context,
    iter_py_files,
    run_check,
)
from .rules import RULES, Rule

__all__ = [
    "CheckContext",
    "Finding",
    "RULES",
    "Rule",
    "check_source",
    "default_context",
    "iter_py_files",
    "run_check",
]
