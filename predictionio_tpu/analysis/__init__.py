"""``ptpu check`` — JAX-aware + concurrency static analysis.

Public surface:

- :func:`run_check` / :func:`check_source` / :func:`check_project` —
  run the rule suite over paths, a source blob, or an in-memory
  multi-module project, returning :class:`Finding`\\ s. Module rules
  run per file; project rules (the cross-file lock-order graph, the
  interprocedural summary consumers) run once over the whole parsed
  set, against the :class:`~.core.ProjectIndex` call graph.
- :data:`RULES` — the rule registry (name → :class:`Rule`): six JAX
  rules, the concurrency family (:mod:`.concurrency`), and the Pallas
  kernel-safety family (:mod:`.kernels`).
- :func:`findings_to_json` / :func:`findings_to_sarif` — machine
  output (:mod:`.report`); SARIF feeds GitHub code-scanning, with
  interprocedural call chains as ``relatedLocations``.
- :func:`write_baseline` / :func:`load_baseline` /
  :func:`new_findings` / :func:`shrinkable_entries` — gate CI on *no
  new findings* and ratchet the recorded debt monotonically down
  (:mod:`.baseline`).
- ``# ptpu: allow[rule] — why`` pragmas suppress a finding on that line
  or via the comment block directly above; ``# ptpu: guarded-by[lock]``
  is the lock-contract annotation ``unguarded-shared-state`` honors. A
  pragma at an effect's direct site also stops interprocedural
  propagation (blessing the one named helper blesses its callers).

See ``docs/static-analysis.md`` for the operator-facing rule catalogue.
"""

from .baseline import (
    load_baseline,
    new_findings,
    shrinkable_entries,
    write_baseline,
)
from .core import (
    CheckContext,
    Finding,
    ProjectIndex,
    check_project,
    check_source,
    default_context,
    iter_py_files,
    run_check,
)
from .numerics import NUMERICS_RULES
from .report import findings_to_json, findings_to_sarif
from .rules import RULES, Rule
from .sharding import SHARDING_RULES, count_sharding_pragmas

__all__ = [
    "NUMERICS_RULES",
    "SHARDING_RULES",
    "count_sharding_pragmas",
    "CheckContext",
    "Finding",
    "ProjectIndex",
    "RULES",
    "Rule",
    "check_project",
    "check_source",
    "default_context",
    "findings_to_json",
    "findings_to_sarif",
    "iter_py_files",
    "load_baseline",
    "new_findings",
    "run_check",
    "shrinkable_entries",
    "write_baseline",
]
