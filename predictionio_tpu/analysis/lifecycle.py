"""Resource-lifecycle rule family: leak lint for the control-plane era.

PRs 17–19 grew a fleet aggregator scrape loop, router/autoscaler/
lifecycle daemons, an AOT artifact store, and a columnar ingest lane —
continuously-running control loops where a leaked thread, an
un-timed-out HTTP call, or a torn state-file write becomes a wedged
autoscaler or a replica that never drains. This family rides the PR 8
interprocedural engine (:class:`~.core.ProjectIndex`) the same way the
sharding and numerics families do:

- ``leaked-thread`` — a ``threading.Thread`` whose target runs an
  unbounded (or stop-event) loop, started in ``server/`` / ``fleet/`` /
  ``router/`` / ``streaming/`` / ``rollout/`` code, with no reachable
  ``join`` for the handle. Joins are resolved through the class (any
  method joining the storing attribute, including via locals and
  ``for t in self._threads`` iteration) and through the call graph (a
  helper that joins its parameter blesses every caller passing the
  handle). One-shot targets (warmups, remote-log ships, delayed
  shutdowns) terminate on their own and are exempt by construction.
  ``# ptpu: allow[leaked-thread]`` marks intentional process-lifetime
  daemons.
- ``missing-timeout`` — ``urllib.request.urlopen`` /
  ``http.client.HTTP(S)Connection`` / ``socket.create_connection``
  without an explicit timeout, reachable from ``fleet/`` / ``router/``
  / ``data/`` (storage) code. The hang that freezes a scrape or a
  control tick may sit N helpers away: a timeout-less net call exports
  a ``net_wait`` effect summary, and an in-scope caller of the helper
  is flagged at its own call site with the chain in the message.
- ``non-atomic-persist`` — durable state (baselines, release/registry/
  gate files, AOT artifacts) written with a plain ``open(path, "w")``
  outside the temp-file+fsync+rename funnel established in PR 11: a
  crash mid-write tears the file and the next boot reads garbage.
  A function that calls ``os.replace``/``os.rename`` itself, writes a
  ``*.tmp`` staging path, or routes through a blessed ``atomic_write*``
  helper is clean.
- ``unbounded-queue`` — ``queue.Queue()`` / ``collections.deque()``
  constructed without a bound on serving/streaming paths: backlog is
  the memory leak you only meet under overload.
- ``hot-spin-loop`` — ``while True`` daemon loops with *neither* a
  stop-event check *nor* a pacing/blocking call in the body: a busy
  spin that pins a core and never yields shutdown. Complements PR 11's
  ``unbounded-retry`` (which needs a swallowed exception to fire).

All five obey ``# ptpu: allow[rule] — justification`` pragmas; a pragma
at a net call's *direct site* also stops the ``net_wait`` effect from
propagating (blessing the helper blesses its callers). Runtime
complement: ``ptpu audit-lifecycle`` (:mod:`.lifecycle_audit`) cycles
each subsystem start→serve→stop and ratchets /proc thread/fd/socket
leak counts against ``analysis/lifecycle_baseline.json``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    CheckContext,
    Finding,
    ModuleInfo,
    chain_related,
    chain_text,
    short_name,
)

LIFECYCLE_RULES = (
    "leaked-thread",
    "missing-timeout",
    "non-atomic-persist",
    "unbounded-queue",
    "hot-spin-loop",
)

#: where long-lived worker threads live — servers, fleet control
#: plane, router daemons, streaming trainer, rollout controller
THREAD_SCOPE_PARTS = {"server", "fleet", "router", "streaming",
                      "rollout"}
#: where a hung HTTP call freezes a scrape/control tick or a
#: storage client
NET_SCOPE_PARTS = {"fleet", "router", "data", "storage"}
#: where durable state files are produced (baselines, gates,
#: registries, artifacts, cursors)
PERSIST_SCOPE_PARTS = {"analysis", "slo", "aot", "rollout",
                       "controller", "data", "storage", "streaming"}
#: serving/streaming paths where an unbounded backlog is an OOM
QUEUE_SCOPE_PARTS = {"server", "streaming"}
#: daemon-loop territory for the spin rule
SPIN_SCOPE_PARTS = {"server", "streaming", "fleet", "router",
                    "rollout", "slo"}


def _in_dirs(mod: ModuleInfo, parts: Set[str]) -> bool:
    return bool(set(mod.path.split("/")[:-1]) & parts)


def _same_scope(node: ast.AST):
    """Walk without descending into nested defs/lambdas — their
    lifecycles are judged where they are defined."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _same_scope(child)


def _body_nodes(fn: ast.AST) -> List[ast.AST]:
    return [n for stmt in fn.body for n in [stmt, *_same_scope(stmt)]]


# ---------------------------------------------------------------------------
# missing-timeout — the net_wait effect (collected by core, like
# host_sync/blocking) plus the scope rule that reports it
# ---------------------------------------------------------------------------

_NET_CALLS = {
    # resolved dotted name → (positional slot of the timeout
    # argument, human label)
    "urllib.request.urlopen": (2, "urlopen"),
    "http.client.HTTPConnection": (2, "HTTPConnection"),
    "http.client.HTTPSConnection": (2, "HTTPSConnection"),
    "socket.create_connection": (1, "create_connection"),
}
_NET_ATTRS = {name.rsplit(".", 1)[-1]: spec
              for name, spec in _NET_CALLS.items()}


def net_wait_reason(mod: ModuleInfo, node: ast.Call) -> Optional[str]:
    """Reason string when ``node`` is a network call with no explicit
    timeout (the ``net_wait`` direct-effect detector, called from
    :meth:`~.core.ProjectIndex._collect_direct`)."""
    resolved = mod.resolve(node.func)
    spec = _NET_CALLS.get(resolved or "")
    if spec is None and isinstance(node.func, ast.Attribute):
        spec = _NET_ATTRS.get(node.func.attr)
    if spec is None:
        return None
    slot, label = spec
    if any(kw.arg == "timeout" for kw in node.keywords):
        return None
    if len(node.args) > slot:
        return None  # timeout passed positionally
    return (f"`{label}(…)` with no timeout — the peer hanging "
            f"hangs this call forever")


def rule_missing_timeout(mods: Sequence[ModuleInfo],
                         ctx: CheckContext) -> List[Finding]:
    """Project-scoped: direct timeout-less net calls inside fleet/
    router/data/storage functions, plus — through the call graph —
    in-scope calls into helpers (anywhere in the project) that
    transitively reach one, reported at the in-scope call site with
    the chain down to the direct site."""
    findings: List[Finding] = []
    for mod in mods:
        if not _in_dirs(mod, NET_SCOPE_PARTS):
            continue
        if "urlopen" not in mod.source \
                and "Connection" not in mod.source \
                and "create_connection" not in mod.source:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            why = net_wait_reason(mod, node)
            if why is not None:
                findings.append(Finding(
                    "missing-timeout", mod.path, node.lineno,
                    node.col_offset,
                    f"{why}; a wedged peer freezes the scrape/"
                    f"control tick that issued it — pass an explicit "
                    f"timeout"))
    proj = ctx.project
    if proj is None:
        return findings
    for fninfo in proj.functions.values():
        if not fninfo.hot(NET_SCOPE_PARTS):
            continue
        for call in fninfo.calls:
            callee = proj.functions.get(call.callee or "")
            if callee is None or callee.hot(NET_SCOPE_PARTS):
                continue  # in-scope helpers got the direct finding
            if callee.effects["net_wait"] is None:
                continue
            hops = proj.chain(callee, "net_wait")
            if not hops:
                continue
            findings.append(Finding(
                "missing-timeout", fninfo.mod.path, call.line,
                call.col,
                f"calling `{short_name(callee.qname)}` from "
                f"`{short_name(fninfo.qname)}` transitively performs "
                f"a network call with no timeout: "
                f"{chain_text(hops)}; thread a timeout through, or "
                f"pragma the blessed helper at its direct site",
                related=chain_related(hops)))
    return findings


# ---------------------------------------------------------------------------
# leaked-thread
# ---------------------------------------------------------------------------

def _is_thread_ctor(mod: ModuleInfo, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.resolve(node.func)
    if resolved == "threading.Thread":
        return True
    return isinstance(node.func, ast.Name) \
        and mod.aliases.get(node.func.id) == "threading.Thread"


def _target_expr(node: ast.Call) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == "target":
            return kw.value
    return None


def _is_stoppy_test(test: ast.AST) -> bool:
    """``while not self._stop.is_set()`` / ``while not stop.wait(t)``
    — a stop-event loop: long-running until someone signals it."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("is_set", "wait"):
            return True
    return False


def _target_loops_forever(mod: ModuleInfo, target: ast.AST) -> bool:
    """True when the thread target's own body contains an unbounded
    loop (``while True`` / ``itertools.count``) or a stop-event loop —
    either way a thread that outlives its spawner unless joined.
    One-shot targets (no such loop) terminate on their own."""
    for node in _body_nodes(target):
        if isinstance(node, ast.While):
            t = node.test
            if isinstance(t, ast.Constant) and bool(t.value):
                return True
            if _is_stoppy_test(t):
                return True
        if isinstance(node, ast.For) and isinstance(node.iter, ast.Call) \
                and mod.resolve(node.iter.func) == "itertools.count":
            return True
    return False


def _resolve_target_def(mod: ModuleInfo, expr: ast.AST,
                        enclosing_fn: Optional[ast.AST],
                        enclosing_cls: Optional[ast.ClassDef]
                        ) -> Optional[ast.AST]:
    """The FunctionDef a ``target=`` expression names: ``self.method``,
    a module-level def, or a closure defined in the enclosing
    function. Unresolvable targets (bound methods of other objects,
    e.g. ``httpd.serve_forever``) return None — judged one-shot rather
    than guessed at."""
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and enclosing_cls is not None:
        for item in enclosing_cls.body:
            if isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and item.name == expr.attr:
                return item
        return None
    if isinstance(expr, ast.Name):
        if enclosing_fn is not None:
            for node in ast.walk(enclosing_fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node.name == expr.id:
                    return node
        for item in mod.tree.body:
            if isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and item.name == expr.id:
                return item
    if isinstance(expr, ast.Lambda):
        return None  # a lambda daemon would be its own finding
    return None


def _attr_roots(env: Dict[str, Set[str]], expr: ast.AST) -> Set[str]:
    """The ``self.<attr>`` tokens an expression can reach: direct
    attribute accesses plus whatever the names in it were bound from
    (the tiny intra-method dataflow that sees through
    ``threads = list(self._threads)``)."""
    roots: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) \
                and isinstance(n.value, ast.Name) \
                and n.value.id == "self":
            roots.add(n.attr)
        elif isinstance(n, ast.Name):
            roots |= env.get(n.id, set())
    return roots


def _join_roots_of_method(method: ast.AST) -> Set[str]:
    """Attributes of ``self`` that this method (transitively through
    locals and for-targets) calls ``.join()`` on."""
    env: Dict[str, Set[str]] = {}
    joined: Set[str] = set()
    for node in _body_nodes(method):
        if isinstance(node, ast.Assign):
            roots = _attr_roots(env, node.value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    env[tgt.id] = env.get(tgt.id, set()) | roots
        elif isinstance(node, ast.For):
            roots = _attr_roots(env, node.iter)
            # tuple targets get every root (conservative: `for q, ts in
            # ((self._q, self._threads),)` binds both names to both)
            targets = (node.target.elts
                       if isinstance(node.target, ast.Tuple)
                       else [node.target])
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    env[tgt.id] = env.get(tgt.id, set()) | roots
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            joined |= _attr_roots(env, node.func.value)
    return joined


def _class_join_roots(cls: ast.ClassDef) -> Set[str]:
    roots: Set[str] = set()
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            roots |= _join_roots_of_method(item)
    return roots


def _param_joiners(proj) -> Set[Tuple[str, int]]:
    """(qname, param position) pairs whose function joins that
    parameter — the "stop helper" the call graph resolves: a spawner
    passing its thread handle to one of these has a join path."""
    out: Set[Tuple[str, int]] = set()
    for qname, fn in proj.functions.items():
        params = fn.params
        if not params:
            continue
        env: Dict[str, Set[str]] = {p: {p} for p in params}
        for node in _body_nodes(fn.node):
            if isinstance(node, ast.Assign):
                roots = _attr_roots(env, node.value) | {
                    n.id for n in ast.walk(node.value)
                    if isinstance(n, ast.Name) and n.id in env
                }
                roots = {r for r in roots if r in params}
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        env[tgt.id] = env.get(tgt.id, set()) | roots
            elif isinstance(node, ast.For):
                roots = {n.id for n in ast.walk(node.iter)
                         if isinstance(n, ast.Name) and n.id in env}
                hit = set()
                for r in roots:
                    hit |= env.get(r, set())
                if isinstance(node.target, ast.Name):
                    env[node.target.id] = \
                        env.get(node.target.id, set()) | hit | roots
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and isinstance(node.func.value, ast.Name):
                for p in env.get(node.func.value.id, set()):
                    if p in params:
                        out.add((qname, params.index(p)))
    return out


def _enclosing_maps(mod: ModuleInfo):
    """(node id → enclosing FunctionDef, node id → enclosing ClassDef)
    for every node in the module."""
    fn_of: Dict[int, ast.AST] = {}
    cls_of: Dict[int, ast.ClassDef] = {}

    def visit(node, fn, cls):
        for child in ast.iter_child_nodes(node):
            nfn, ncls = fn, cls
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                nfn = node
            if isinstance(node, ast.ClassDef):
                ncls = node
            fn_of[id(child)] = nfn
            cls_of[id(child)] = ncls
            visit(child, nfn, ncls)

    visit(mod.tree, None, None)
    return fn_of, cls_of


def rule_leaked_thread(mods: Sequence[ModuleInfo],
                       ctx: CheckContext) -> List[Finding]:
    """Project-scoped: daemon-looping threads spawned in server/,
    fleet/, router/, streaming/, or rollout/ code whose handle nobody
    joins — in the spawning function, anywhere in the owning class
    (through locals and list-attr iteration), or through a call-graph
    helper that joins its parameter."""
    proj = ctx.project
    joiners: Set[Tuple[str, int]] = \
        _param_joiners(proj) if proj is not None else set()
    findings: List[Finding] = []
    for mod in mods:
        if not _in_dirs(mod, THREAD_SCOPE_PARTS):
            continue
        if "Thread" not in mod.source:
            continue
        fn_of, cls_of = _enclosing_maps(mod)
        for node in ast.walk(mod.tree):
            if not _is_thread_ctor(mod, node):
                continue
            enclosing_fn = fn_of.get(id(node))
            enclosing_cls = cls_of.get(id(node))
            target = _target_expr(node)
            tdef = _resolve_target_def(mod, target, enclosing_fn,
                                       enclosing_cls) \
                if target is not None else None
            if tdef is None or not _target_loops_forever(mod, tdef):
                continue  # one-shot (or unresolvable): ends on its own
            if _handle_joined(mod, node, enclosing_fn, enclosing_cls,
                              proj, joiners):
                continue
            tname = (target.attr if isinstance(target, ast.Attribute)
                     else getattr(target, "id", "<target>"))
            findings.append(Finding(
                "leaked-thread", mod.path, node.lineno,
                node.col_offset,
                f"thread running looping target `{tname}` is never "
                f"joined — no stop-event + join path reachable from "
                f"the owning class or through any helper: the daemon "
                f"outlives every start→stop cycle (the audit-"
                f"lifecycle leak). Store the handle, signal a stop "
                f"event, and join it in close()/stop(); pragma "
                f"`# ptpu: allow[leaked-thread]` only for intentional "
                f"process-lifetime daemons"))
    return findings


def _handle_joined(mod: ModuleInfo, ctor: ast.Call,
                   enclosing_fn: Optional[ast.AST],
                   enclosing_cls: Optional[ast.ClassDef],
                   proj, joiners: Set[Tuple[str, int]]) -> bool:
    """Is the Thread constructed at ``ctor`` joined anywhere its
    handle flows? Tracks: local var, ``self.<attr>`` stores (direct or
    via local), ``self.<attr>.append``, return (caller's
    responsibility), and handle-passed-to-joiner-helper calls."""
    if enclosing_fn is None:
        return False  # module-level daemon construction
    local: Optional[str] = None
    attrs: Set[str] = set()
    returned = False
    for node in _body_nodes(enclosing_fn):
        if isinstance(node, ast.Assign) and any(
                n is ctor for n in ast.walk(node.value)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    local = tgt.id
                elif isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    attrs.add(tgt.attr)
    if local is not None:
        for node in _body_nodes(enclosing_fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == local:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        attrs.add(tgt.attr)
            if isinstance(node, ast.Call):
                fnc = node.func
                if isinstance(fnc, ast.Attribute) \
                        and fnc.attr == "join" \
                        and isinstance(fnc.value, ast.Name) \
                        and fnc.value.id == local:
                    return True  # joined in the spawning function
                if isinstance(fnc, ast.Attribute) \
                        and fnc.attr == "append" \
                        and isinstance(fnc.value, ast.Attribute) \
                        and isinstance(fnc.value.value, ast.Name) \
                        and fnc.value.value.id == "self" \
                        and any(isinstance(a, ast.Name)
                                and a.id == local
                                for a in node.args):
                    attrs.add(fnc.value.attr)
                # handle passed to a call-graph joiner helper
                if proj is not None and joiners:
                    arg_pos = [i for i, a in enumerate(node.args)
                               if isinstance(a, ast.Name)
                               and a.id == local]
                    if arg_pos and _calls_joiner(
                            mod, node, arg_pos, proj, joiners,
                            enclosing_cls):
                        return True
            if isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == local:
                returned = True
    # stored-in-list append of the ctor expression itself:
    # self._threads.append(threading.Thread(...))
    for node in _body_nodes(enclosing_fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append" \
                and any(n is ctor for a in node.args
                        for n in ast.walk(a)):
            base = node.func.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                attrs.add(base.attr)
    if attrs and enclosing_cls is not None:
        if attrs & _class_join_roots(enclosing_cls):
            return True
    if returned:
        return True  # the caller owns the handle now
    return False


def _calls_joiner(mod: ModuleInfo, call: ast.Call,
                  arg_positions: List[int], proj, joiners,
                  enclosing_cls: Optional[ast.ClassDef]) -> bool:
    cls_name = enclosing_cls.name if enclosing_cls is not None \
        else None
    callee, bound = proj.resolve_call(mod, cls_name, call.func)
    if callee is None:
        return False
    off = 1 if bound else 0
    return any((callee, pos + off) in joiners
               for pos in arg_positions)


# ---------------------------------------------------------------------------
# non-atomic-persist
# ---------------------------------------------------------------------------

_ATOMIC_FUNNELS = ("atomic_write", "atomic_write_text",
                   "atomic_replace", "write_atomic")
#: truncate-rewrite modes only: append-only logs ("a") are a
#: legitimate durable pattern — a crashed appender tears at most the
#: trailing record, which replay detects and truncates (localfs.py's
#: event-log discipline); rewriting in place tears the whole file
_WRITE_MODES = set("wx")


def _open_write_mode(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Name)
            and node.func.id == "open"):
        return False
    mode = None
    if len(node.args) > 1:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not isinstance(mode, ast.Constant) \
            or not isinstance(mode.value, str):
        return False
    return bool(set(mode.value) & _WRITE_MODES)


def _tmp_staged(node: ast.Call) -> bool:
    """The opened path is visibly a staging file (``…tmp…`` in a name
    or literal): the rename half may live one helper away."""
    path_arg = node.args[0] if node.args else None
    if path_arg is None:
        return False
    for n in ast.walk(path_arg):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and "tmp" in n.value.lower():
            return True
        if isinstance(n, ast.Name) and "tmp" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "tmp" in n.attr.lower():
            return True
    return False


def rule_non_atomic_persist(mod: ModuleInfo,
                            ctx: CheckContext) -> List[Finding]:
    """Plain ``open(path, "w")`` writes of durable state in analysis/,
    slo/, aot/, rollout/, controller/, data/, storage/, or streaming/
    — outside a function that completes the temp+rename funnel
    (``os.replace``/``os.rename`` in the same function, a ``*tmp*``
    staging path, or a blessed ``atomic_write*`` helper)."""
    if not _in_dirs(mod, PERSIST_SCOPE_PARTS):
        return []
    if "open(" not in mod.source:
        return []
    findings: List[Finding] = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        nodes = _body_nodes(fn)
        calls = [n for n in nodes if isinstance(n, ast.Call)]
        atomic = False
        for c in calls:
            resolved = mod.resolve(c.func) or ""
            if resolved in ("os.replace", "os.rename") \
                    or resolved.endswith(_ATOMIC_FUNNELS):
                atomic = True
                break
        if atomic:
            continue
        for c in calls:
            if not _open_write_mode(c) or _tmp_staged(c):
                continue
            findings.append(Finding(
                "non-atomic-persist", mod.path, c.lineno,
                c.col_offset,
                "durable state written in place — a crash mid-write "
                "tears the file and the next reader gets garbage; "
                "write to a temp file, fsync, and os.replace() over "
                "the destination (localfs.atomic_write / "
                "analysis.baseline.atomic_write_text are the blessed "
                "funnels), or pragma with a durability argument"))
    return findings


# ---------------------------------------------------------------------------
# unbounded-queue
# ---------------------------------------------------------------------------

_QUEUE_CTORS = {
    "queue.Queue": "maxsize",
    "queue.LifoQueue": "maxsize",
    "queue.PriorityQueue": "maxsize",
    "collections.deque": "maxlen",
}


def rule_unbounded_queue(mod: ModuleInfo,
                         ctx: CheckContext) -> List[Finding]:
    """Queue/deque construction with no bound (or an explicit 0) on
    serving/streaming paths: producers outrunning a consumer grow it
    without limit, and the overload you bought batching for becomes
    an OOM instead of backpressure."""
    if not _in_dirs(mod, QUEUE_SCOPE_PARTS):
        return []
    if "Queue" not in mod.source and "deque" not in mod.source:
        return []
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = mod.resolve(node.func) or ""
        bound_kw = _QUEUE_CTORS.get(resolved)
        if bound_kw is None:
            continue
        bound = node.args[0] if node.args else None
        if resolved == "collections.deque" and len(node.args) > 1:
            bound = node.args[1]
        elif resolved == "collections.deque":
            bound = None
        for kw in node.keywords:
            if kw.arg == bound_kw:
                bound = kw.value
        unbounded = bound is None or (
            isinstance(bound, ast.Constant)
            and (bound.value is None or bound.value == 0))
        if not unbounded:
            continue
        short = resolved.rsplit(".", 1)[-1]
        findings.append(Finding(
            "unbounded-queue", mod.path, node.lineno,
            node.col_offset,
            f"`{short}` constructed without a bound on a serving/"
            f"streaming path — backlog grows without limit under "
            f"overload; pass {bound_kw}= (shed or block at the "
            f"bound), or pragma with the invariant that bounds it"))
    return findings


# ---------------------------------------------------------------------------
# hot-spin-loop
# ---------------------------------------------------------------------------

#: attribute calls that pace (block/sleep) a loop iteration —
#: mirrors unbounded-retry's table; ``*_nowait`` does not count
_PACING_ATTRS = {"sleep", "wait", "get", "join", "acquire", "select",
                 "accept", "recv", "poll", "serve_forever"}
_PACING_NAMES = {"time.sleep", "select.select"}
_PACING_SUFFIXES = ("retry_call", "backoff_delays")


def _paces(mod: ModuleInfo, call: ast.Call) -> bool:
    name = mod.resolve(call.func) or ""
    if name in _PACING_NAMES or name.endswith(_PACING_SUFFIXES):
        return True
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        return attr in _PACING_ATTRS and not attr.endswith("_nowait")
    return False


def _checks_stop(nodes: List[ast.AST]) -> bool:
    return any(isinstance(n, ast.Call)
               and isinstance(n.func, ast.Attribute)
               and n.func.attr == "is_set"
               for n in nodes)


def rule_hot_spin_loop(mod: ModuleInfo,
                       ctx: CheckContext) -> List[Finding]:
    """``while True`` (or ``itertools.count``) daemon loops in
    server/, streaming/, fleet/, router/, rollout/, or slo/ code with
    neither a stop-event check nor a pacing/blocking call in the body:
    a spin that pins a core and a daemon that cannot be shut down.
    Complements ``unbounded-retry``, which only fires on swallowed
    exceptions."""
    if not _in_dirs(mod, SPIN_SCOPE_PARTS):
        return []
    if "while" not in mod.source and "count(" not in mod.source:
        return []
    findings: List[Finding] = []
    for loop in ast.walk(mod.tree):
        unbounded = False
        if isinstance(loop, ast.While):
            t = loop.test
            unbounded = isinstance(t, ast.Constant) and bool(t.value)
        elif isinstance(loop, ast.For):
            unbounded = isinstance(loop.iter, ast.Call) \
                and mod.resolve(loop.iter.func) == "itertools.count"
        if not unbounded:
            continue
        nodes = [n for stmt in loop.body
                 for n in [stmt, *_same_scope(stmt)]]
        if any(isinstance(n, ast.Yield) for n in nodes):
            continue  # generator pump: consumer-paced by pull
        if any(isinstance(n, ast.Try) for n in nodes):
            continue  # retry-shaped loop: unbounded-retry's territory
            # (it judges swallowed exceptions and back-off; one loop
            # must not draw two findings)
        if any(isinstance(n, ast.Call) and _paces(mod, n)
               for n in nodes):
            continue
        if _checks_stop(nodes):
            continue
        findings.append(Finding(
            "hot-spin-loop", mod.path, loop.lineno, loop.col_offset,
            "unbounded loop with neither a stop-event check nor any "
            "pacing/blocking call — it pins a core while idle and "
            "ignores shutdown; block on the work source (queue.get / "
            "event.wait) or check a stop event with a sleep, or "
            "pragma with the bound"))
    return findings
