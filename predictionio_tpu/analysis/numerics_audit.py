"""``ptpu audit-numerics`` — the abstract-eval precision audit.

The static dtype-flow rules (:mod:`.numerics`) catch the narrowings
and upcasts the AST can see; this module catches the ones only the
traced program sees. It abstract-interprets the framework's registered
numeric entry points (``jax.make_jaxpr`` — a jaxpr walk, NO device
execution and no XLA compile) and extracts a per-entry **dtype
census**:

- ``ops`` — primitive-application counts keyed by result dtype;
- ``casts`` — every ``convert_element_type`` site, keyed
  ``src->dst``: the cast inventory. A new ``int8->float32`` or
  ``bfloat16->float32`` cast in a quantized entry is a dequantized
  table copy forfeiting the 4×-users-per-HBM win; a new ``->bfloat16``
  cast is dropped mantissa;
- ``reductions`` — accumulation dtype per reducing primitive
  (``reduce_sum`` / ``dot_general`` / …): the result dtype IS the
  accumulator dtype, so an einsum that loses its
  ``preferred_element_type=jnp.float32`` shows up as a
  ``dot_general`` accumulating at ``bfloat16``;
- ``bytes`` — result bytes by dtype (abstract shapes × itemsize): the
  footprint census that moves when a program starts materializing
  wide buffers.

The census diffs against a committed golden manifest
(``analysis/numerics_baseline.json``) with the same ratchet semantics
as ``audit-hlo``:

- a cast key the baseline entry does not record — or a count above
  the recorded one — FAILS, naming the entry, the cast and the count;
- a reducing primitive accumulating at bf16/f16 beyond the recorded
  count FAILS (an accumulator lost its widening);
- per-dtype bytes above ``BYTES_GROWTH_RATIO`` × recorded (plus a
  fixed slack) fail the same way;
- everything below the record prints as shrinkable and
  ``--write-baseline`` only ever ratchets the file down; recording
  new casts/entries (a deliberate precision change) takes the
  explicit ``--baseline-grow``.

Entry points audited (small shapes — the *dtype structure* is
shape-independent, which is why a golden manifest works): the eight
``audit-hlo`` SPMD entries traced through the same builders' inputs,
plus the three serving-quant seams PR 13 made load-bearing —
``foldin_update_bf16`` (the streaming fold-in's bf16 gather shadow
into :func:`~predictionio_tpu.models.als._update_block`),
``quantize_serving_model`` (the blessed dequant funnel pair), and
``device_topk_{off,bf16,int8}`` (the fused serving dispatch in all
three quant modes).

Everything jax-flavored imports lazily; the CLI pins the forced
8-device CPU topology (:func:`~.hlo_audit.ensure_cpu_devices`) before
the first jax import, because half the entries trace through meshes.

See docs/static-analysis.md ("How to read an audit-numerics diff").
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .hlo_audit import AUDIT_DEVICE_COUNT, AuditError, ensure_cpu_devices

MANIFEST_VERSION = 1

#: per-dtype result bytes may grow this factor (plus slack) over the
#: recorded baseline before the gate fails — shape-padding jitter moves
#: bytes a little; a dequantized table copy moves them a lot
BYTES_GROWTH_RATIO = 1.5
BYTES_SLACK = 64 * 1024

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "numerics_baseline.json")

#: accumulation dtypes that fail the gate when a reduction's count
#: grows — a sum/dot accumulating here is a lost f32 widening
LOW_PRECISION = ("bfloat16", "float16", "float8")

#: reducing primitives whose RESULT dtype is the accumulator dtype
REDUCING_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "dot_general", "cumsum",
    "reduce_window_sum", "cumprod",
})


def _is_low(dtype: str) -> bool:
    return any(dtype.startswith(p) for p in LOW_PRECISION)


# ---------------------------------------------------------------------------
# jaxpr census
# ---------------------------------------------------------------------------

def _sub_jaxprs(params: dict):
    """Inner jaxprs of one equation (pjit/scan/cond/shard_map/…)."""
    from jax import core as jcore

    def _as_jaxpr(v):
        if isinstance(v, jcore.ClosedJaxpr):
            return v.jaxpr
        if isinstance(v, jcore.Jaxpr):
            return v
        return None

    for v in params.values():
        j = _as_jaxpr(v)
        if j is not None:
            yield j
        elif isinstance(v, (list, tuple)):
            for x in v:
                j = _as_jaxpr(x)
                if j is not None:
                    yield j


def census_jaxpr(closed) -> dict:
    """One entry-point record: {ops, casts, reductions, bytes} over a
    ClosedJaxpr, recursing into sub-jaxprs. Call-like equations
    (those CARRYING sub-jaxprs) contribute only their bodies — their
    outvars duplicate the inner results."""
    ops: Dict[str, int] = {}
    casts: Dict[str, int] = {}
    reductions: Dict[str, Dict[str, int]] = {}
    nbytes: Dict[str, int] = {}

    def walk(jaxpr) -> None:
        for eqn in jaxpr.eqns:
            inner = list(_sub_jaxprs(eqn.params))
            if inner:
                for sub in inner:
                    walk(sub)
                continue
            prim = eqn.primitive.name
            out_dts: List[str] = []
            for ov in eqn.outvars:
                aval = getattr(ov, "aval", None)
                dt = getattr(aval, "dtype", None)
                if dt is None:
                    continue
                d = str(dt)
                out_dts.append(d)
                size = int(getattr(aval, "size", 0) or 0)
                nbytes[d] = nbytes.get(d, 0) + size * dt.itemsize
            for d in out_dts:
                ops[d] = ops.get(d, 0) + 1
            if prim == "convert_element_type" and eqn.invars and out_dts:
                src_aval = getattr(eqn.invars[0], "aval", None)
                src = str(getattr(src_aval, "dtype", "?"))
                key = f"{src}->{out_dts[0]}"
                casts[key] = casts.get(key, 0) + 1
            elif prim in REDUCING_PRIMS and out_dts:
                by = reductions.setdefault(prim, {})
                by[out_dts[0]] = by.get(out_dts[0], 0) + 1

    walk(closed.jaxpr)
    return {"ops": ops, "casts": casts, "reductions": reductions,
            "bytes": nbytes}


# ---------------------------------------------------------------------------
# entry-point builders (each returns a jax.core.ClosedJaxpr)
# ---------------------------------------------------------------------------

def _training_mesh():
    from ..parallel.mesh import make_mesh

    return make_mesh()


def _serving_mesh():
    from ..parallel.mesh import make_serving_mesh

    return make_serving_mesh()


def _lhs_arrays(n_dev: int):
    import numpy as np

    table = np.ones((8 * n_dev, 16), np.float32)
    idx = np.zeros((n_dev, 4, 8), np.int32)
    w = np.ones((n_dev, 4, 8), np.float32)
    return table, idx, w


def _entry_gramian_allreduce():
    import jax
    import numpy as np

    from ..parallel.collectives import gramian_allreduce

    mesh = _training_mesh()
    x = np.ones((8 * mesh.devices.size, 16), np.float32)
    return jax.make_jaxpr(lambda t: gramian_allreduce(t, mesh))(x)


def _entry_gather_rows():
    import jax
    import numpy as np

    from ..models.als import _gather_rows_fn

    mesh = _serving_mesh()
    table = np.ones((8 * mesh.devices.size, 16), np.float32)
    idx = np.zeros((4,), np.int64)
    return jax.make_jaxpr(_gather_rows_fn(mesh))(table, idx)


def _entry_sharded_rank():
    import jax
    import numpy as np

    from ..models.als import _sharded_rank_fn

    mesh = _serving_mesh()
    n = 8 * mesh.devices.size
    table = np.ones((n, 16), np.float32)
    vecs = np.ones((4, 16), np.float32)
    fn = _sharded_rank_fn(mesh, 8, 8, n)
    return jax.make_jaxpr(fn)(vecs, table)


def _entry_lhs_einsum():
    import functools

    import jax

    from ..models.als import _lhs_fn

    table, idx, w = _lhs_arrays(AUDIT_DEVICE_COUNT)
    fn = functools.partial(_lhs_fn, gram="einsum", bf16=False, mesh=None)
    return jax.make_jaxpr(fn)(table, idx, w, w)


def _entry_lhs_fused():
    import functools

    import jax

    from ..models.als import _lhs_fn

    mesh = _training_mesh()
    table, idx, w = _lhs_arrays(mesh.devices.size)
    fn = functools.partial(_lhs_fn, gram="fused", bf16=False, mesh=mesh)
    return jax.make_jaxpr(fn)(table, idx, w, w)


def _entry_train_update_block():
    import functools

    import jax
    import numpy as np

    from ..models.als import _update_block

    table, idx, w = _lhs_arrays(AUDIT_DEVICE_COUNT)
    counts = np.ones((AUDIT_DEVICE_COUNT, 4), np.float32)
    G = np.zeros((16, 16), np.float32)
    fn = functools.partial(
        _update_block.__wrapped__, implicit=True, scale_reg=True,
        bf16=False, gram="einsum", mesh=None)
    return jax.make_jaxpr(fn)(table, G, idx, w, counts, 0.1, 40.0)


def _entry_seqrec_train_step():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.seqrec import SeqRecParams, _init_weights, _train_step

    p = SeqRecParams(dim=16, heads=2, max_len=8, n_negatives=4,
                     batch_size=8)
    w = _init_weights(jax.random.key(0), 32, p)
    m = {k: jnp.zeros_like(v) for k, v in w.items()}
    v = {k: jnp.zeros_like(v) for k, v in w.items()}
    seq = np.zeros((8, 8), np.int32)
    fn = jax.make_jaxpr(_train_step, static_argnums=(6, 7))
    return fn(w, m, v, jnp.zeros((), jnp.int32), seq,
              jax.random.key(1), p, 32)


def _entry_sharded_topk():
    import jax
    import numpy as np

    from ..parallel.collectives import sharded_top_k
    from ..parallel.mesh import make_mesh

    mesh = make_mesh(data=2, model=4)
    scores = np.ones((4, 64), np.float32)
    return jax.make_jaxpr(
        lambda s: sharded_top_k(s, 8, mesh, axis="model"))(scores)


def _entry_foldin_update_bf16():
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.als import _update_block

    table, idx, w = _lhs_arrays(1)
    counts = np.ones((1, 4), np.float32)
    G = np.zeros((16, 16), np.float32)
    inner = functools.partial(
        _update_block.__wrapped__, implicit=True, scale_reg=True,
        bf16=True, gram="einsum", mesh=None)

    def fold_block(table, G, idx, w, counts):
        # the fold_in_rows seam verbatim: gather_dtype="bfloat16"
        # shadows the fixed table INTO the gather, accumulation f32
        return inner(table.astype(jnp.bfloat16), G, idx, w, counts,
                     0.1, 40.0)

    return jax.make_jaxpr(fold_block)(table, G, idx, w, counts)


def _entry_quantize_serving_model():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.als import _dequant_plain, _dequant_scaled

    data = np.zeros((64, 16), np.int8)
    scale = np.ones((64, 1), np.float32)
    bdata = jnp.zeros((64, 16), jnp.bfloat16)

    def funnels(data, scale, bdata):
        # the two blessed dequant funnels quantize_serving_model's
        # consumers route through
        return _dequant_scaled(data, scale), _dequant_plain(bdata)

    return jax.make_jaxpr(funnels)(data, scale, bdata)


def _topk_tables(quant: str):
    import jax.numpy as jnp
    import numpy as np

    from ..models.als import QuantizedFactors

    u = np.ones((32, 16), np.float32)
    v = np.ones((64, 16), np.float32)
    if quant == "off":
        return u, v
    if quant == "bf16":
        def mk(a):
            # ptpu: allow[quantize-without-parity-gate] — audit
            # fixture on a synthetic all-ones table; nothing serves it
            return QuantizedFactors(jnp.asarray(a, jnp.bfloat16),
                                    None, "bf16")
    else:
        def mk(a):
            # ptpu: allow[quantize-without-parity-gate] — audit
            # fixture on a synthetic all-ones table; nothing serves it
            return QuantizedFactors(
                np.ones(a.shape, np.int8),
                np.ones((a.shape[0], 1), np.float32), "int8")
    return mk(u), mk(v)


def _entry_device_topk(quant: str):
    import jax
    import numpy as np

    from ..models.als import _serve_topk

    u, v = _topk_tables(quant)
    idx = np.zeros((4,), np.int32)
    fn = jax.make_jaxpr(
        lambda uf, vf, i: _serve_topk(uf, vf, i, k=8, n_items=60))
    return fn(u, v, idx)


#: name → (builder, one-line description); ordered — the manifest and
#: the CI artifact list entries in this order
ENTRY_POINTS: Dict[str, Tuple[Callable[[], object], str]] = {
    "gramian_allreduce": (
        _entry_gramian_allreduce,
        "explicit per-shard Gramian partial + ICI psum"),
    "gather_rows": (
        _entry_gather_rows,
        "cross-shard user-row fetch"),
    "sharded_rank": (
        _entry_sharded_rank,
        "per-shard top-k + candidate all-gather (einsum ranker)"),
    "lhs_einsum": (
        _entry_lhs_einsum,
        "_lhs_fn normal-equation build (einsum lane)"),
    "lhs_fused": (
        _entry_lhs_fused,
        "_lhs_fn through the shard_map'd fused kernel"),
    "train_update_block": (
        _entry_train_update_block,
        "one ALS training block (gather+Gramian+solve)"),
    "seqrec_train_step": (
        _entry_seqrec_train_step,
        "sequential-model Adam step"),
    "sharded_topk": (
        _entry_sharded_topk,
        "two-phase global top-k over the (data=2, model=4) mesh"),
    "foldin_update_bf16": (
        _entry_foldin_update_bf16,
        "streaming fold-in solve under the bf16 gather shadow"),
    "quantize_serving_model": (
        _entry_quantize_serving_model,
        "the blessed dequant funnel pair (scaled int8 + plain bf16)"),
    "device_topk_off": (
        lambda: _entry_device_topk("off"),
        "fused serving dispatch (_serve_topk), plain f32 tables"),
    "device_topk_bf16": (
        lambda: _entry_device_topk("bf16"),
        "fused serving dispatch, bf16 tables (in-program upcast)"),
    "device_topk_int8": (
        lambda: _entry_device_topk("int8"),
        "fused serving dispatch, int8+scale tables"),
}


def run_audit(names: Optional[Sequence[str]] = None) -> dict:
    """Trace + census every (selected) entry point; returns the
    manifest dict. Needs the forced device count — half the entries
    trace through 8-device meshes."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < AUDIT_DEVICE_COUNT:
        raise AuditError(
            f"audit-numerics needs {AUDIT_DEVICE_COUNT} devices, found "
            f"{n_dev}; run in a fresh process (the CLI forces "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{AUDIT_DEVICE_COUNT} before importing jax)")
    unknown = set(names or ()) - set(ENTRY_POINTS)
    if unknown:
        raise AuditError(f"unknown entry point(s): {sorted(unknown)} "
                         f"(have: {sorted(ENTRY_POINTS)})")
    entries: Dict[str, dict] = {}
    for name, (builder, _desc) in ENTRY_POINTS.items():
        if names and name not in names:
            continue
        entries[name] = census_jaxpr(builder())
    return {"version": MANIFEST_VERSION,
            "devices": AUDIT_DEVICE_COUNT,
            "entries": entries}


# ---------------------------------------------------------------------------
# manifest I/O + ratchet diff
# ---------------------------------------------------------------------------

def load_manifest(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) \
            or doc.get("version") != MANIFEST_VERSION:
        raise ValueError(f"{path}: not an audit-numerics manifest "
                         f"(expected version {MANIFEST_VERSION})")
    return doc


def _clamp_counts(new: Dict[str, int], old: Dict[str, int]
                  ) -> Dict[str, int]:
    return {k: min(c, old[k]) for k, c in new.items() if k in old}


def write_manifest(path: str, manifest: dict,
                   cap: Optional[dict] = None) -> None:
    """Persist the manifest. With ``cap`` (the previously committed
    baseline) the write RATCHETS: entries/keys the old baseline never
    held are dropped and counts/bytes clamp to the recorded values —
    the file only shrinks (``--baseline-grow`` writes as-is)."""
    doc = manifest
    if cap is not None:
        old = cap.get("entries", {})
        entries: Dict[str, dict] = {}
        for name, rec in manifest.get("entries", {}).items():
            if name not in old:
                continue
            orec = old[name]
            oreds = orec.get("reductions", {})
            reds = {prim: _clamp_counts(by, oreds[prim])
                    for prim, by in rec.get("reductions", {}).items()
                    if prim in oreds}
            entries[name] = {
                "ops": _clamp_counts(rec.get("ops", {}),
                                     orec.get("ops", {})),
                "casts": _clamp_counts(rec.get("casts", {}),
                                       orec.get("casts", {})),
                "reductions": reds,
                "bytes": _clamp_counts(rec.get("bytes", {}),
                                       orec.get("bytes", {})),
            }
        doc = {"version": MANIFEST_VERSION,
               "devices": manifest.get("devices", AUDIT_DEVICE_COUNT),
               "entries": entries}
    from .baseline import atomic_write_text

    atomic_write_text(
        path, json.dumps(doc, indent=2, sort_keys=True) + "\n")


def diff_manifests(current: dict, baseline: dict
                   ) -> Tuple[List[str], List[str]]:
    """(violations, shrinkable) between a fresh census and the golden
    baseline. Violations name the entry, the op/cast and the counts —
    the line an operator greps for."""
    violations: List[str] = []
    shrinkable: List[str] = []
    if current.get("devices") != baseline.get("devices"):
        violations.append(
            f"device count {current.get('devices')} != baseline "
            f"{baseline.get('devices')} (mesh entries trace "
            f"topology-dependent programs; audit on the forced mesh)")
    cur = current.get("entries", {})
    base = baseline.get("entries", {})
    for name, rec in cur.items():
        brec = base.get(name)
        if brec is None:
            violations.append(
                f"{name}: entry point not in the baseline — record it "
                f"deliberately with --write-baseline --baseline-grow")
            continue
        bcasts = brec.get("casts", {})
        for key, c in sorted(rec.get("casts", {}).items()):
            b = bcasts.get(key, 0)
            if c > b:
                violations.append(
                    f"{name}: cast {key} x{c} (baseline {b}) — a new "
                    f"convert_element_type in the traced program. An "
                    f"upcast of quantized data materializes a wide "
                    f"copy (forfeits the serving-quant HBM win); a "
                    f"downcast drops mantissa: find the .astype or "
                    f"implicit promotion feeding this entry, or "
                    f"record deliberately with --baseline-grow")
            elif c < b:
                shrinkable.append(f"{name}: cast {key} recorded {b}, "
                                  f"found {c}")
        for key, b in sorted(bcasts.items()):
            if key not in rec.get("casts", {}):
                shrinkable.append(f"{name}: cast {key} recorded {b}, "
                                  f"found 0")
        breds = brec.get("reductions", {})
        for prim, by in sorted(rec.get("reductions", {}).items()):
            bby = breds.get(prim, {})
            for dt, c in sorted(by.items()):
                b = bby.get(dt, 0)
                if _is_low(dt) and c > b:
                    violations.append(
                        f"{name}: {prim} accumulating at {dt} x{c} "
                        f"(baseline {b}) — a reduction lost its f32 "
                        f"accumulator; restore "
                        f"preferred_element_type=jnp.float32 (the "
                        f"ops/gram.py contract) or record "
                        f"deliberately with --baseline-grow")
                elif c < b:
                    shrinkable.append(f"{name}: {prim}@{dt} recorded "
                                      f"{b}, found {c}")
        bbytes = brec.get("bytes", {})
        for dt, n in sorted(rec.get("bytes", {}).items()):
            b = bbytes.get(dt, 0)
            if n > b * BYTES_GROWTH_RATIO + BYTES_SLACK:
                violations.append(
                    f"{name}: {dt} result traffic {n}B vs baseline "
                    f"{b}B (> x{BYTES_GROWTH_RATIO} + {BYTES_SLACK}B "
                    f"slack) — the entry is materializing wider "
                    f"buffers (a dequantized table copy?); or "
                    f"--baseline-grow")
            elif n < b / BYTES_GROWTH_RATIO - BYTES_SLACK:
                shrinkable.append(f"{name}: {dt} bytes recorded {b}, "
                                  f"found {n}")
    for name in base:
        if name not in cur:
            shrinkable.append(f"{name}: entry point no longer audited")
    return violations, shrinkable


def format_text(manifest: dict) -> str:
    lines: List[str] = []
    for name, rec in manifest.get("entries", {}).items():
        ops = rec.get("ops", {})
        summary = ", ".join(f"{dt} x{c}"
                            for dt, c in sorted(ops.items())) \
            or "no ops"
        lines.append(f"{name}: {summary}")
        casts = rec.get("casts", {})
        if casts:
            lines.append("  casts: " + ", ".join(
                f"{k} x{c}" for k, c in sorted(casts.items())))
        for prim, by in sorted(rec.get("reductions", {}).items()):
            lines.append(f"  {prim}: " + ", ".join(
                f"{dt} x{c}" for dt, c in sorted(by.items())))
        low = {dt: n for dt, n in rec.get("bytes", {}).items()
               if _is_low(dt) or dt == "int8"}
        if low:
            lines.append("  low-precision bytes: " + ", ".join(
                f"{dt} {n}B" for dt, n in sorted(low.items())))
    return "\n".join(lines)


__all__ = (
    "AUDIT_DEVICE_COUNT",
    "AuditError",
    "BYTES_GROWTH_RATIO",
    "BYTES_SLACK",
    "DEFAULT_BASELINE",
    "ENTRY_POINTS",
    "LOW_PRECISION",
    "REDUCING_PRIMS",
    "census_jaxpr",
    "diff_manifests",
    "ensure_cpu_devices",
    "format_text",
    "load_manifest",
    "run_audit",
    "write_manifest",
)
