"""Numerics-flow rule family: dtype-lattice lint for the quantized stack.

ISSUE 13 made low precision load-bearing — int8/bf16 row-quantized
serving tables, bf16 gather shadows feeding the fold-in solver, and an
f32-accumulator contract inside every kernel. Until now only the
Pallas-scratch rule (``low-precision-accumulator``) watched any of it.
This family lifts the same discipline to the jnp level and to the
quantization seams, riding the PR 8 interprocedural engine
(:class:`~.core.ProjectIndex` carries per-function *dtype sinks*
propagated through the call graph like every other effect):

- ``low-precision-reduction`` — ``sum``/``mean``/``dot``/``einsum``/
  ``@`` over bf16/f16 operands without an f32
  ``preferred_element_type=`` (or an explicit upcast), in
  ``models/``/``ops/``/``streaming/``. The reduction may sit N helpers
  away: a function that reduces a *parameter* at operand precision
  exports a dtype sink on that position, and a caller passing a known
  bf16 value is flagged at its own call site with the chain in the
  message. bf16 has an 8-bit mantissa — summing a few hundred terms in
  it silently loses the low bits that fold-in solves and Gramians
  depend on.
- ``dequant-outside-funnel`` — f32 materialization of quantized table
  data (``.astype(jnp.float32)`` on an int8/bf16 value or on a
  ``.data`` leaf) anywhere but the blessed funnels
  (``dequantize_table`` / ``table_host_f32`` / ``_host_row_f32`` /
  the in-kernel post-wire upcasts). An ad-hoc dequant materializes a
  full-precision copy of the table and silently forfeits the
  4×-users-per-HBM-byte win that quantized serving bought.
- ``quantize-without-parity-gate`` — constructing ``QuantizedFactors``
  (or calling ``_quantize_rows``) outside
  ``quantize_serving_model``'s NDCG@10 parity probe / auto-fallback
  path (``apply_row_updates`` and ``extend_factor_rows`` re-quantize
  under an already-gated decision and are equally blessed).
- ``unguarded-domain`` — ``log``/``sqrt``/``rsqrt``/division applied
  to traced or accumulated values with no epsilon/clip guard.
  ``drift.py``'s ``max(x, 1e-9)`` is the blessed idiom; also honored:
  ``jnp.maximum``/``clip``/``where`` wrappers, ``+ eps`` shifts,
  enclosing ``if``/ternary tests over the same value, and counters
  that were ``+= 1``'d before the divide.
- ``requant-torn-pair`` — writing ``QuantizedFactors.data`` (attribute
  assignment or ``dataclasses.replace(…, data=…)``) without the paired
  ``scale`` update. Across the fold-in/hot-swap seam a torn pair
  dequantizes new rows with stale per-row scales — every affected
  score is silently wrong.

All five obey ``# ptpu: allow[rule] — justification`` pragmas; a pragma
at a reduction's *direct site* also stops the dtype sink from
propagating (blessing the helper blesses its callers). Runtime
complements: ``ptpu audit-numerics`` (:mod:`.numerics_audit`) ratchets
an abstract-eval dtype census per entry point, and
``PTPU_DEBUG_NUMERICS=1`` arms the checkify NaN/Inf sentinel
(:mod:`predictionio_tpu.obs.numerics`).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import (
    CheckContext,
    Finding,
    ModuleInfo,
    Witness,
    chain_related,
    chain_text,
    short_name,
)
from .sharding import _Assigns, _function_nodes

NUMERICS_RULES = (
    "low-precision-reduction",
    "dequant-outside-funnel",
    "quantize-without-parity-gate",
    "unguarded-domain",
    "requant-torn-pair",
)

#: directories the precision rules patrol — where quantized tables and
#: reductions actually live; utility/storage code stays unbothered
_HOT_DIRS = {"models", "ops", "streaming"}
_DEQUANT_DIRS = {"models", "ops", "streaming", "server"}

_LOW = {"bfloat16", "float16"}
_WIDE = {"float32", "float64"}
_QUANT = {"int8", "bfloat16", "float16"}

_DTYPE_TOKENS = {
    "bfloat16", "float16", "float32", "float64", "int8", "int16",
    "int32", "int64", "uint8", "uint16", "uint32", "float8_e4m3fn",
    "float8_e5m2",
}

#: array-creation callees whose ``dtype=`` kwarg types the result
_CREATION = {"zeros", "ones", "full", "empty", "array", "asarray",
             "arange", "zeros_like", "ones_like", "full_like",
             "empty_like"}

#: dtype-preserving wrappers `_param_source` sees through
_PRESERVE_METHODS = {"reshape", "transpose", "ravel", "flatten",
                     "squeeze", "copy", "conj"}
_PRESERVE_CALLS = {"reshape", "transpose", "asarray", "ravel",
                   "squeeze", "expand_dims", "broadcast_to", "pad",
                   "atleast_2d", "ascontiguousarray"}

#: reduction callees → positional operand slots that set the
#: accumulation dtype (einsum is special-cased: operands follow the
#: subscript string)
_REDUCE_CALLS: Dict[str, Tuple[int, ...]] = {
    "jax.numpy.sum": (0,), "jax.numpy.mean": (0,),
    "jax.numpy.prod": (0,), "jax.numpy.dot": (0, 1),
    "jax.numpy.vdot": (0, 1), "jax.numpy.inner": (0, 1),
    "jax.numpy.matmul": (0, 1), "jax.numpy.tensordot": (0, 1),
    "jax.lax.dot": (0, 1), "jax.lax.dot_general": (0, 1),
    "numpy.sum": (0,), "numpy.mean": (0,), "numpy.dot": (0, 1),
    "numpy.matmul": (0, 1), "numpy.tensordot": (0, 1),
}
_REDUCE_METHODS = {"sum", "mean", "prod", "dot"}

#: unary ops with a restricted domain (operand must be > 0 / >= 0)
_DOMAIN_CALLS = {
    "jax.numpy.log", "jax.numpy.log2", "jax.numpy.log10",
    "jax.numpy.sqrt", "jax.lax.rsqrt", "jax.lax.sqrt",
    "numpy.log", "numpy.log2", "numpy.log10", "numpy.sqrt",
    "math.log", "math.log2", "math.log10", "math.sqrt",
}

#: called on the operand of a domain op / a divisor, these make the
#: value safe: positive-clamped, shifted, or branch-selected
_GUARD_TEXT = ("maximum(", "max(", "clip(", "where(", "errstate",
               "abs(", "> 0", ">= 1", "!= 0")

_DEQUANT_FUNNELS = {"dequantize_table", "table_host_f32",
                    "_host_row_f32"}
_PARITY_FUNNELS = {"quantize_serving_model", "apply_row_updates",
                   "extend_factor_rows", "_quantize_rows"}

_EPS_NAME = re.compile(r"(^|_)(eps|epsilon)\w*$")


# ---------------------------------------------------------------------------
# cheap per-module text gates (memoized on ModuleInfo — the PR 14
# perf pattern: the scan is O(repo), the AST passes must not be)
# ---------------------------------------------------------------------------

def _mentions_lowprec(mod: ModuleInfo) -> bool:
    cached = getattr(mod, "_lowprec_hint", None)
    if cached is None:
        cached = ("bfloat16" in mod.source or "float16" in mod.source)
        mod._lowprec_hint = cached
    return cached


def _mentions_reduction(mod: ModuleInfo) -> bool:
    cached = getattr(mod, "_reduce_hint", None)
    if cached is None:
        src = mod.source
        cached = any(t in src for t in (
            "einsum(", ".sum(", ".mean(", "jnp.dot", "dot_general",
            "matmul", " @ ", "tensordot", "vdot", "jnp.sum",
            "jnp.mean"))
        mod._reduce_hint = cached
    return cached


def _in_dirs(mod: ModuleInfo, dirs: Set[str]) -> bool:
    return bool(set(mod.path.split("/")[:-1]) & dirs)


# ---------------------------------------------------------------------------
# dtype lattice: literal dtype inference over one function's locals
# ---------------------------------------------------------------------------

def _dtype_token(mod: ModuleInfo, assigns: _Assigns,
                 node: ast.AST) -> Optional[str]:
    """``jnp.bfloat16`` / ``ml_dtypes.bfloat16`` / ``"bfloat16"`` →
    ``"bfloat16"`` — the canonical dtype string of a dtype
    expression, or None when it cannot be pinned."""
    node = assigns.follow(node)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_TOKENS else None
    resolved = mod.resolve(node) or ""
    last = resolved.rsplit(".", 1)[-1]
    return last if last in _DTYPE_TOKENS else None


def _expr_dtype(mod: ModuleInfo, assigns: _Assigns, node: ast.AST,
                dmap: Optional[Dict[str, Tuple[str, int]]] = None,
                depth: int = 0) -> Optional[str]:
    """Best-effort dtype of a value expression: ``x.astype(D)``,
    creation calls with ``dtype=D``, and names followed through the
    local assignment map."""
    if depth > 6:
        return None
    if isinstance(node, ast.Name) and dmap and node.id in dmap:
        return dmap[node.id][0]
    node = assigns.follow(node)
    if isinstance(node, ast.Name) and dmap and node.id in dmap:
        return dmap[node.id][0]
    if isinstance(node, ast.IfExp):
        # `t.astype(jnp.bfloat16) if cond else t`: the conditional
        # gather-shadow idiom — if EITHER branch is low precision the
        # value may be, and the reduction may be lossy
        for branch in (node.body, node.orelse):
            dt = _expr_dtype(mod, assigns, branch, dmap, depth + 1)
            if dt in _LOW:
                return dt
        return None
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "astype" \
            and node.args:
        return _dtype_token(mod, assigns, node.args[0])
    resolved = mod.resolve(f) or ""
    last = resolved.rsplit(".", 1)[-1]
    if last in _CREATION:
        for kw in node.keywords:
            if kw.arg == "dtype":
                return _dtype_token(mod, assigns, kw.value)
        if last in ("zeros", "ones", "empty") and len(node.args) >= 2:
            return _dtype_token(mod, assigns, node.args[1])
    return None


def local_dtype_map(mod: ModuleInfo, fn: ast.AST
                    ) -> Dict[str, Tuple[str, int]]:
    """Variable → (dtype, line) facts inside one function, from
    ``x = y.astype(jnp.bfloat16)`` and dtype'd creation calls —
    memoized per function (``ptpu check`` runs this from two rules and
    the sink collector)."""
    memo = getattr(mod, "_dtype_maps", None)
    if memo is None:
        memo = mod._dtype_maps = {}
    cached = memo.get(id(fn))
    if cached is not None:
        return cached
    assigns = _Assigns(mod, fn)
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        dt = _expr_dtype(mod, assigns, node.value, out)
        if dt is not None:
            out[node.targets[0].id] = (dt, node.lineno)
    memo[id(fn)] = out
    return out


# ---------------------------------------------------------------------------
# reductions: direct sites + interprocedural dtype sinks
# ---------------------------------------------------------------------------

def _widened(mod: ModuleInfo, assigns: _Assigns,
             call: ast.Call) -> bool:
    """An explicit wide accumulator on the call: f32/f64
    ``preferred_element_type=`` / ``dtype=`` / ``acc_dtype=``."""
    for kw in call.keywords:
        if kw.arg in ("preferred_element_type", "dtype", "acc_dtype"):
            if _dtype_token(mod, assigns, kw.value) in _WIDE:
                return True
    return False


def _reduction_operands(mod: ModuleInfo, assigns: _Assigns,
                        node: ast.AST
                        ) -> Iterable[Tuple[ast.AST, str]]:
    """(operand expression, human description) pairs for a reduction
    site that accumulates at operand precision (nothing yielded when
    the call already declares a wide accumulator)."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
        yield node.left, "`@` matmul"
        yield node.right, "`@` matmul"
        return
    if not isinstance(node, ast.Call):
        return
    if _widened(mod, assigns, node):
        return
    f = node.func
    resolved = mod.resolve(f) or ""
    if resolved in _REDUCE_CALLS:
        short = resolved.rsplit(".", 1)[-1]
        for pos in _REDUCE_CALLS[resolved]:
            if pos < len(node.args):
                yield node.args[pos], f"`{short}`"
        return
    if resolved.rsplit(".", 1)[-1] == "einsum" and len(node.args) > 1:
        for a in node.args[1:]:
            yield a, "`einsum`"
        return
    if isinstance(f, ast.Attribute) and f.attr in _REDUCE_METHODS \
            and not isinstance(f.value, ast.Constant):
        yield f.value, f"`.{f.attr}()`"
        if f.attr == "dot" and node.args:
            yield node.args[0], "`.dot()`"


def _param_source(mod: ModuleInfo, assigns: _Assigns,
                  params: List[str], node: ast.AST,
                  depth: int = 0) -> Optional[int]:
    """Parameter position an expression derives from through
    dtype-PRESERVING wrappers (subscript, reshape/transpose, ``.T``,
    plain ``asarray``). ``astype`` breaks the chain — an upcast at the
    call site is the fix, not a finding."""
    if depth > 6:
        return None
    node = assigns.follow(node)
    if isinstance(node, ast.Name):
        return params.index(node.id) if node.id in params else None
    if isinstance(node, ast.Subscript):
        return _param_source(mod, assigns, params, node.value,
                             depth + 1)
    if isinstance(node, ast.Attribute) and node.attr == "T":
        return _param_source(mod, assigns, params, node.value,
                             depth + 1)
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) \
                and f.attr in _PRESERVE_METHODS:
            return _param_source(mod, assigns, params, f.value,
                                 depth + 1)
        resolved = mod.resolve(f) or ""
        if resolved.rsplit(".", 1)[-1] in _PRESERVE_CALLS \
                and node.args \
                and not any(kw.arg == "dtype" for kw in node.keywords):
            return _param_source(mod, assigns, params, node.args[0],
                                 depth + 1)
    return None


def collect_lowprec_sinks(fn_info) -> Dict[int, Witness]:
    """Parameter position → witness for params this function reduces
    at operand precision (no f32 ``preferred_element_type``/upcast):
    the direct sites of ``low-precision-reduction``. A pragma at the
    reduction kills the sink — blessing the helper blesses callers.
    Collected by :meth:`~.core.ProjectIndex._collect_direct` and
    propagated through the call graph like every other effect."""
    mod: ModuleInfo = fn_info.mod
    params: List[str] = fn_info.params
    if not params or not _mentions_reduction(mod):
        return {}
    assigns = _Assigns(mod, fn_info.node)
    out: Dict[int, Witness] = {}
    for node in ast.walk(fn_info.node):
        for operand, desc in _reduction_operands(mod, assigns, node):
            pos = _param_source(mod, assigns, params, operand)
            if pos is None or pos in out:
                continue
            if mod.suppressed(Finding("low-precision-reduction",
                                      mod.path, node.lineno, 0, "")):
                continue
            out[pos] = Witness(
                "low-precision-reduction", mod.path, node.lineno,
                node.col_offset,
                f"{desc} reduces `{params[pos]}` at operand precision "
                f"(no f32 preferred_element_type / upcast)")
    return out


def rule_low_precision_reduction(mods: Sequence[ModuleInfo],
                                 ctx: CheckContext) -> List[Finding]:
    """A reduction over bf16/f16 operands accumulating at operand
    precision — directly, or through any helper chain whose leaf
    reduction trusts its caller's dtype. bf16's 8-bit mantissa makes
    long sums lossy; the repo contract (ops/gram.py, the Pallas
    kernels) is an explicit f32 accumulator."""
    proj = ctx.project
    findings: List[Finding] = []
    for mod in mods:
        if not _in_dirs(mod, _HOT_DIRS) or not _mentions_lowprec(mod):
            continue
        for cls, fn in _function_nodes(mod):
            assigns = _Assigns(mod, fn)
            dmap = local_dtype_map(mod, fn)
            for node in ast.walk(fn):
                # direct: reducing a known-low-precision value
                hit = False
                for operand, desc in _reduction_operands(mod, assigns,
                                                         node):
                    dt = _expr_dtype(mod, assigns, operand, dmap)
                    if dt not in _LOW:
                        continue
                    findings.append(Finding(
                        "low-precision-reduction", mod.path,
                        node.lineno, node.col_offset,
                        f"{desc} over {dt} operands accumulates in "
                        f"{dt}: an 8-bit mantissa loses the low bits "
                        f"of every long sum — declare the accumulator "
                        f"wide (preferred_element_type=jnp.float32, "
                        f"the ops/gram.py contract) or upcast the "
                        f"operand first"))
                    hit = True
                    break
                if hit or not isinstance(node, ast.Call) \
                        or proj is None:
                    continue
                # interprocedural: a known-low value passed into a
                # helper that (transitively) reduces that position
                qname, bound = proj.resolve_call(mod, cls, node.func)
                callee = proj.functions.get(qname or "")
                if callee is None or not callee.lowprec_sinks:
                    continue
                off = 1 if bound else 0
                for i, a in enumerate(node.args):
                    dt = _expr_dtype(mod, assigns, a, dmap)
                    if dt not in _LOW:
                        continue
                    pos = i + off
                    if pos not in callee.lowprec_sinks:
                        continue
                    hops = proj.sink_chain(callee, "lowprec", pos)
                    findings.append(Finding(
                        "low-precision-reduction", mod.path,
                        node.lineno, node.col_offset,
                        f"this {dt} argument reaches a reduction that "
                        f"accumulates at operand precision: "
                        f"{chain_text(hops)} — widen the accumulator "
                        f"at the direct site "
                        f"(preferred_element_type=jnp.float32) or "
                        f"upcast before the call",
                        related=chain_related(hops)))
                    break
    return findings


# ---------------------------------------------------------------------------
# rule: dequant-outside-funnel
# ---------------------------------------------------------------------------

def _module_level_name(mod: ModuleInfo, node: ast.AST) -> str:
    """For a site at module level (outside any def): the Assign target
    name whose statement contains it, so the ``_dequant_*`` jit
    lambdas bless themselves."""
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) \
                and stmt.lineno <= node.lineno <= (stmt.end_lineno
                                                   or stmt.lineno) \
                and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            return stmt.targets[0].id
    return ""


def rule_dequant_outside_funnel(mod: ModuleInfo,
                                ctx: CheckContext) -> List[Finding]:
    """f32 materialization of quantized table data outside the blessed
    dequant funnels — the silent HBM-win defeat: one stray
    ``.astype(jnp.float32)`` keeps a full-precision copy of a table
    that was quantized precisely so it would not exist."""
    if not _in_dirs(mod, _DEQUANT_DIRS) or "astype" not in mod.source:
        return []
    findings: List[Finding] = []
    covered: Set[int] = set()

    def scan(owner: str, scope: ast.AST,
             dmap: Dict[str, Tuple[str, int]],
             assigns: _Assigns) -> None:
        blessed = owner in _DEQUANT_FUNNELS \
            or owner.startswith("_dequant")
        for node in ast.walk(scope):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and scope is mod.tree:
                continue  # handled with its own owner
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args):
                continue
            if id(node) in covered:
                continue
            covered.add(id(node))
            if _dtype_token(mod, assigns, node.args[0]) not in _WIDE:
                continue
            recv = node.func.value
            seg = ast.get_source_segment(mod.source, recv) or ""
            followed = assigns.follow(recv)
            fseg = ast.get_source_segment(mod.source, followed) or ""
            quantized = (".data" in seg or ".data" in fseg
                         or _expr_dtype(mod, assigns, recv,
                                        dmap) in _QUANT)
            if not quantized:
                continue
            site_owner = owner or _module_level_name(mod, node)
            if site_owner in _DEQUANT_FUNNELS \
                    or site_owner.startswith("_dequant") or blessed:
                continue
            findings.append(Finding(
                "dequant-outside-funnel", mod.path, node.lineno,
                node.col_offset,
                "f32 materialization of quantized table data outside "
                "the blessed funnels: this builds a full-precision "
                "copy of a table quantized to avoid exactly that — "
                "route through dequantize_table / table_host_f32 / "
                "_host_row_f32, or upcast inside the kernel after "
                "the wire"))

    for _, fn in _function_nodes(mod):
        scan(fn.name, fn, local_dtype_map(mod, fn), _Assigns(mod, fn))
    scan("", mod.tree, {}, _Assigns(mod))
    return findings


# ---------------------------------------------------------------------------
# rule: quantize-without-parity-gate
# ---------------------------------------------------------------------------

def _copies_quant(node: ast.Call) -> bool:
    """The ``quant`` slot (3rd positional / ``quant=``) reads some
    existing table's ``.quant`` attribute."""
    exprs: List[ast.AST] = []
    if len(node.args) >= 3:
        exprs.append(node.args[2])
    exprs += [kw.value for kw in node.keywords if kw.arg == "quant"]
    return any(isinstance(e, ast.Attribute) and e.attr == "quant"
               for e in exprs)


def rule_quantize_without_parity_gate(mod: ModuleInfo,
                                      ctx: CheckContext
                                      ) -> List[Finding]:
    """Raw construction of quantized serving tables —
    ``QuantizedFactors(...)`` or ``_quantize_rows(...)`` — outside the
    parity-gated path. ``quantize_serving_model`` probes NDCG@10
    against the f32 tables and auto-falls-back below the floor; a raw
    construction skips the probe and can ship a table that scores
    garbage."""
    if "QuantizedFactors" not in mod.source \
            and "_quantize_rows" not in mod.source:
        return []
    findings: List[Finding] = []
    for cls, fn in _function_nodes(mod):
        if fn.name in _PARITY_FUNNELS or cls == "QuantizedFactors":
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func) or ""
            last = resolved.rsplit(".", 1)[-1]
            if last not in ("QuantizedFactors", "_quantize_rows"):
                continue
            if last == "QuantizedFactors" and _copies_quant(node):
                # copy-constructor signature: quant= carries an
                # EXISTING table's `.quant` — a residency/pinning move
                # propagating an already-gated decision, not a fresh
                # quantization
                continue
            findings.append(Finding(
                "quantize-without-parity-gate", mod.path, node.lineno,
                node.col_offset,
                f"`{last}` constructs a quantized serving table "
                f"outside the parity gate — route through "
                f"quantize_serving_model (NDCG@10 probe + auto "
                f"fallback below SERVING_QUANT_NDCG_FLOOR) so a "
                f"quality regression falls back to f32 instead of "
                f"shipping"))
    return findings


# ---------------------------------------------------------------------------
# rule: unguarded-domain
# ---------------------------------------------------------------------------

_WORD = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _src(mod: ModuleInfo, node: ast.AST) -> str:
    return ast.get_source_segment(mod.source, node) or ""


def _int_params(fn: ast.AST) -> Set[str]:
    """Params statically annotated ``int`` — compile-time shape/config
    scalars, not traced values."""
    out: Set[str] = set()
    a = fn.args
    for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id == "int":
            out.add(p.arg)
    return out


def _literal_defaults(fn: ast.AST) -> Set[str]:
    """Params whose default is a positive numeric literal (the
    ``lam: float = 1.0`` Laplace-smoothing idiom)."""
    out: Set[str] = set()
    a = fn.args
    pos = [*a.posonlyargs, *a.args]
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        if isinstance(d, ast.Constant) \
                and isinstance(d.value, (int, float)) and d.value > 0:
            out.add(p.arg)
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if isinstance(d, ast.Constant) \
                and isinstance(d.value, (int, float)) and d.value > 0:
            out.add(p.arg)
    return out


class _DomainScope:
    """Per-function context for the guard battery: conditional test
    texts (``if``/ternary/``while``/``assert``), ``+=``'d counters,
    int-annotated params, positive-literal defaults."""

    def __init__(self, mod: ModuleInfo, fn: ast.AST,
                 assigns: _Assigns):
        self.mod = mod
        self.assigns = assigns
        self.tests: List[str] = []
        self.bumped: Set[str] = set()
        self.int_params = _int_params(fn)
        self.pos_defaults = _literal_defaults(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.IfExp, ast.While,
                                 ast.Assert)):
                self.tests.append(_src(mod, node.test))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Add) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, (int, float)) \
                    and node.value.value > 0:
                self.bumped.add(_src(mod, node.target))

    def tested(self, text: str) -> bool:
        """Some conditional in the function mentions this expression
        (or one of its identifier tokens, word-bounded) — the
        ``if ideal else 0.0`` / early-return-guard family."""
        if not text:
            return False
        tokens = set(_WORD.findall(text)) - {
            "jnp", "np", "jax", "math", "lax"}
        for t in self.tests:
            if text in t:
                return True
            for tok in tokens:
                if re.search(rf"\b{re.escape(tok)}\b", t):
                    return True
        return False


def _static_positive(mod: ModuleInfo, scope: _DomainScope,
                     node: ast.AST, depth: int = 0) -> bool:
    """Compile-time-positive: numeric literals, arithmetic over them,
    int-annotated params through ``float()``/``int()``, names followed
    to any of those."""
    if depth > 6:
        return False
    node = scope.assigns.follow(node)
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool) and node.value > 0
    if isinstance(node, ast.Name):
        return node.id in scope.int_params \
            or node.id in scope.pos_defaults
    if isinstance(node, ast.BinOp) \
            and isinstance(node.op, (ast.Mult, ast.Add, ast.Pow)):
        return _static_positive(mod, scope, node.left, depth + 1) \
            and _static_positive(mod, scope, node.right, depth + 1)
    if isinstance(node, ast.Call):
        resolved = mod.resolve(node.func) or ""
        last = resolved.rsplit(".", 1)[-1]
        if last in ("float", "int") and node.args:
            return _static_positive(mod, scope, node.args[0],
                                    depth + 1)
        if last == "exp":
            return True  # e^x > 0 always
    return False


def _domain_guarded(mod: ModuleInfo, scope: _DomainScope,
                    node: ast.AST, depth: int = 0) -> bool:
    """The blessed guard battery for one operand/divisor."""
    if depth > 4:
        return False
    if _static_positive(mod, scope, node):
        return True
    followed = scope.assigns.follow(node)
    for probe in (node, followed):
        seg = _src(mod, probe)
        if seg and any(g in seg for g in _GUARD_TEXT):
            return True
        if isinstance(probe, ast.Constant):
            return True  # non-numeric constant: not our domain
    if scope.tested(_src(mod, node)) \
            or scope.tested(_src(mod, followed)):
        return True
    seg = _src(mod, node)
    if seg in scope.bumped or _src(mod, followed) in scope.bumped:
        return True
    if isinstance(followed, ast.BinOp) \
            and isinstance(followed.op, ast.Add):
        # `x + eps` shift: either side a positive literal / eps name
        for side in (followed.left, followed.right):
            s = scope.assigns.follow(side)
            if _static_positive(mod, scope, s):
                return True
            if isinstance(side, ast.Name) \
                    and _EPS_NAME.search(side.id):
                return True
    if isinstance(followed, ast.Call):
        resolved = mod.resolve(followed.func) or ""
        last = resolved.rsplit(".", 1)[-1]
        if last in ("exp", "float", "int", "len", "abs") \
                and (last == "exp" or not followed.args
                     or _domain_guarded(mod, scope,
                                        followed.args[0], depth + 1)
                     or scope.tested(_src(mod, followed))):
            # len()/abs()/float() of something itself guarded or
            # tested; exp() is positive unconditionally
            if last == "exp":
                return True
            if last in ("float", "int") and followed.args \
                    and _static_positive(mod, scope,
                                         followed.args[0]):
                return True
            if scope.tested(_src(mod, node)) \
                    or scope.tested(_src(mod, followed)):
                return True
        if last in ("log", "log2", "log10", "sqrt") and followed.args:
            # log/sqrt of a shifted/guarded argument is bounded away
            # from the pole for the shifted-index idiom
            # (`1 / log2(i + 2)`)
            return _domain_guarded(mod, scope, followed.args[0],
                                   depth + 1)
    return False


def rule_unguarded_domain(mod: ModuleInfo,
                          ctx: CheckContext) -> List[Finding]:
    """``log``/``sqrt``/``rsqrt``/division applied to traced or
    accumulated values without an epsilon/clip guard. NaN/Inf born
    here propagates through every downstream op and surfaces as
    garbage scores long after the cause — guard at the source
    (``max(x, 1e-9)`` per drift.py, ``jnp.maximum(x, eps)``,
    ``+ eps``, or a clip/where)."""
    if not _in_dirs(mod, _HOT_DIRS):
        return []
    findings: List[Finding] = []
    for _, fn in _function_nodes(mod):
        assigns = _Assigns(mod, fn)
        scope = _DomainScope(mod, fn, assigns)
        for node in ast.walk(fn):
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Div):
                if _domain_guarded(mod, scope, node.right):
                    continue
                findings.append(Finding(
                    "unguarded-domain", mod.path, node.lineno,
                    node.col_offset,
                    f"division by `{_src(mod, node.right)}` with no "
                    f"zero guard — a zero divisor mints NaN/Inf that "
                    f"propagates silently; guard the divisor "
                    f"(max(x, 1e-9) per drift.py, jnp.maximum(x, "
                    f"eps), or + eps)"))
            elif isinstance(node, ast.Call):
                resolved = mod.resolve(node.func) or ""
                if resolved not in _DOMAIN_CALLS or not node.args:
                    continue
                if _domain_guarded(mod, scope, node.args[0]):
                    continue
                short = resolved.rsplit(".", 1)[-1]
                findings.append(Finding(
                    "unguarded-domain", mod.path, node.lineno,
                    node.col_offset,
                    f"`{short}` of `{_src(mod, node.args[0])}` with "
                    f"no domain guard — negative/zero input mints "
                    f"NaN/-Inf; clamp first (jnp.maximum(x, eps), "
                    f"clip, or an explicit branch)"))
    return findings


# ---------------------------------------------------------------------------
# rule: requant-torn-pair
# ---------------------------------------------------------------------------

def _quantish_names(mod: ModuleInfo, fn: ast.AST) -> Set[str]:
    """Names this function can prove hold a ``QuantizedFactors``:
    annotated params, construction assignments, isinstance checks."""
    names: Set[str] = set()
    a = fn.args
    for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
        ann = p.annotation
        if ann is not None \
                and (mod.resolve(ann) or "").rsplit(".", 1)[-1] \
                == "QuantizedFactors":
            names.add(p.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and (mod.resolve(node.value.func) or "").rsplit(
                    ".", 1)[-1] == "QuantizedFactors":
            names.add(node.targets[0].id)
        if isinstance(node, ast.Call) \
                and (mod.resolve(node.func) or "").rsplit(
                    ".", 1)[-1] == "isinstance" \
                and len(node.args) == 2 \
                and isinstance(node.args[0], ast.Name) \
                and (mod.resolve(node.args[1]) or "").rsplit(
                    ".", 1)[-1] == "QuantizedFactors":
            names.add(node.args[0].id)
    return names


def rule_requant_torn_pair(mod: ModuleInfo,
                           ctx: CheckContext) -> List[Finding]:
    """A write to ``QuantizedFactors.data`` without the paired
    ``scale`` update — attribute assignment or
    ``dataclasses.replace(…, data=…)`` missing ``scale=``. int8 rows
    dequantize as ``data * scale``; a torn pair serves every affected
    row through a stale per-row scale (silently wrong scores, no
    crash). ``apply_row_updates`` is the blessed seam: it re-quantizes
    rows and swaps data+scale together."""
    if "QuantizedFactors" not in mod.source:
        return []
    findings: List[Finding] = []
    for _, fn in _function_nodes(mod):
        quantish = _quantish_names(mod, fn)
        if not quantish:
            continue
        scale_written: Set[str] = set()
        data_writes: List[Tuple[str, ast.AST]] = []
        for node in ast.walk(fn):
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in quantish:
                    if t.attr == "scale":
                        scale_written.add(t.value.id)
                    elif t.attr == "data":
                        data_writes.append((t.value.id, node))
            if isinstance(node, ast.Call) \
                    and (mod.resolve(node.func) or "").rsplit(
                        ".", 1)[-1] == "replace" \
                    and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in quantish:
                kws = {kw.arg for kw in node.keywords}
                if "data" in kws and "scale" not in kws:
                    findings.append(Finding(
                        "requant-torn-pair", mod.path, node.lineno,
                        node.col_offset,
                        f"replace(…, data=…) on "
                        f"`{node.args[0].id}` without the paired "
                        f"scale= — new int8 rows dequantize through "
                        f"STALE per-row scales; re-quantize and swap "
                        f"data+scale together "
                        f"(apply_row_updates is the blessed seam)"))
        for name, node in data_writes:
            if name in scale_written:
                continue
            findings.append(Finding(
                "requant-torn-pair", mod.path, node.lineno,
                node.col_offset,
                f"`{name}.data` written without the paired "
                f"`{name}.scale` update — rows dequantize as "
                f"data * scale, so a torn pair serves silently wrong "
                f"scores; swap both leaves together "
                f"(apply_row_updates is the blessed seam)"))
    return findings


__all__ = [
    "NUMERICS_RULES",
    "collect_lowprec_sinks",
    "local_dtype_map",
    "rule_dequant_outside_funnel",
    "rule_low_precision_reduction",
    "rule_quantize_without_parity_gate",
    "rule_requant_torn_pair",
    "rule_unguarded_domain",
]
