"""``ptpu audit-hlo`` — the compiled-HLO sharding audit.

The static sharding-flow rules (:mod:`.sharding`) catch spec
disagreements the AST can see; this module catches the ones only XLA
sees. It compiles the framework's registered SPMD entry points on a
forced 8-device CPU mesh (``.lower().compile()`` — no TPU needed, the
GSPMD partitioner runs identically), parses the optimized HLO for
collective ops (all-gather / all-reduce / reduce-scatter / all-to-all /
collective-permute) and the executable's temp-buffer allocation, and
diffs the result against a committed golden manifest
(``analysis/hlo_baseline.json``) with the same ratchet semantics as
the ``ptpu check`` baseline:

- a collective op a baseline entry does not record — or a count above
  the recorded one — FAILS, with the op name, its result shape, and
  the entry point named: an accidental reshard introduced three
  helpers away is caught in CI before it eats ICI bandwidth on a real
  mesh;
- temp bytes above ``TEMP_GROWTH_RATIO`` × recorded (plus a fixed
  slack) fail the same way — a spec change that materializes a
  gathered table shows up here even when the collective count is
  unchanged;
- counts/temps BELOW the record print as shrinkable, and
  ``--write-baseline`` only ever ratchets the file down; recording new
  collectives (a deliberately added entry point or schedule change)
  takes the explicit ``--baseline-grow``.

Everything jax-flavored imports lazily: ``ptpu check`` must stay
importable on a storage-only host, and the CLI sets
``JAX_PLATFORMS=cpu`` + the forced-device-count flag *before* the
first jax import (:func:`ensure_cpu_devices`).

Entry points audited (small shapes — the *collective structure* is
shape-independent, which is exactly why a golden manifest works):

- ``gramian_allreduce`` — the explicit per-shard partial + ICI psum
  (``parallel/collectives.py``); the overlapped-all-reduce contract.
- ``gather_rows`` — ``models/als.py::_gather_rows_fn``: the GSPMD
  collective resolving a cross-shard user-row fetch.
- ``sharded_rank`` — ``_sharded_rank_fn``: per-shard top-k + the
  O(k·n_dev) candidate all-gather (einsum realization).
- ``lhs_einsum`` — ``_lhs_fn`` under GSPMD with row-sharded
  table/indices: the half-step's derived gather collective.
- ``lhs_fused`` — ``_lhs_fn`` routed through the shard_map'd fused
  kernel (interpret mode on CPU): the replicated-table boundary's
  all-gather, and nothing else.
- ``train_update_block`` — ``_update_block``: one whole training
  block (gather + Gramian + solve) under GSPMD.
- ``seqrec_train_step`` — ``models/seqrec.py::_train_step`` with
  replicated weights and a row-sharded batch: the gradient
  all-reduces XLA derives for data parallelism.
- ``sharded_topk`` — ``parallel/collectives.py::sharded_top_k`` over
  a ``(data=2, model=4)`` mesh's model axis.

See docs/parallelism.md ("How to read an audit-hlo diff") and
docs/static-analysis.md.
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

MANIFEST_VERSION = 1
AUDIT_DEVICE_COUNT = 8

#: temp allocation may grow this factor (plus slack) over the recorded
#: baseline before the gate fails — fusion-order jitter across XLA
#: builds moves temps a little; a materialized gathered table moves
#: them a lot
TEMP_GROWTH_RATIO = 1.5
TEMP_SLACK_BYTES = 64 * 1024

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "hlo_baseline.json")

#: `= <shape> <op>(`-form HLO instruction heads; `-start` variants
#: count (async launch), `-done` halves do not (they would double
#: count the same collective)
_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-gather|all-reduce|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)(-start)?\(")


class AuditError(RuntimeError):
    """Environment/usage errors (wrong device count, unknown entry)."""


def ensure_cpu_devices(n: int = AUDIT_DEVICE_COUNT) -> None:
    """Arrange for ``n`` forced CPU devices — MUST run before the
    first jax import (the flags are read at backend init). A process
    that already imported jax with a different topology cannot be
    fixed up; :func:`run_audit` verifies the live device count."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}"
        ).strip()


def parse_collectives(hlo: str) -> Tuple[Dict[str, int],
                                         Dict[str, List[str]]]:
    """(op → count, op → result shapes) over one compiled module's
    HLO text."""
    counts: Dict[str, int] = {}
    shapes: Dict[str, List[str]] = {}
    for m in _COLLECTIVE_RE.finditer(hlo):
        op = m.group(2)
        counts[op] = counts.get(op, 0) + 1
        shapes.setdefault(op, []).append(m.group(1))
    return counts, shapes


def audit_compiled(compiled) -> dict:
    """One entry-point record: collectives (count + shapes) and the
    executable's temp allocation."""
    counts, shapes = parse_collectives(compiled.as_text())
    temp = 0
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
    except Exception:  # noqa: BLE001 — backend-optional API
        temp = 0
    return {"collectives": counts,
            "collective_shapes": shapes,
            "temp_bytes": temp}


# ---------------------------------------------------------------------------
# entry-point builders (each returns a jax.stages.Compiled)
# ---------------------------------------------------------------------------

def _serving_mesh():
    from ..parallel.mesh import make_serving_mesh

    return make_serving_mesh()


def _training_mesh():
    from ..parallel.mesh import make_mesh

    return make_mesh()


def _rows(mesh, arr):
    import jax
    from jax.sharding import NamedSharding

    from ..parallel.mesh import rows_spec

    return jax.device_put(arr, NamedSharding(mesh, rows_spec(mesh)))


def _entry_gramian_allreduce():
    import jax
    import numpy as np

    from ..parallel.collectives import gramian_allreduce

    mesh = _training_mesh()
    x = _rows(mesh, np.ones((8 * mesh.devices.size, 16), np.float32))
    return jax.jit(lambda t: gramian_allreduce(t, mesh)).lower(x).compile()


def _entry_gather_rows():
    import numpy as np

    from ..models.als import _gather_rows_fn

    mesh = _serving_mesh()
    table = _rows(mesh, np.ones((8 * mesh.devices.size, 16), np.float32))
    idx = np.zeros((4,), np.int64)
    return _gather_rows_fn(mesh).lower(table, idx).compile()


def _entry_sharded_rank():
    import numpy as np

    from ..models.als import _sharded_rank_fn

    mesh = _serving_mesh()
    n = 8 * mesh.devices.size
    table = _rows(mesh, np.ones((n, 16), np.float32))
    vecs = np.ones((4, 16), np.float32)
    fn = _sharded_rank_fn(mesh, 8, 8, n)
    return fn.lower(vecs, table).compile()


def _lhs_inputs(mesh):
    import numpy as np

    n_dev = mesh.devices.size
    table = _rows(mesh, np.ones((8 * n_dev, 16), np.float32))
    idx = _rows(mesh, np.zeros((n_dev, 4, 8), np.int32))
    w = _rows(mesh, np.ones((n_dev, 4, 8), np.float32))
    return table, idx, w


def _entry_lhs_einsum():
    import functools

    import jax

    from ..models.als import _lhs_fn

    mesh = _training_mesh()
    table, idx, w = _lhs_inputs(mesh)
    fn = jax.jit(functools.partial(_lhs_fn, gram="einsum", bf16=False,
                                   mesh=None))
    return fn.lower(table, idx, w, w).compile()


def _entry_lhs_fused():
    import functools

    import jax

    from ..models.als import _lhs_fn

    mesh = _training_mesh()
    table, idx, w = _lhs_inputs(mesh)
    fn = jax.jit(functools.partial(_lhs_fn, gram="fused", bf16=False,
                                   mesh=mesh))
    return fn.lower(table, idx, w, w).compile()


def _entry_train_update_block():
    import functools

    import jax
    import numpy as np

    from ..models.als import _update_block

    mesh = _training_mesh()
    table, idx, w = _lhs_inputs(mesh)
    counts = _rows(mesh, np.ones((mesh.devices.size, 4), np.float32))
    G = np.zeros((16, 16), np.float32)
    fn = jax.jit(functools.partial(
        _update_block.__wrapped__, implicit=True, scale_reg=True,
        bf16=False, gram="einsum", mesh=None))
    return fn.lower(table, G, idx, w, counts, 0.1, 40.0).compile()


def _entry_seqrec_train_step():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models.seqrec import SeqRecParams, _init_weights, _train_step

    mesh = _training_mesh()
    p = SeqRecParams(dim=16, heads=2, max_len=8, n_negatives=4,
                     batch_size=8)
    w = _init_weights(jax.random.key(0), 32, p)
    rep = NamedSharding(mesh, P())
    w = jax.device_put(w, rep)
    m = jax.device_put({k: jnp.zeros_like(v) for k, v in w.items()}, rep)
    v = jax.device_put({k: jnp.zeros_like(v) for k, v in w.items()}, rep)
    seq = _rows(mesh, np.zeros((mesh.devices.size, 8), np.int32))
    return _train_step.lower(w, m, v, jnp.zeros((), jnp.int32), seq,
                             jax.random.key(1), p, 32).compile()


def _entry_sharded_topk():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.collectives import sharded_top_k
    from ..parallel.mesh import make_mesh

    mesh = make_mesh(data=2, model=4)
    scores = jax.device_put(
        np.ones((4, 64), np.float32),
        NamedSharding(mesh, P(None, "model")))
    fn = jax.jit(lambda s: sharded_top_k(s, 8, mesh, axis="model"))
    return fn.lower(scores).compile()


#: name → (builder, one-line description); ordered — the manifest and
#: the CI artifact list entries in this order
ENTRY_POINTS: Dict[str, Tuple[Callable[[], object], str]] = {
    "gramian_allreduce": (
        _entry_gramian_allreduce,
        "explicit per-shard Gramian partial + ICI psum"),
    "gather_rows": (
        _entry_gather_rows,
        "cross-shard user-row fetch (GSPMD-derived collective)"),
    "sharded_rank": (
        _entry_sharded_rank,
        "per-shard top-k + candidate all-gather (einsum ranker)"),
    "lhs_einsum": (
        _entry_lhs_einsum,
        "_lhs_fn normal-equation build under GSPMD row sharding"),
    "lhs_fused": (
        _entry_lhs_fused,
        "_lhs_fn through the shard_map'd fused kernel "
        "(replicated-table boundary)"),
    "train_update_block": (
        _entry_train_update_block,
        "one ALS training block (gather+Gramian+solve) under GSPMD"),
    "seqrec_train_step": (
        _entry_seqrec_train_step,
        "sequential-model Adam step: data-parallel gradient "
        "all-reduces"),
    "sharded_topk": (
        _entry_sharded_topk,
        "two-phase global top-k over the (data=2, model=4) mesh"),
}


def run_audit(names: Optional[Sequence[str]] = None) -> dict:
    """Compile + parse every (selected) entry point; returns the
    manifest dict. Raises :class:`AuditError` when the process does
    not see the forced device count (the collective structure depends
    on it — a 1-device audit would record an empty manifest)."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < AUDIT_DEVICE_COUNT:
        raise AuditError(
            f"audit-hlo needs {AUDIT_DEVICE_COUNT} devices, found "
            f"{n_dev}; run in a fresh process (the CLI forces "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{AUDIT_DEVICE_COUNT} before importing jax)")
    unknown = set(names or ()) - set(ENTRY_POINTS)
    if unknown:
        raise AuditError(f"unknown entry point(s): {sorted(unknown)} "
                         f"(have: {sorted(ENTRY_POINTS)})")
    entries: Dict[str, dict] = {}
    for name, (builder, _desc) in ENTRY_POINTS.items():
        if names and name not in names:
            continue
        entries[name] = audit_compiled(builder())
    return {"version": MANIFEST_VERSION,
            "devices": AUDIT_DEVICE_COUNT,
            "entries": entries}


# ---------------------------------------------------------------------------
# manifest I/O + ratchet diff
# ---------------------------------------------------------------------------

def load_manifest(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) \
            or doc.get("version") != MANIFEST_VERSION:
        raise ValueError(f"{path}: not an audit-hlo manifest "
                         f"(expected version {MANIFEST_VERSION})")
    return doc


def write_manifest(path: str, manifest: dict,
                   cap: Optional[dict] = None) -> None:
    """Persist the manifest. With ``cap`` (the previously committed
    baseline) the write RATCHETS: entries/ops the old baseline never
    held are dropped, counts and temp bytes clamp to the recorded
    values — the file only shrinks (use :func:`diff_manifests` first
    to fail on unabsorbed growth; ``--baseline-grow`` writes as-is)."""
    doc = manifest
    if cap is not None:
        old = cap.get("entries", {})
        entries = {}
        for name, rec in manifest.get("entries", {}).items():
            if name not in old:
                continue
            orec = old[name]
            colls = {op: min(c, orec.get("collectives", {})[op])
                     for op, c in rec.get("collectives", {}).items()
                     if op in orec.get("collectives", {})}
            entries[name] = {
                "collectives": colls,
                "collective_shapes": {
                    op: rec.get("collective_shapes", {}).get(op, [])
                    for op in colls},
                "temp_bytes": min(rec.get("temp_bytes", 0),
                                  orec.get("temp_bytes", 0)),
            }
        doc = {"version": MANIFEST_VERSION,
               "devices": manifest.get("devices", AUDIT_DEVICE_COUNT),
               "entries": entries}
    from .baseline import atomic_write_text

    atomic_write_text(
        path, json.dumps(doc, indent=2, sort_keys=True) + "\n")


def diff_manifests(current: dict, baseline: dict
                   ) -> Tuple[List[str], List[str]]:
    """(violations, shrinkable) between a fresh audit and the golden
    baseline. Violations name the entry point, the op, and its result
    shape — the line an operator greps for."""
    violations: List[str] = []
    shrinkable: List[str] = []
    if current.get("devices") != baseline.get("devices"):
        violations.append(
            f"device count {current.get('devices')} != baseline "
            f"{baseline.get('devices')} (the collective structure is "
            f"topology-dependent; audit on the forced mesh)")
    cur = current.get("entries", {})
    base = baseline.get("entries", {})
    for name, rec in cur.items():
        brec = base.get(name)
        if brec is None:
            violations.append(
                f"{name}: entry point not in the baseline — record it "
                f"deliberately with --write-baseline --baseline-grow")
            continue
        bcolls = brec.get("collectives", {})
        for op, count in sorted(rec.get("collectives", {}).items()):
            b = bcolls.get(op, 0)
            shapes = rec.get("collective_shapes", {}).get(op, [])
            if count > b:
                violations.append(
                    f"{name}: {op} x{count} (baseline {b}) — new "
                    f"collective in the compiled program"
                    + (f"; shapes {shapes}" if shapes else "")
                    + ". A spec change made XLA insert a reshard: "
                    f"diff the specs feeding this entry point, or "
                    f"record deliberately with --baseline-grow")
            elif count < b:
                shrinkable.append(f"{name}: {op} recorded {b}, "
                                  f"found {count}")
        for op, b in sorted(bcolls.items()):
            if op not in rec.get("collectives", {}):
                shrinkable.append(f"{name}: {op} recorded {b}, "
                                  f"found 0")
        btemp = brec.get("temp_bytes", 0)
        temp = rec.get("temp_bytes", 0)
        if temp > btemp * TEMP_GROWTH_RATIO + TEMP_SLACK_BYTES:
            violations.append(
                f"{name}: temp allocation {temp}B vs baseline "
                f"{btemp}B (> x{TEMP_GROWTH_RATIO} + "
                f"{TEMP_SLACK_BYTES}B slack) — a spec change is "
                f"materializing a gathered buffer; check for an "
                f"implicit reshard, or --baseline-grow")
        elif temp < btemp / TEMP_GROWTH_RATIO - TEMP_SLACK_BYTES:
            shrinkable.append(f"{name}: temp_bytes recorded {btemp}, "
                              f"found {temp}")
    for name in base:
        if name not in cur:
            shrinkable.append(f"{name}: entry point no longer audited")
    return violations, shrinkable


def format_text(manifest: dict) -> str:
    lines: List[str] = []
    for name, rec in manifest.get("entries", {}).items():
        colls = rec.get("collectives", {})
        summary = ", ".join(f"{op} x{c}"
                            for op, c in sorted(colls.items())) \
            or "no collectives"
        lines.append(f"{name}: {summary}; "
                     f"temp {rec.get('temp_bytes', 0)}B")
        for op, shapes in sorted(
                rec.get("collective_shapes", {}).items()):
            lines.append(f"  {op}: {' '.join(shapes)}")
    return "\n".join(lines)


__all__ = (
    "AUDIT_DEVICE_COUNT",
    "AuditError",
    "DEFAULT_BASELINE",
    "ENTRY_POINTS",
    "audit_compiled",
    "diff_manifests",
    "ensure_cpu_devices",
    "format_text",
    "load_manifest",
    "parse_collectives",
    "run_audit",
    "write_manifest",
)
