"""The Pallas kernel-safety rule family behind ``ptpu check``.

PR 7 put hand-written Pallas kernels on the training hot path
(``ops/fused_gram.py``; ``ops/solve.py`` and ``ops/gram.py`` were
already there), and the failure classes that silently corrupt or OOM a
kernel are invisible to both ``ruff`` and the JAX rules: a VMEM
working set that only blows up at rank 128, a DMA started and never
waited (reads garbage from the in-flight buffer), an accumulator that
quietly rounds in bf16, a ``pallas_call`` that hard-fails on every
backend whose Mosaic can't lower it. ALX (arXiv 2112.02194) and Tensor
Casting (arXiv 2010.13100) both live or die on exactly these
invariants — on-chip memory layout and mixed-precision accumulation —
so the checker enforces them before the hardware does. Four rules,
pure AST like everything else in this package:

- ``vmem-overbudget`` — statically evaluate every ``pallas_call``'s
  VMEM working set (BlockSpec tiles — doubled when a grid pipelines
  them — plus VMEM scratch) against the ~16 MiB/core budget, across
  the rank grid declared by ``ops/gram_autotune_defaults.json`` and
  the module's own chunk constants: the static sibling of
  ``fused_gram.fused_vmem_bytes``. Symbolic dims resolve through
  local assignments, module constants, and parameter defaults; rank-
  like / chunk-like / history-like free names bind to the scenario
  grid; enclosing ``if``/``assert`` bounds (``if rp <= _RP_SCRATCH:``)
  make infeasible scenarios skip instead of lying. Dims that still
  can't be evaluated drop out of the sum (under-counting never
  over-reports).
- ``dma-unwaited`` — a ``make_async_copy`` ``.start()`` with no
  matching ``.wait()`` anywhere in the kernel (matched by copy
  variable or by semaphore expression, so the split
  issue-in-one-helper / drain-in-another pipeline idiom of
  ``fused_gram`` matches), or the same semaphore slot restarted
  within a straight-line block before its wait.
- ``low-precision-accumulator`` — ``+=`` / read-modify-write / dot
  results accumulated into bf16/f16 VMEM scratch refs. Accumulators
  must be f32 (``preferred_element_type`` upcasting exists precisely
  so the wire can be bf16 while the sum is not).
- ``missing-interpret-fallback`` — a ``pallas_call`` with no
  ``interpret=`` escape hatch: every kernel must be routable through
  a support-gated dispatcher (``fused_gram_dispatch`` is the model)
  so CPU hosts and Mosaic versions that can't lower it degrade
  instead of raising mid-train.

All four honor ``# ptpu: allow[rule] — justification`` pragmas and
flow through ``--format sarif`` and the baseline gate like every other
rule. See docs/static-analysis.md (rules) and docs/kernels.md (the
budget math the first rule encodes).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import CheckContext, Finding, ModuleInfo

#: per-core VMEM (the guide's ~16 MB; Mosaic's scoped limit)
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

#: history-axis bound for L-like free dims: the bucketed ALS layouts
#: reach L=8192 (docs/kernels.md) — a kernel whose working set scales
#: with L must survive the largest bucket
MAX_HISTORY_L = 8192

#: scenario fallback bindings for names that never resolve statically
_RANK_NAME = re.compile(r"^(r|rank)$")
_CHUNK_NAME = re.compile(r"^(chunk|chunks|lc)$", re.IGNORECASE)
_HIST_NAME = re.compile(r"^(l|lp|seq_len|slen)$", re.IGNORECASE)

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "bool_": 1,
}

_LOW_PRECISION = {"bfloat16", "float16"}

_DOT_CALLS = {"jax.lax.dot_general", "jax.lax.dot", "jax.numpy.dot",
              "jax.numpy.matmul", "jax.numpy.einsum"}


def _uses_pallas(mod: ModuleInfo) -> bool:
    return any(v.startswith("jax.experimental.pallas")
               for v in mod.aliases.values())


def _dtype_bytes(mod: ModuleInfo, node: Optional[ast.AST]
                 ) -> Optional[int]:
    """Bytes/element for a dtype expression, or None when unknown
    (callers treat unknown as 4 — worst-case f32 wire)."""
    if node is None:
        return None
    name = mod.resolve(node)
    if name:
        return _DTYPE_BYTES.get(name.rsplit(".", 1)[-1])
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_BYTES.get(node.value)
    return None


def _dtype_name(mod: ModuleInfo, node: Optional[ast.AST]
                ) -> Optional[str]:
    if node is None:
        return None
    name = mod.resolve(node)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# symbolic integer evaluation over one function scope
# ---------------------------------------------------------------------------

class _Scope:
    """Evaluation environment for one function: module-level int
    constants, the function's simple local assignments, parameter
    defaults, and the per-scenario bindings for rank/chunk/history
    names that cannot resolve any other way."""

    def __init__(self, mod: ModuleInfo, fn: Optional[ast.AST]):
        self.mod = mod
        self.consts: Dict[str, ast.AST] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.consts[node.targets[0].id] = node.value
        self.assigns: Dict[str, ast.AST] = {}
        if fn is not None:
            a = fn.args
            defaults = list(a.defaults)
            pos = list(a.posonlyargs) + list(a.args)
            for p, d in zip(pos[len(pos) - len(defaults):], defaults):
                self.assigns[p.arg] = d
            for p, d in zip(a.kwonlyargs, a.kw_defaults):
                if d is not None:
                    self.assigns[p.arg] = d
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    self.assigns[node.targets[0].id] = node.value
        self.scenario: Dict[str, int] = {}

    def bind(self, rank: int, chunk: int) -> None:
        self.scenario = {"__rank__": rank, "__chunk__": chunk}

    def _fallback(self, name: str) -> Optional[int]:
        if _RANK_NAME.match(name):
            return self.scenario.get("__rank__")
        if _CHUNK_NAME.match(name):
            return self.scenario.get("__chunk__")
        if _HIST_NAME.match(name):
            return MAX_HISTORY_L
        return None

    def eval(self, node: Optional[ast.AST],
             depth: int = 0) -> Optional[int]:
        """Best-effort integer value of an expression; None when it
        cannot be pinned down (the caller drops the term)."""
        if node is None or depth > 24:
            return None
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, int) \
                and not isinstance(node.value, bool) else None
        if isinstance(node, ast.Name):
            tgt = self.assigns.get(node.id)
            if tgt is not None and tgt is not node:
                v = self.eval(tgt, depth + 1)
                if v is not None:
                    return v
            tgt = self.consts.get(node.id)
            if tgt is not None:
                v = self.eval(tgt, depth + 1)
                if v is not None:
                    return v
            return self._fallback(node.id)
        if isinstance(node, ast.UnaryOp) \
                and isinstance(node.op, ast.USub):
            v = self.eval(node.operand, depth + 1)
            return -v if v is not None else None
        if isinstance(node, ast.BinOp):
            a = self.eval(node.left, depth + 1)
            b = self.eval(node.right, depth + 1)
            if a is None or b is None:
                return None
            try:
                if isinstance(node.op, ast.Add):
                    return a + b
                if isinstance(node.op, ast.Sub):
                    return a - b
                if isinstance(node.op, ast.Mult):
                    return a * b
                if isinstance(node.op, ast.FloorDiv):
                    return a // b
                if isinstance(node.op, ast.Mod):
                    return a % b
                if isinstance(node.op, ast.Div):
                    return a // b if a % b == 0 else None
            except (ZeroDivisionError, ValueError):
                return None
            return None
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Name) \
                and node.func.id in ("min", "max") and node.args \
                and not node.keywords:
            vals = [self.eval(a, depth + 1) for a in node.args]
            if any(v is None for v in vals):
                return None
            return min(vals) if node.func.id == "min" else max(vals)
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or):
            # `chunk or _L_CHUNK` with chunk defaulting to None — take
            # the first operand that pins down
            for operand in node.values:
                v = self.eval(operand, depth + 1)
                if v is not None:
                    return v
            return None
        return None

    def feasible(self, constraints: Sequence[ast.AST]) -> bool:
        """True unless some enclosing ``if``/``assert`` comparison
        provably fails under the current scenario (unknowns pass)."""
        for test in constraints:
            if not isinstance(test, ast.Compare) \
                    or len(test.ops) != 1:
                continue
            a = self.eval(test.left)
            b = self.eval(test.comparators[0])
            if a is None or b is None:
                continue
            op = test.ops[0]
            ok = {ast.Lt: a < b, ast.LtE: a <= b, ast.Gt: a > b,
                  ast.GtE: a >= b, ast.Eq: a == b,
                  ast.NotEq: a != b}.get(type(op), True)
            if not ok:
                return False
        return True


# ---------------------------------------------------------------------------
# shared pallas_call site discovery
# ---------------------------------------------------------------------------

class _PallasSite:
    def __init__(self, call: ast.Call, fn: Optional[ast.AST],
                 constraints: Tuple[ast.AST, ...]):
        self.call = call
        self.fn = fn
        self.constraints = constraints
        self.kwargs = {kw.arg: kw.value for kw in call.keywords
                       if kw.arg}


def _is_pallas_call(mod: ModuleInfo, node: ast.Call) -> bool:
    resolved = mod.resolve(node.func)
    if resolved and (resolved.endswith(".pallas_call")
                     or resolved == "pallas_call"):
        return True
    return isinstance(node.func, ast.Attribute) \
        and node.func.attr == "pallas_call"


def _pallas_sites(mod: ModuleInfo) -> List[_PallasSite]:
    """Every ``pallas_call`` with its enclosing function and the
    comparison constraints in force there (enclosing ``if`` tests on
    the taken branch; the function's ``assert``s)."""
    sites: List[_PallasSite] = []

    def visit(node: ast.AST, fn: Optional[ast.AST],
              constraints: Tuple[ast.AST, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            asserts = tuple(
                n.test for n in ast.walk(node)
                if isinstance(n, ast.Assert))
            for child in ast.iter_child_nodes(node):
                visit(child, node, asserts)
            return
        if isinstance(node, ast.If):
            for child in node.body:
                visit(child, fn, constraints + (node.test,))
            for child in node.orelse:
                visit(child, fn, constraints)
            visit(node.test, fn, constraints)
            return
        if isinstance(node, ast.Call) and _is_pallas_call(mod, node):
            sites.append(_PallasSite(node, fn, constraints))
        for child in ast.iter_child_nodes(node):
            visit(child, fn, constraints)

    visit(mod.tree, None, ())
    return sites


def _resolve_local(scope: _Scope, node: ast.AST,
                   depth: int = 0) -> ast.AST:
    """Follow simple Name → local-assignment chains (``mat_spec =
    pl.BlockSpec(…)`` then ``in_specs=[mat_spec]``)."""
    while isinstance(node, ast.Name) and depth < 8:
        tgt = scope.assigns.get(node.id) or scope.consts.get(node.id)
        if tgt is None or tgt is node:
            break
        node = tgt
        depth += 1
    return node


def _spec_list(scope: _Scope, node: Optional[ast.AST]
               ) -> List[ast.AST]:
    if node is None:
        return []
    node = _resolve_local(scope, node)
    if isinstance(node, (ast.List, ast.Tuple)):
        return [_resolve_local(scope, e) for e in node.elts]
    return [node]


def _memory_space_of(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "memory_space":
            name = mod.resolve(kw.value) or ""
            return name.rsplit(".", 1)[-1]
    return None


# ---------------------------------------------------------------------------
# rule: vmem-overbudget
# ---------------------------------------------------------------------------

_ranks_cache: Dict[str, Tuple[int, ...]] = {}


def autotune_ranks(mod_path: str) -> Tuple[int, ...]:
    """The rank grid ``vmem-overbudget`` evaluates: the ``r<N>``
    buckets declared by ``gram_autotune_defaults.json`` next to the
    scanned module (falling back to the packaged table), so the
    checker and the autotuner always argue over the same ranks."""
    for candidate in (
            os.path.join(os.path.dirname(mod_path) or ".",
                         "gram_autotune_defaults.json"),
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "ops",
                "gram_autotune_defaults.json")):
        cached = _ranks_cache.get(candidate)
        if cached is not None:
            return cached
        try:
            with open(candidate, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        ranks = sorted({int(m.group(1))
                        for key in doc
                        for m in [re.search(r"\|r(\d+)\|", key)]
                        if m})
        out = tuple(ranks) or (32, 64, 128)
        _ranks_cache[candidate] = out
        return out
    return (32, 64, 128)


def _module_chunks(scope: _Scope) -> Tuple[int, ...]:
    """Chunk-size scenario values: every module constant whose name
    contains CHUNK (``_L_CHUNK = 512``), else the fused-gram default."""
    out: Set[int] = set()
    for name, value in scope.consts.items():
        if "CHUNK" in name.upper():
            v = scope.eval(value)
            if v is not None and v > 0:
                out.add(v)
    return tuple(sorted(out)) or (512,)


def _block_bytes(mod: ModuleInfo, scope: _Scope, spec: ast.AST,
                 dtype_bytes: int, pipelined: bool
                 ) -> Tuple[Optional[int], Optional[str]]:
    """(bytes, label) for one BlockSpec — None bytes when the spec
    carries no static shape (HBM/ANY residents, whole-operand blocks)
    or a dim can't be evaluated."""
    if not (isinstance(spec, ast.Call)
            and (mod.resolve(spec.func) or "").endswith("BlockSpec")):
        return None, None
    space = _memory_space_of(mod, spec)
    if space in ("ANY", "HBM", "SMEM"):
        return None, None       # not VMEM-resident (SMEM is scalar mem)
    shape = spec.args[0] if spec.args else None
    for kw in spec.keywords:
        if kw.arg == "block_shape":
            shape = kw.value
    if not isinstance(shape, (ast.Tuple, ast.List)):
        return None, "?"        # whole-operand block: size unknown
    total = dtype_bytes
    dims: List[str] = []
    for e in shape.elts:
        v = scope.eval(e)
        if v is None:
            return None, "?"
        total *= v
        dims.append(str(v))
    if pipelined:
        total *= 2              # Mosaic double-buffers gridded blocks
    return total, "×".join(dims)


def rule_vmem_overbudget(mod: ModuleInfo,
                         ctx: CheckContext) -> List[Finding]:
    if not _uses_pallas(mod):
        return []
    findings: List[Finding] = []
    ranks = autotune_ranks(mod.path)
    for site in _pallas_sites(mod):
        scope = _Scope(mod, site.fn)
        chunks = _module_chunks(scope)
        pipelined = "grid" in site.kwargs
        worst: Optional[Tuple[int, int, int, List[str]]] = None
        for rank in ranks:
            for chunk in chunks:
                scope.bind(rank, chunk)
                if not scope.feasible(site.constraints):
                    continue
                total = 0
                parts: List[str] = []
                skipped = 0
                out_shapes = _spec_list(
                    scope, site.kwargs.get("out_shape"))
                for kind in ("in_specs", "out_specs"):
                    specs = _spec_list(scope, site.kwargs.get(kind))
                    for i, spec in enumerate(specs):
                        dt = 4
                        if kind == "out_specs" and i < len(out_shapes):
                            os_call = out_shapes[i]
                            if isinstance(os_call, ast.Call) \
                                    and len(os_call.args) > 1:
                                dt = _dtype_bytes(
                                    mod, os_call.args[1]) or 4
                        nbytes, label = _block_bytes(
                            mod, scope, spec, dt, pipelined)
                        if nbytes is None:
                            skipped += label is not None
                            continue
                        total += nbytes
                        parts.append(
                            f"{kind[:-1]}[{i}] {label}·{dt}B"
                            f"{'·2buf' if pipelined else ''}")
                for i, sc in enumerate(_spec_list(
                        scope, site.kwargs.get("scratch_shapes"))):
                    if not isinstance(sc, ast.Call):
                        continue
                    sname = (mod.resolve(sc.func) or "")
                    if not sname.endswith(".VMEM"):
                        continue   # SMEM / semaphores are not VMEM
                    shape = sc.args[0] if sc.args else None
                    dt = _dtype_bytes(
                        mod, sc.args[1] if len(sc.args) > 1
                        else None) or 4
                    if not isinstance(shape, (ast.Tuple, ast.List)):
                        skipped += 1
                        continue
                    n = dt
                    dims = []
                    bad = False
                    for e in shape.elts:
                        v = scope.eval(e)
                        if v is None:
                            bad = True
                            break
                        n *= v
                        dims.append(str(v))
                    if bad:
                        skipped += 1
                        continue
                    total += n
                    parts.append(f"scratch[{i}] {'×'.join(dims)}·{dt}B")
                if total > VMEM_BUDGET_BYTES \
                        and (worst is None or total > worst[0]):
                    worst = (total, rank, chunk, parts)
        if worst is not None:
            total, rank, chunk, parts = worst
            findings.append(Finding(
                "vmem-overbudget", mod.path, site.call.lineno,
                site.call.col_offset,
                f"pallas_call VMEM working set ≈ "
                f"{total / (1 << 20):.1f} MiB at rank {rank} / chunk "
                f"{chunk} exceeds the ~16 MiB/core budget "
                f"({' + '.join(parts)}); shrink the block/scratch "
                f"tiles, stream via ANY+DMA like fused_gram, or "
                f"pragma with the measured budget argument "
                f"(docs/kernels.md)"))
    return findings


# ---------------------------------------------------------------------------
# rule: dma-unwaited
# ---------------------------------------------------------------------------

def _is_make_async_copy(mod: ModuleInfo, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.resolve(node.func) or ""
    return resolved.endswith("make_async_copy") \
        or (isinstance(node.func, ast.Attribute)
            and node.func.attr == "make_async_copy")


def _sem_key(copy_call: ast.Call) -> Optional[str]:
    sem = copy_call.args[2] if len(copy_call.args) > 2 else None
    for kw in copy_call.keywords:
        if kw.arg in ("sem", "sems", "semaphore"):
            sem = kw.value
    if sem is None:
        return None
    try:
        return ast.unparse(sem).replace(" ", "")
    except Exception:  # noqa: BLE001 — unparse is best-effort
        return None


class _DmaEvent:
    __slots__ = ("kind", "key", "var", "node")

    def __init__(self, kind: str, key: Optional[str],
                 var: Optional[str], node: ast.AST):
        self.kind = kind     # "start" | "wait"
        self.key = key       # normalized semaphore expression
        self.var = var       # copy variable, for var.start()/var.wait()
        self.node = node


def _dma_events(mod: ModuleInfo, fn: ast.AST) -> List[_DmaEvent]:
    """start/wait events anywhere in ``fn`` (nested helper defs
    included — the issue-in-one-helper/drain-in-another pipeline split
    is the idiom the matching must span)."""
    copies: Dict[str, Optional[str]] = {}   # var → sem key
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_make_async_copy(mod, node.value):
            copies[node.targets[0].id] = _sem_key(node.value)
    events: List[_DmaEvent] = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("start", "wait")):
            continue
        recv = node.func.value
        if _is_make_async_copy(mod, recv):
            events.append(_DmaEvent(node.func.attr, _sem_key(recv),
                                    None, node))
        elif isinstance(recv, ast.Name) and recv.id in copies:
            events.append(_DmaEvent(node.func.attr, copies[recv.id],
                                    recv.id, node))
    return events


def rule_dma_unwaited(mod: ModuleInfo,
                      ctx: CheckContext) -> List[Finding]:
    if not _uses_pallas(mod):
        return []
    findings: List[Finding] = []
    for fn in mod.tree.body:
        stack = [fn]
        tops: List[ast.AST] = []
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                tops.append(n)
            elif isinstance(n, ast.ClassDef):
                stack.extend(n.body)
        for top in tops:
            events = _dma_events(mod, top)
            if not events:
                continue
            waited_keys = {e.key for e in events
                           if e.kind == "wait" and e.key}
            waited_vars = {e.var for e in events
                           if e.kind == "wait" and e.var}
            for e in events:
                if e.kind != "start":
                    continue
                if (e.var and e.var in waited_vars) \
                        or (e.key and e.key in waited_keys):
                    continue
                what = f"`{e.var}.start()`" if e.var else \
                    "`make_async_copy(…).start()`"
                findings.append(Finding(
                    "dma-unwaited", mod.path, e.node.lineno,
                    e.node.col_offset,
                    f"{what} has no matching .wait() in "
                    f"`{top.name}` (matched by copy variable and by "
                    f"semaphore slot); an unwaited DMA races the "
                    f"compute reading its destination buffer — pair "
                    f"every start with a wait before the data is "
                    f"consumed"))
            # same-slot restart before its wait, per straight-line
            # statement block: only simple statements participate —
            # events under a nested compound (loop/branch/def) have
            # ordering the block can't see statically, and the
            # double-buffer slot rotation idiom lives exactly there
            for node in ast.walk(top):
                bodies = []
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.With,
                                     ast.If, ast.For, ast.While)):
                    bodies = [node.body, getattr(node, "orelse", [])]
                for body in bodies:
                    started: Set[str] = set()
                    for stmt in body:
                        if not isinstance(stmt, (ast.Expr, ast.Assign,
                                                 ast.AugAssign)):
                            started.clear()
                            continue
                        for e in events:
                            if not (stmt.lineno <= e.node.lineno
                                    <= (stmt.end_lineno
                                        or stmt.lineno)) \
                                    or e.key is None:
                                continue
                            if e.kind == "wait":
                                started.discard(e.key)
                            elif e.key in started:
                                findings.append(Finding(
                                    "dma-unwaited", mod.path,
                                    e.node.lineno, e.node.col_offset,
                                    f"semaphore slot `{e.key}` "
                                    f"restarted before its wait in "
                                    f"`{top.name}`; the second DMA "
                                    f"overwrites the in-flight "
                                    f"buffer — wait (or rotate "
                                    f"slots) first"))
                            else:
                                started.add(e.key)
    return findings


# ---------------------------------------------------------------------------
# rule: low-precision-accumulator
# ---------------------------------------------------------------------------

def _kernel_fn_and_bound(mod: ModuleInfo, scope: _Scope,
                         site: _PallasSite
                         ) -> Tuple[Optional[ast.AST], int]:
    """The kernel FunctionDef a pallas_call dispatches to, plus the
    number of leading params pre-bound by functools.partial."""
    if not site.call.args:
        return None, 0
    target = _resolve_local(scope, site.call.args[0])
    bound = 0
    if isinstance(target, ast.Call) \
            and (mod.resolve(target.func) or "").endswith("partial") \
            and target.args:
        bound = len(target.args) - 1
        target = _resolve_local(scope, target.args[0])
    if isinstance(target, ast.Name):
        target = _resolve_local(scope, target)
    if isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return target, bound
    if isinstance(target, ast.Name):
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node.name == target.id:
                return node, bound
    return None, bound


def _function_by_name(mod: ModuleInfo, name: str
                      ) -> Optional[ast.AST]:
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def rule_low_precision_accumulator(mod: ModuleInfo,
                                   ctx: CheckContext) -> List[Finding]:
    if not _uses_pallas(mod):
        return []
    findings: List[Finding] = []
    flagged: Set[int] = set()
    for site in _pallas_sites(mod):
        scope = _Scope(mod, site.fn)
        kernel, bound = _kernel_fn_and_bound(mod, scope, site)
        if isinstance(kernel, ast.Name):
            kernel = _function_by_name(mod, kernel.id)
        if kernel is None:
            continue
        in_specs = _spec_list(scope, site.kwargs.get("in_specs"))
        out_specs = _spec_list(scope, site.kwargs.get("out_specs"))
        scratch = _spec_list(scope, site.kwargs.get("scratch_shapes"))
        a = kernel.args
        params = [p.arg for p in (*a.posonlyargs, *a.args)]
        expect = bound + len(in_specs) + len(out_specs) + len(scratch)
        if not scratch or len(params) != expect:
            continue    # can't map refs to scratch slots — stay quiet
        low: Dict[str, str] = {}
        base = bound + len(in_specs) + len(out_specs)
        for i, sc in enumerate(scratch):
            if not (isinstance(sc, ast.Call)
                    and (mod.resolve(sc.func) or "")
                    .endswith(".VMEM")):
                continue
            dt = _dtype_name(mod, sc.args[1]
                             if len(sc.args) > 1 else None)
            if dt in _LOW_PRECISION:
                low[params[base + i]] = dt
        if not low:
            continue
        for node in ast.walk(kernel):
            tgt = None
            accum = False
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Subscript) \
                    and isinstance(node.target.value, ast.Name):
                tgt = node.target.value.id
                accum = True
                rhs = node.value
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript) \
                    and isinstance(node.targets[0].value, ast.Name):
                tgt = node.targets[0].value.id
                rhs = node.value
                accum = any(
                    isinstance(n, ast.Name) and n.id == tgt
                    for n in ast.walk(rhs)) or any(
                    isinstance(n, ast.Call)
                    and (mod.resolve(n.func) or "") in _DOT_CALLS
                    for n in ast.walk(rhs))
            if tgt in low and accum and id(node) not in flagged:
                flagged.add(id(node))
                findings.append(Finding(
                    "low-precision-accumulator", mod.path,
                    node.lineno, node.col_offset,
                    f"accumulation into {low[tgt]} scratch ref "
                    f"`{tgt}` — every partial sum rounds to "
                    f"{low[tgt]} and the Gramian drifts; declare the "
                    f"accumulator f32 (upcast after the wire, "
                    f"contract with preferred_element_type=f32, like "
                    f"ops/fused_gram.py)"))
    return findings


# ---------------------------------------------------------------------------
# rule: missing-interpret-fallback
# ---------------------------------------------------------------------------

def rule_missing_interpret_fallback(mod: ModuleInfo,
                                    ctx: CheckContext
                                    ) -> List[Finding]:
    if not _uses_pallas(mod):
        return []
    findings: List[Finding] = []
    for site in _pallas_sites(mod):
        interp = site.kwargs.get("interpret")
        hardwired = interp is None or (
            isinstance(interp, ast.Constant)
            and interp.value is False)
        if hardwired:
            findings.append(Finding(
                "missing-interpret-fallback", mod.path,
                site.call.lineno, site.call.col_offset,
                "pallas_call is hard-wired to compiled mode; thread "
                "an interpret= parameter through and route callers "
                "via a support-gated dispatcher (the "
                "fused_gram_dispatch pattern: compiled kernel on "
                "TPU, interpret-mode elsewhere, XLA reference where "
                "Mosaic can't lower) so a CPU host or an older "
                "Mosaic degrades instead of raising mid-train"))
    return findings


# re-exported by .rules into the registry
__all__: Iterable[str] = (
    "VMEM_BUDGET_BYTES",
    "MAX_HISTORY_L",
    "autotune_ranks",
    "rule_dma_unwaited",
    "rule_low_precision_accumulator",
    "rule_missing_interpret_fallback",
    "rule_vmem_overbudget",
)
