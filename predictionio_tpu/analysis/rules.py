"""The JAX-specific rule catalogue behind ``ptpu check``.

This module holds the six JAX rules and assembles the full registry
(:data:`RULES`), which also includes the concurrency rule family from
:mod:`.concurrency` (``unguarded-shared-state``,
``lock-order-inversion``, ``blocking-under-lock``,
``callback-under-lock``) and the Pallas kernel-safety family from
:mod:`.kernels` (``vmem-overbudget``, ``dma-unwaited``,
``low-precision-accumulator``, ``missing-interpret-fallback``).

``host-sync-in-hot-path`` and ``materialized-gather`` are
project-scoped: beyond their direct per-module passes they consult the
interprocedural effect summaries (:class:`~.core.ProjectIndex`) so a
violation hidden inside a helper — any number of calls away — is
reported at the hot-path call site with the call chain in the message.

The JAX rules:

- ``host-sync-in-hot-path`` — device→host landings (``np.asarray``,
  ``.item()``, ``.tolist()``, ``jax.device_get``,
  ``.block_until_ready()``, ``float(jnp...)``) inside functions of the
  hot packages (``server/``, ``ops/``). Each is a synchronous transfer
  that stalls the dispatch pipeline; on the query path one stray sync
  caps throughput at the PCIe/tunnel round-trip rate.
- ``recompile-hazard`` — jit call sites that re-trace or re-compile
  silently: unhashable values passed for static args, jitted closures
  capturing ``jnp`` arrays built in an enclosing scope (the captured
  array is baked into the trace — a new array means a new program),
  and Python ``if``/``while`` on traced arguments (data-dependent
  control flow re-traces per branch or just fails late).
- ``missing-donation`` — ``x = step(x, …)`` update patterns calling a
  jitted function that does not donate the re-bound buffer: the old
  ``x`` stays alive across the step, doubling peak HBM for large
  factor/accumulator arrays.
- ``sharding-mismatch`` — ``PartitionSpec`` axis-name literals (wherever
  they appear: ``NamedSharding(mesh, P(...))`` annotations on entry
  points, ``shard_map`` in/out specs, jit ``out_shardings``) and axis
  names passed to ``lax`` collectives (``psum``/``all_gather``/
  ``ppermute``/``axis_index``/…) that no mesh builder in
  ``parallel/mesh.py`` declares; XLA only reports these at trace time
  on a real mesh, usually mid-deploy.
- ``materialized-gather`` — ``table[indices]`` advanced-indexing and
  ``jnp.take``/``jnp.take_along_axis`` gathers by a caller-supplied
  index array inside ``models/``/``ops/``/``server/`` functions
  (directly, or through a helper the traced index flows into): XLA
  materializes the gathered rows as an HBM temp sized by the index
  shape (the ``[B, L, r]`` ALS gather temp behind BENCH_r05's
  75%-HBM/0.6%-MFU roofline); fuse it (``gram_mode="fused"``), bound
  it, or pragma a size case.
- ``config-drift`` — ``jax.config.update`` outside
  ``utils/platform.py``: scattered config flips make process behavior
  depend on import order (exactly the class of bug
  ``force_cpu_if_requested`` exists to fix).

Every rule obeys the ``# ptpu: allow[rule] — justification`` pragma
(see :mod:`.core`). Rules are heuristics tuned for this codebase's
idioms; they prefer a pragma-able false positive on genuinely hot files
over silence.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    CheckContext,
    Finding,
    ModuleInfo,
    chain_related,
    chain_text,
    short_name,
)

RuleFn = Callable[[ModuleInfo, CheckContext], List[Finding]]


@dataclass(frozen=True)
class Rule:
    name: str
    description: str
    fn: RuleFn
    #: project-scoped rules run ONCE over the whole parsed module set
    #: (cross-file facts like the lock-order graph); their ``fn`` takes
    #: ``(mods: List[ModuleInfo], ctx)`` instead of one module
    project: bool = False


# ---------------------------------------------------------------------------
# rule 1: host-sync-in-hot-path
# ---------------------------------------------------------------------------

#: directories whose function bodies are considered hot (serving/query
#: and device-op code; module level runs once at import and is exempt)
HOT_DIR_PARTS = {"server", "ops"}

HOST_SYNC_CALLS = {
    "numpy.asarray": "np.asarray on a device value copies device→host "
                     "synchronously",
    "numpy.ascontiguousarray": "np.ascontiguousarray forces a host "
                               "copy (and a second one if the first "
                               "landing was non-contiguous)",
    "jax.device_get": "jax.device_get blocks until the transfer "
                      "completes",
}

HOST_SYNC_METHODS = {
    "item": ".item() synchronously pulls a scalar off the device",
    "tolist": ".tolist() copies the whole array to host Python objects",
    "block_until_ready": ".block_until_ready() stalls the caller on "
                         "device completion",
}


def _in_hot_path(path: str) -> bool:
    parts = path.split("/")
    return bool(set(parts[:-1]) & HOT_DIR_PARTS)


def host_sync_reason(mod: ModuleInfo, node: ast.Call) -> Optional[str]:
    """Why this call is a device→host sync, or None — the shared
    predicate behind the direct rule and the interprocedural effect
    summaries (:class:`~.core.ProjectIndex`)."""
    name = mod.resolve(node.func)
    if name in HOST_SYNC_CALLS:
        return HOST_SYNC_CALLS[name]
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in HOST_SYNC_METHODS \
            and not node.args and not node.keywords:
        return HOST_SYNC_METHODS[node.func.attr]
    if name in ("float", "int") and len(node.args) == 1 \
            and isinstance(node.args[0], ast.Call):
        inner = mod.resolve(node.args[0].func)
        if inner and inner.startswith("jax.numpy."):
            return (f"{name}() on a jnp result forces a blocking "
                    f"device→host scalar read")
    return None


def rule_host_sync(mods: Sequence[ModuleInfo],
                   ctx: CheckContext) -> List[Finding]:
    """Project-scoped: direct syncs inside hot-package functions, plus
    — through the call graph — hot-path calls into helpers (anywhere
    in the project) that transitively sync, reported at the hot call
    site with the chain down to the direct site. Helpers living in hot
    packages are skipped here: their bodies already get the direct
    finding."""
    findings: List[Finding] = []
    for mod in mods:
        if not _in_hot_path(mod.path):
            continue
        seen: Set[int] = set()
        funcs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        for fn in funcs:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                why = host_sync_reason(mod, node)
                if why is not None:
                    findings.append(Finding(
                        "host-sync-in-hot-path", mod.path, node.lineno,
                        node.col_offset,
                        f"{why} (in hot function `{fn.name}`); keep "
                        f"the hot path device-resident or pragma with "
                        f"justification"))
    proj = ctx.project
    if proj is None:
        return findings
    for fninfo in proj.functions.values():
        if not fninfo.hot(HOT_DIR_PARTS):
            continue
        for call in fninfo.calls:
            callee = proj.functions.get(call.callee or "")
            if callee is None or callee.hot(HOT_DIR_PARTS):
                continue
            if callee.effects["host_sync"] is None:
                continue
            hops = proj.chain(callee, "host_sync")
            if not hops:
                continue
            findings.append(Finding(
                "host-sync-in-hot-path", fninfo.mod.path, call.line,
                call.col,
                f"calling `{short_name(callee.qname)}` from hot "
                f"function `{short_name(fninfo.qname)}` transitively "
                f"syncs device→host: {chain_text(hops)}; keep the hot "
                f"path device-resident, or pragma the blessed helper "
                f"at its direct site",
                related=chain_related(hops)))
    return findings


# ---------------------------------------------------------------------------
# shared jit-site discovery (rules 2 and 3)
# ---------------------------------------------------------------------------

#: constructors whose results are device arrays — a jitted closure
#: capturing one re-traces whenever the captured array changes identity
ARRAY_BUILDERS_PREFIX = "jax.numpy."
ARRAY_BUILDERS_EXACT = {"jax.device_put"}


@dataclass
class JitSite:
    """One jit wrapping: decorator or ``jax.jit(fn, …)`` call."""

    fn: Optional[ast.AST]           # FunctionDef/Lambda being wrapped
    call: Optional[ast.Call]        # the jax.jit(...) call node, if any
    lineno: int
    col: int
    bound_name: Optional[str]       # name the jitted callable binds to
    static_names: Set[str]
    donate_nums: Set[int]
    donate_names: Set[str]
    scope_stack: Tuple[ast.AST, ...]  # enclosing function defs, outer→inner


def _param_names(fn: ast.AST) -> List[str]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
        return []
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _const_strs(node: ast.AST) -> List[str]:
    """String literals in a str/tuple/list constant expression."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _jit_kwargs(call: ast.Call) -> Dict[str, ast.AST]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _statics_and_donations(kwargs: Dict[str, ast.AST],
                           params: Sequence[str]
                           ) -> Tuple[Set[str], Set[int], Set[str]]:
    static_names: Set[str] = set()
    if "static_argnames" in kwargs:
        static_names |= set(_const_strs(kwargs["static_argnames"]))
    if "static_argnums" in kwargs:
        for i in _const_ints(kwargs["static_argnums"]):
            if 0 <= i < len(params):
                static_names.add(params[i])
    donate_nums = set(_const_ints(kwargs["donate_argnums"])) \
        if "donate_argnums" in kwargs else set()
    donate_names = set(_const_strs(kwargs["donate_argnames"])) \
        if "donate_argnames" in kwargs else set()
    return static_names, donate_nums, donate_names


class _JitCollector(ast.NodeVisitor):
    """Find every jit wrapping in a module, with its enclosing function
    scopes and the per-scope simple ``name = <expr>`` assignments (for
    the closure-capture check)."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.sites: List[JitSite] = []
        self.scope: List[ast.AST] = []
        #: id(scope fn) or None → {name: value expr}
        self.assigns: Dict[Optional[int], Dict[str, ast.AST]] = {None: {}}
        #: function defs by name, outermost first (jax.jit(Name) lookup)
        self.defs_by_name: Dict[str, ast.AST] = {}

    def _scope_key(self) -> Optional[int]:
        return id(self.scope[-1]) if self.scope else None

    def visit_FunctionDef(self, node):  # noqa: N802 — ast API
        self._handle_def(node)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802 — ast API
        self._handle_def(node)

    def _handle_def(self, node) -> None:
        self.defs_by_name.setdefault(node.name, node)
        params = _param_names(node)
        for dec in node.decorator_list:
            site = self._site_from_decorator(dec, node, params)
            if site is not None:
                self.sites.append(site)
        self.scope.append(node)
        self.assigns.setdefault(id(node), {})
        self.generic_visit(node)
        self.scope.pop()

    def _site_from_decorator(self, dec: ast.AST, node, params
                             ) -> Optional[JitSite]:
        name = self.mod.resolve(dec)
        if name == "jax.jit":
            return JitSite(node, None, node.lineno, node.col_offset,
                           node.name, set(), set(), set(),
                           tuple(self.scope))
        if isinstance(dec, ast.Call):
            callee = self.mod.resolve(dec.func)
            if callee == "jax.jit":
                s, dn, dnm = _statics_and_donations(_jit_kwargs(dec),
                                                    params)
                return JitSite(node, dec, node.lineno, node.col_offset,
                               node.name, s, dn, dnm, tuple(self.scope))
            if callee == "functools.partial" and dec.args \
                    and self.mod.resolve(dec.args[0]) == "jax.jit":
                s, dn, dnm = _statics_and_donations(_jit_kwargs(dec),
                                                    params)
                return JitSite(node, dec, node.lineno, node.col_offset,
                               node.name, s, dn, dnm, tuple(self.scope))
        return None

    def visit_Assign(self, node):  # noqa: N802 — ast API
        if len(node.targets) == 1 and isinstance(node.targets[0],
                                                 ast.Name):
            self.assigns[self._scope_key()][node.targets[0].id] = \
                node.value
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802 — ast API
        if self.mod.resolve(node.func) == "jax.jit" and node.args:
            wrapped = node.args[0]
            target: Optional[ast.AST] = None
            if isinstance(wrapped, ast.Lambda):
                target = wrapped
            elif isinstance(wrapped, ast.Name):
                target = self.defs_by_name.get(wrapped.id)
            elif isinstance(wrapped, ast.Attribute) \
                    and wrapped.attr == "__wrapped__" \
                    and isinstance(wrapped.value, ast.Name):
                # jax.jit(f.__wrapped__, ...) re-wraps a decorated def
                target = self.defs_by_name.get(wrapped.value.id)
            params = _param_names(target) if target is not None else []
            s, dn, dnm = _statics_and_donations(_jit_kwargs(node), params)
            bound = None
            site = JitSite(target, node, node.lineno, node.col_offset,
                           bound, s, dn, dnm, tuple(self.scope))
            self.sites.append(site)
        self.generic_visit(node)


def _collect_jit(mod: ModuleInfo) -> _JitCollector:
    collector = _JitCollector(mod)
    collector.visit(mod.tree)
    # bind `X = jax.jit(f, …)` sites to their assigned name so call
    # sites of X resolve to the wrapped function's params/donations
    for scope_assigns in collector.assigns.values():
        for name, value in scope_assigns.items():
            for site in collector.sites:
                if site.call is value:
                    site.bound_name = name
    return collector


def _free_loads(fn: ast.AST) -> Set[str]:
    """Names a function/lambda loads but neither binds as a param nor
    assigns locally — its closure candidates."""
    params = set(_param_names(fn))
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    local: Set[str] = set()
    loads: Set[str] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    local.add(node.id)
                elif isinstance(node.ctx, ast.Load):
                    loads.add(node.id)
    return loads - params - local


def rule_recompile_hazard(mod: ModuleInfo,
                          ctx: CheckContext) -> List[Finding]:
    collector = _collect_jit(mod)
    findings: List[Finding] = []

    # (a) unhashable values passed for declared static args
    statics_by_name: Dict[str, Set[str]] = {}
    for site in collector.sites:
        if site.bound_name and site.static_names:
            statics_by_name[site.bound_name] = site.static_names
    unhashable = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                  ast.DictComp, ast.SetComp)
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)):
            continue
        statics = statics_by_name.get(node.func.id)
        if not statics:
            continue
        for kw in node.keywords:
            if kw.arg in statics and isinstance(kw.value, unhashable):
                findings.append(Finding(
                    "recompile-hazard", mod.path, node.lineno,
                    node.col_offset,
                    f"static arg `{kw.arg}` of `{node.func.id}` gets an "
                    f"unhashable {type(kw.value).__name__.lower()}; "
                    f"jit static args must hash — pass a tuple or "
                    f"hashable config object"))

    # (b) jitted closures over enclosing-scope jnp arrays
    for site in collector.sites:
        if site.fn is None or not site.scope_stack:
            continue
        free = _free_loads(site.fn)
        for scope in reversed(site.scope_stack):
            scope_assigns = collector.assigns.get(id(scope), {})
            for name in sorted(free & set(scope_assigns)):
                value = scope_assigns[name]
                built = mod.resolve(value.func) \
                    if isinstance(value, ast.Call) else None
                if built and (built.startswith(ARRAY_BUILDERS_PREFIX)
                              or built in ARRAY_BUILDERS_EXACT):
                    findings.append(Finding(
                        "recompile-hazard", mod.path, site.lineno,
                        site.col,
                        f"jitted function closes over device array "
                        f"`{name}` (built by `{built}` in an enclosing "
                        f"scope); captured arrays are baked into the "
                        f"trace — a fresh array means a fresh compile. "
                        f"Pass it as an argument instead"))

    # (c) Python control flow on traced arguments inside jitted bodies
    flagged: Set[int] = set()
    for site in collector.sites:
        fn = site.fn
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        traced = set(_param_names(fn)) - site.static_names
        if not traced:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                continue
            if id(node) in flagged:
                continue
            test_loads: Dict[str, int] = {}
            for n in ast.walk(node.test):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                    test_loads[n.id] = test_loads.get(n.id, 0) + 1
            # `x is None` / `x is not None` resolves by pytree STRUCTURE
            # at trace time (None is a static empty pytree): a bounded
            # Optional specialization, not a value-dependent retrace.
            # Exempt names used ONLY that way in this test.
            structural: Dict[str, int] = {}
            for c in ast.walk(node.test):
                if (isinstance(c, ast.Compare) and len(c.ops) == 1
                        and isinstance(c.ops[0], (ast.Is, ast.IsNot))
                        and isinstance(c.left, ast.Name)
                        and isinstance(c.comparators[0], ast.Constant)
                        and c.comparators[0].value is None):
                    structural[c.left.id] = \
                        structural.get(c.left.id, 0) + 1
            bad = sorted(name for name, cnt in test_loads.items()
                         if name in traced
                         and structural.get(name) != cnt)
            if bad:
                flagged.add(id(node))
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression"}[type(node)]
                findings.append(Finding(
                    "recompile-hazard", mod.path, node.lineno,
                    node.col_offset,
                    f"Python `{kind}` on traced argument(s) "
                    f"{', '.join(bad)} inside jitted `{fn.name}`; "
                    f"mark them static, or branch with "
                    f"jnp.where/lax.cond"))
    return findings


# ---------------------------------------------------------------------------
# rule 3: missing-donation
# ---------------------------------------------------------------------------

def rule_missing_donation(mod: ModuleInfo,
                          ctx: CheckContext) -> List[Finding]:
    collector = _collect_jit(mod)
    jitted: Dict[str, JitSite] = {}
    for site in collector.sites:
        if site.bound_name:
            jitted.setdefault(site.bound_name, site)

    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id in jitted):
            continue
        site = jitted[call.func.id]
        params = _param_names(site.fn) if site.fn is not None else []
        targets: Set[str] = set()
        for t in node.targets:
            if isinstance(t, ast.Name):
                targets.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                targets |= {e.id for e in t.elts
                            if isinstance(e, ast.Name)}
        if not targets:
            continue
        rebound: List[Tuple[int, str]] = []
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Name) and arg.id in targets:
                pname = params[i] if i < len(params) else ""
                if i not in site.donate_nums \
                        and pname not in site.donate_names:
                    rebound.append((i, arg.id))
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name) and kw.arg \
                    and kw.value.id in targets \
                    and kw.arg not in site.donate_names \
                    and (kw.arg not in params
                         or params.index(kw.arg)
                         not in site.donate_nums):
                rebound.append((-1, kw.value.id))
        for _, name in rebound:
            findings.append(Finding(
                "missing-donation", mod.path, node.lineno,
                node.col_offset,
                f"`{name}` is re-bound to an output of jitted "
                f"`{call.func.id}` but not donated; the old buffer "
                f"stays live across the step (2x peak HBM for large "
                f"arrays) — add it to donate_argnums"))
    return findings


# ---------------------------------------------------------------------------
# rule 4: sharding-mismatch
# ---------------------------------------------------------------------------

def _axis_literals(node: ast.AST) -> List[str]:
    """Axis-name string literals in one PartitionSpec argument: a bare
    string, or a tuple/list of strings (multi-axis sharding)."""
    out: List[str] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
    return out


#: ``lax`` collectives whose axis-name argument must name a declared
#: mesh axis — a typo'd axis here fails exactly like a bad
#: PartitionSpec, at trace time on a real mesh. Maps dotted name →
#: positional index of the axis argument.
_COLLECTIVE_AXIS_ARG = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.pvary": 1,
}


def rule_sharding_mismatch(mod: ModuleInfo,
                           ctx: CheckContext) -> List[Finding]:
    from .sharding import _is_pspec_call, _is_shard_map_call

    axes = ctx.declared_axes
    if not axes:
        return []
    findings: List[Finding] = []
    flagged: Set[Tuple[int, str]] = set()

    def check(node: ast.AST, arg: ast.AST, what: str) -> None:
        for name in _axis_literals(arg):
            if name not in axes and (id(node), name) not in flagged:
                flagged.add((id(node), name))
                findings.append(Finding(
                    "sharding-mismatch", mod.path, node.lineno,
                    node.col_offset,
                    f"{what} axis {name!r} is not declared by "
                    f"parallel/mesh.py (declared: {sorted(axes)}); "
                    f"XLA will reject it at trace time on a real "
                    f"mesh"))

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = mod.resolve(node.func)
        if _is_pspec_call(mod, node):
            # covers every NamedSharding-annotated entry point too:
            # NamedSharding(mesh, P(...)), shard_map in/out specs, jit
            # out_shardings — the axis names always ride a
            # PartitionSpec call, however P was imported (the alias
            # table, OR a bare `P`/`PartitionSpec` name the aliases
            # cannot resolve: star imports, `jax.P`)
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                check(node, arg, "PartitionSpec")
            continue
        if _is_shard_map_call(mod, node):
            # keyword-form in_specs=/out_specs= of a shard_map
            # boundary: axis literals OUTSIDE a P(...) call (those are
            # caught above) — bare tuple/string forms a compat wrapper
            # might accept
            for kw in node.keywords:
                if kw.arg not in ("in_specs", "out_specs"):
                    continue
                covered: Set[int] = set()
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Call) \
                            and _is_pspec_call(mod, sub):
                        covered |= {id(d) for d in ast.walk(sub)}
                for sub in ast.walk(kw.value):
                    if id(sub) not in covered \
                            and isinstance(sub, (ast.Tuple, ast.List,
                                                 ast.Constant)):
                        check(node, sub, f"shard_map {kw.arg}")
            continue
        pos = _COLLECTIVE_AXIS_ARG.get(resolved or "")
        if pos is None:
            continue
        short = (resolved or "").rsplit(".", 1)[-1]
        if pos < len(node.args):
            check(node, node.args[pos], f"lax.{short}")
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                check(node, kw.value, f"lax.{short}")
    return findings


# ---------------------------------------------------------------------------
# rule 5: materialized-gather
# ---------------------------------------------------------------------------

#: directories whose functions sit on the train/serve hot paths — the
#: places where an advanced-indexing gather's HBM temp scales with the
#: problem, not with a constant
MATGATHER_DIR_PARTS = {"models", "ops", "server"}

#: gather-by-call forms that materialize exactly like advanced
#: indexing (``jnp.take(table, idx)`` lowers to the same XLA gather);
#: maps dotted name → positional index of the ``indices`` argument
GATHER_CALLS = {
    "jax.numpy.take": 1,
    "jax.numpy.take_along_axis": 1,
}


def _gather_finding(mod: ModuleInfo, node: ast.AST, desc: str,
                    fname: str, idx_name: str) -> Finding:
    return Finding(
        "materialized-gather", mod.path, node.lineno, node.col_offset,
        f"{desc} by the index array `{idx_name}` in hot function "
        f"`{fname}` materializes the gathered rows as an HBM temp of "
        f"unbounded size; bound it (row blocks), fuse it "
        f"(gram_mode='fused', ops/fused_gram.py), or pragma with a "
        f"size justification")


def _module_materialized_gather(mod: ModuleInfo,
                                ctx: CheckContext) -> List[Finding]:
    """``table[indices]`` advanced indexing by an index ARRAY inside
    train/serve hot-path functions.

    XLA materializes the gathered rows as an HBM temp whose size is the
    full index shape times the row width — ``fixed[indices]`` in the
    ALS half-step was ``[B, L, r]``, written once and read back at
    least once, which is exactly the 75%-HBM/0.6%-MFU bound BENCH_r05
    measured. Bound the gather (row blocks), fuse it
    (``gram_mode="fused"`` / ``ops/fused_gram.py``), or pragma it with
    a size justification (a ``[B, r]`` serving row-fetch is fine; an
    unbounded ``[B, L, r]`` training temp is not).

    Heuristic scope: inside a JITTED function (decorator, wrapped def,
    or ``jax.jit(lambda …)``) whose subscripted value and index are
    both bare names, with the index a TRACED parameter of that jit
    site — a traced scalar would be a data-dependent-shape error, so a
    traced parameter used as a subscript is an index array and the
    result is a device gather sized by the caller. ``jnp.take`` /
    ``jnp.take_along_axis`` on a traced-parameter index are the same
    gather spelled as a call and are flagged identically. ``x.at[i]``
    scatter/update builders and tuple-literal subscripts (host
    dispatch tables) are excluded; host-side helpers are out of scope
    (their gathers are numpy, paid once, not per dispatch).

    The project pass (:func:`rule_materialized_gather`) additionally
    flags a jitted function PASSING a traced parameter into a helper
    that (transitively) uses that parameter position as a gather
    index — the helper hides the subscript, the call site pays the
    HBM temp."""
    parts = set(mod.path.split("/")[:-1])
    if not (parts & MATGATHER_DIR_PARTS):
        return []
    findings: List[Finding] = []
    seen: Set[int] = set()
    proj = ctx.project
    collector = _collect_jit(mod)
    for site in collector.sites:
        fn = site.fn
        if fn is None:
            continue
        params = set(_param_names(fn)) - site.static_names
        if not params:
            continue
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        fname = getattr(fn, "name", "<lambda>")
        for stmt in body:
            for node in ast.walk(stmt):
                if id(node) in seen:
                    continue
                if isinstance(node, ast.Subscript) \
                        and isinstance(node.ctx, ast.Load):
                    idx = node.slice
                    if not (isinstance(idx, ast.Name)
                            and idx.id in params):
                        continue
                    val = node.value
                    if not isinstance(val, (ast.Name, ast.Attribute)):
                        continue  # (a, b)[i] host dispatch
                    if isinstance(val, ast.Attribute) \
                            and val.attr == "at":
                        continue  # x.at[ids] is a scatter builder
                    seen.add(id(node))
                    vname = mod.resolve(val) or "<expr>"
                    findings.append(_gather_finding(
                        mod, node,
                        f"advanced indexing `{vname}[{idx.id}]`",
                        fname, idx.id))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                resolved = mod.resolve(node.func)
                pos = GATHER_CALLS.get(resolved or "")
                if pos is not None:
                    idx_arg = node.args[pos] \
                        if len(node.args) > pos else None
                    for kw in node.keywords:
                        if kw.arg == "indices":
                            idx_arg = kw.value
                    if isinstance(idx_arg, ast.Name) \
                            and idx_arg.id in params:
                        seen.add(id(node))
                        short = (resolved or "").rsplit(".", 1)[-1]
                        findings.append(_gather_finding(
                            mod, node, f"`jnp.{short}(…)`", fname,
                            idx_arg.id))
                    continue
                # interprocedural: traced param flows into a helper's
                # gather-index position
                if proj is None or id(node) in seen:
                    continue
                qname, bound = proj.resolve_call(mod, None, node.func)
                callee = proj.functions.get(qname or "")
                if callee is None or not callee.index_sinks:
                    continue
                off = 1 if bound else 0
                flow = None
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Name) and a.id in params \
                            and (i + off) in callee.index_sinks:
                        flow = (a.id, i + off)
                        break
                if flow is None:
                    for kw in node.keywords:
                        if kw.arg and kw.arg in callee.params \
                                and isinstance(kw.value, ast.Name) \
                                and kw.value.id in params:
                            p = callee.params.index(kw.arg)
                            if p in callee.index_sinks:
                                flow = (kw.value.id, p)
                                break
                if flow is None:
                    continue
                seen.add(id(node))
                idx_name, p = flow
                hops = proj.sink_chain(callee, "index", p)
                findings.append(Finding(
                    "materialized-gather", mod.path, node.lineno,
                    node.col_offset,
                    f"traced index `{idx_name}` of jitted `{fname}` "
                    f"flows into a gather one call away: "
                    f"{chain_text(hops)} — the helper hides the "
                    f"subscript but the call site pays the HBM temp; "
                    f"bound it, fuse it (gram_mode='fused'), or "
                    f"pragma the helper's gather with a size "
                    f"justification",
                    related=chain_related(hops)))
    return findings


def rule_materialized_gather(mods: Sequence[ModuleInfo],
                             ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in mods:
        findings.extend(_module_materialized_gather(mod, ctx))
    return findings


# ---------------------------------------------------------------------------
# rule 6: config-drift
# ---------------------------------------------------------------------------

#: the one module allowed to flip global jax config (platform policy)
CONFIG_HOME_SUFFIX = "utils/platform.py"


def rule_config_drift(mod: ModuleInfo, ctx: CheckContext) -> List[Finding]:
    if mod.path.endswith(CONFIG_HOME_SUFFIX):
        return []
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and mod.resolve(node.func) == "jax.config.update":
            key = ""
            if node.args and isinstance(node.args[0], ast.Constant):
                key = f" ({node.args[0].value!r})"
            findings.append(Finding(
                "config-drift", mod.path, node.lineno, node.col_offset,
                f"jax.config.update{key} outside utils/platform.py; "
                f"global config flips scattered across modules make "
                f"behavior depend on import order — route it through "
                f"the platform module"))
    return findings


# ---------------------------------------------------------------------------
# rule: unbounded-retry
# ---------------------------------------------------------------------------

#: directories whose loops talk to failable dependencies (ISSUE 11):
#: a swallow-and-continue loop here is a wedged-daemon generator
RETRY_SCOPE_PARTS = {"server", "streaming", "storage"}

#: attribute calls that pace (block/sleep) or bound a loop iteration —
#: their presence anywhere in the loop body means the retry is not a
#: hot spin; ``*_nowait`` variants deliberately do NOT count
_PACING_ATTRS = {"sleep", "wait", "get", "join", "acquire", "select",
                 "accept", "recv", "poll"}
_PACING_NAMES = {"time.sleep", "select.select"}
#: the shared bounded-backoff helpers (utils/retrying.py)
_PACING_SUFFIXES = ("retry_call", "backoff_delays")


def _walk_same_scope(node):
    """Walk a loop body without descending into nested function
    definitions (their loops are judged where they are defined)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _walk_same_scope(child)


def _loop_unbounded(mod: ModuleInfo, node: ast.AST) -> bool:
    if isinstance(node, ast.While):
        t = node.test
        return isinstance(t, ast.Constant) and bool(t.value)
    if isinstance(node, ast.For):
        it = node.iter
        return isinstance(it, ast.Call) \
            and mod.resolve(it.func) == "itertools.count"
    return False


def _is_pacing_call(mod: ModuleInfo, call: ast.Call) -> bool:
    name = mod.resolve(call.func) or ""
    if name in _PACING_NAMES or name.endswith(_PACING_SUFFIXES):
        return True
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        return attr in _PACING_ATTRS and not attr.endswith("_nowait")
    return False


def rule_unbounded_retry(mod: ModuleInfo,
                         ctx: CheckContext) -> List[Finding]:
    """``while True`` (or ``itertools.count``) loops in server/,
    streaming/, or storage/ code that swallow exceptions and loop again
    with NO max-attempts bound and NO pacing call (sleep / blocking
    wait / the shared retry helpers): a failing dependency turns such a
    loop into a hot spin or a silently wedged daemon. Bound it with
    ``utils.retrying.retry_call`` (bounded exponential backoff) or add
    explicit pacing."""
    parts = set(mod.path.split("/")[:-1])
    if not parts & RETRY_SCOPE_PARTS:
        return []
    findings: List[Finding] = []
    for loop in ast.walk(mod.tree):
        if not isinstance(loop, (ast.While, ast.For)) \
                or not _loop_unbounded(mod, loop):
            continue
        body_nodes = [n for stmt in loop.body
                      for n in [stmt, *_walk_same_scope(stmt)]]
        swallows = None
        for n in body_nodes:
            if not isinstance(n, ast.Try):
                continue
            for handler in n.handlers:
                escapes = any(isinstance(h, (ast.Raise, ast.Return,
                                             ast.Break))
                              for stmt in handler.body
                              for h in [stmt, *_walk_same_scope(stmt)])
                if not escapes:
                    swallows = handler
                    break
            if swallows is not None:
                break
        if swallows is None:
            continue
        paced = any(isinstance(n, ast.Call) and _is_pacing_call(mod, n)
                    for n in body_nodes)
        if paced:
            continue
        findings.append(Finding(
            "unbounded-retry", mod.path, swallows.lineno,
            swallows.col_offset,
            "unbounded retry: this loop swallows the exception and "
            "re-runs with no max-attempts bound and no backoff/pacing "
            "— a failing dependency becomes a hot spin or a wedged "
            "daemon; bound it with utils.retrying.retry_call (bounded "
            "exponential backoff) or add explicit pacing"))
    return findings


# ---------------------------------------------------------------------------
# registry (JAX rules here; concurrency rule family in .concurrency)
# ---------------------------------------------------------------------------

from .concurrency import (  # noqa: E402 — registry assembly
    rule_blocking_under_lock,
    rule_callback_under_lock,
    rule_lock_order_inversion,
    rule_unguarded_shared_state,
)
from .kernels import (  # noqa: E402 — registry assembly
    rule_dma_unwaited,
    rule_low_precision_accumulator,
    rule_missing_interpret_fallback,
    rule_vmem_overbudget,
)
from .lifecycle import (  # noqa: E402 — registry assembly
    rule_hot_spin_loop,
    rule_leaked_thread,
    rule_missing_timeout,
    rule_non_atomic_persist,
    rule_unbounded_queue,
)
from .metrics_catalog import (  # noqa: E402 — registry assembly
    rule_metric_catalog_drift,
)
from .numerics import (  # noqa: E402 — registry assembly
    rule_dequant_outside_funnel,
    rule_low_precision_reduction,
    rule_quantize_without_parity_gate,
    rule_requant_torn_pair,
    rule_unguarded_domain,
)
from .sharding import (  # noqa: E402 — registry assembly
    rule_implicit_reshard,
    rule_missing_donation_sharded,
    rule_shard_map_spec_mismatch,
    rule_unsharded_capture,
)

RULES: Dict[str, Rule] = {r.name: r for r in (
    Rule("host-sync-in-hot-path",
         "device→host sync (np.asarray/.item()/.tolist()/device_get/"
         "block_until_ready) inside server/ or ops/ functions, "
         "directly or through any helper call chain",
         rule_host_sync, project=True),
    Rule("recompile-hazard",
         "jit sites that silently re-trace: unhashable statics, "
         "closures over jnp arrays, Python control flow on traced args",
         rule_recompile_hazard),
    Rule("missing-donation",
         "x = jitted(x, …) update steps without donate_argnums on the "
         "re-bound buffer",
         rule_missing_donation),
    Rule("sharding-mismatch",
         "PartitionSpec / NamedSharding / lax-collective / shard_map "
         "spec axis names (bare P() literals included) not declared "
         "by parallel/mesh.py",
         rule_sharding_mismatch),
    Rule("implicit-reshard",
         "a value with a known sharding passed — directly or through "
         "any helper chain — where a shard_map boundary pins a "
         "different spec: a silent all-gather/all-to-all per dispatch",
         rule_implicit_reshard, project=True),
    Rule("shard-map-spec-mismatch",
         "shard_map in_specs/out_specs arity disagreeing with the "
         "wrapped function, or axis names mixing different declared "
         "meshes (parallel/mesh.py groups)",
         rule_shard_map_spec_mismatch),
    Rule("unsharded-capture",
         "a shard_map'd/jitted closure capturing an array the "
         "enclosing scope shards — the capture enters replicated "
         "(implicit all-gather of the whole table)",
         rule_unsharded_capture),
    Rule("missing-donation-sharded",
         "x = step(x, …) re-binding a SHARDED buffer through a "
         "cross-module jitted step that does not donate the slot "
         "(2x peak HBM at exactly the scale that forced sharding)",
         rule_missing_donation_sharded, project=True),
    Rule("materialized-gather",
         "table[indices] / jnp.take gathers by traced params in "
         "models/, ops/, or server/ functions — directly or through "
         "a helper — unbounded HBM temps on train/serve hot paths "
         "(fuse or bound, or pragma with a size case)",
         rule_materialized_gather, project=True),
    Rule("config-drift",
         "jax.config.update outside utils/platform.py",
         rule_config_drift),
    Rule("unbounded-retry",
         "swallow-and-continue retry loops in server/, streaming/, or "
         "storage/ code with no max-attempts bound and no "
         "backoff/pacing (route through utils/retrying.py)",
         rule_unbounded_retry),
    Rule("vmem-overbudget",
         "pallas_call whose statically-evaluated VMEM working set "
         "(BlockSpec tiles double-buffered + scratch) exceeds the "
         "~16 MiB/core budget for the autotune rank/chunk grid",
         rule_vmem_overbudget),
    Rule("dma-unwaited",
         "make_async_copy .start() without a matching .wait() (by "
         "variable or semaphore slot), or a slot restarted before "
         "its wait",
         rule_dma_unwaited),
    Rule("low-precision-accumulator",
         "+=/dot accumulation into bf16/f16 Pallas scratch refs — "
         "kernel accumulators must be f32",
         rule_low_precision_accumulator),
    Rule("missing-interpret-fallback",
         "pallas_call hard-wired to compiled mode (no interpret= "
         "escape) instead of riding a support-gated dispatcher like "
         "fused_gram_dispatch",
         rule_missing_interpret_fallback),
    Rule("unguarded-shared-state",
         "reads/writes of a class's lock-guarded attributes outside "
         "the lock (honors # ptpu: guarded-by[lock])",
         rule_unguarded_shared_state),
    Rule("lock-order-inversion",
         "cycles in the cross-file static lock-acquisition graph "
         "built from nested with-lock scopes",
         rule_lock_order_inversion, project=True),
    Rule("blocking-under-lock",
         "device dispatch, HTTP/storage I/O, sleep, join/wait/result "
         "inside a held-lock region in server/, cache/, or rollout/",
         rule_blocking_under_lock),
    Rule("callback-under-lock",
         "bus/plugin callbacks invoked while holding the publisher's "
         "lock (re-entrancy deadlock)",
         rule_callback_under_lock),
    Rule("low-precision-reduction",
         "sum/mean/dot/einsum/@ over bf16/f16 operands accumulating "
         "at operand precision (no f32 preferred_element_type or "
         "upcast) in models/ops/streaming — directly or through any "
         "helper chain",
         rule_low_precision_reduction, project=True),
    Rule("dequant-outside-funnel",
         "f32 materialization of quantized table data outside the "
         "blessed dequantize_table/table_host_f32/_host_row_f32 "
         "funnels — the silent HBM-win defeat",
         rule_dequant_outside_funnel),
    Rule("quantize-without-parity-gate",
         "QuantizedFactors/_quantize_rows construction bypassing "
         "quantize_serving_model's NDCG@10 parity probe and "
         "auto-fallback path",
         rule_quantize_without_parity_gate),
    Rule("unguarded-domain",
         "log/sqrt/rsqrt/division over traced or accumulated values "
         "with no epsilon/clip guard (drift.py's max(x, 1e-9) is the "
         "blessed idiom)",
         rule_unguarded_domain),
    Rule("requant-torn-pair",
         "QuantizedFactors.data written (assignment or "
         "dataclasses.replace) without the paired scale update across "
         "the fold-in/hot-swap seam",
         rule_requant_torn_pair),
    Rule("metric-catalog-drift",
         "pio_* families registered in code but missing from the "
         "docs/observability.md catalog, or documented but never "
         "emitted (both directions)",
         rule_metric_catalog_drift, project=True),
    Rule("leaked-thread",
         "threading.Thread with a looping target started in server/, "
         "fleet/, router/, streaming/, or rollout/ code whose handle "
         "is never joined — in the spawning function, the owning "
         "class, or through a call-graph join helper",
         rule_leaked_thread, project=True),
    Rule("missing-timeout",
         "urlopen/HTTPConnection/create_connection with no explicit "
         "timeout reachable from fleet/, router/, data/, or storage/ "
         "code — directly or through any helper chain (a wedged peer "
         "freezes the scrape/control tick forever)",
         rule_missing_timeout, project=True),
    Rule("non-atomic-persist",
         "durable state (baselines, gates, registries, artifacts) "
         "written with a plain open(path, 'w') outside the temp-file+"
         "fsync+os.replace funnel — a crash mid-write tears the file",
         rule_non_atomic_persist),
    Rule("unbounded-queue",
         "queue.Queue()/collections.deque() constructed without a "
         "bound on serving/streaming paths — backlog becomes an OOM "
         "instead of backpressure under overload",
         rule_unbounded_queue),
    Rule("hot-spin-loop",
         "while-True daemon loops in server/, streaming/, fleet/, "
         "router/, rollout/, or slo/ code with neither a stop-event "
         "check nor a pacing/blocking call — pins a core and ignores "
         "shutdown (complements unbounded-retry)",
         rule_hot_spin_loop),
)}
